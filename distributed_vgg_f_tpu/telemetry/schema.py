"""Record-shape validators for the telemetry surfaces — the CI tripwire
that makes schema drift fail a test instead of corrupting run archives.

Three record families, each with a `validate_*` returning a list of error
strings (empty = valid; callers assert `not errors` so a failure names every
problem at once):

- metrics JSONL (utils/logging.py MetricLogger): one JSON object per line,
  an `event` string, values JSON-legal — in particular NO bare
  ``NaN``/``Infinity`` tokens. Python's `json.loads` ACCEPTS those
  non-standard tokens by default, so the validator parses with a strict
  `parse_constant` to catch exactly the records that would break a
  spec-compliant downstream parser (jq, BigQuery, serde).
- Chrome trace-event JSON (telemetry/spans.py export): object format with a
  `traceEvents` list of `ph: "X"` complete events (plus `M` metadata), the
  shape Perfetto and chrome://tracing load.
- bench artifacts (benchmarks/host_pipeline_bench.py --json-out): a JSON
  object with a numeric `metric`/`value` pair and finite numbers
  throughout.
"""

from __future__ import annotations

import json
import math
import os
import re
from typing import Any, List

#: Record-schema version stamped into trainer JSONL records (MetricLogger),
#: bench --json-out artifacts, flight-recorder black boxes, and the perf
#: trajectory file. MAJOR bumps mean a consumer written against the old
#: shape would MISREAD the new one (field renamed/retyped/resemanticized);
#: MINOR bumps are additive. Validators accept any minor of a known major,
#: accept ABSENT (every pre-versioned committed artifact), and reject
#: unknown majors — the drift a silent reader would otherwise misparse.
SCHEMA_VERSION = "1.0"
KNOWN_SCHEMA_MAJORS = (1,)


def validate_schema_version(value: Any, path: str,
                            errors: List[str]) -> None:
    """Shared `schema_version` field check: None (pre-versioned record) is
    legal; a present value must be a "MAJOR.MINOR" string whose major is
    known."""
    if value is None:
        return
    if not isinstance(value, str):
        errors.append(f"{path}: schema_version not a string "
                      f"({type(value).__name__})")
        return
    major_s = value.split(".", 1)[0]
    try:
        major = int(major_s)
    except ValueError:
        errors.append(f"{path}: schema_version {value!r} not MAJOR.MINOR")
        return
    if major not in KNOWN_SCHEMA_MAJORS:
        errors.append(
            f"{path}: unknown schema_version major {major} (known: "
            f"{KNOWN_SCHEMA_MAJORS}) — this reader predates the record; "
            f"refusing to guess at its shape")


def _strict_loads(text: str):
    """json.loads rejecting the non-standard NaN/Infinity/-Infinity tokens
    (JSON-illegal, but emitted by a naive json.dumps of a non-finite float
    — the exact bug the MetricLogger satellite fixed)."""

    def _bad(token: str):
        raise ValueError(f"JSON-illegal constant {token!r}")

    return json.loads(text, parse_constant=_bad)


def _check_finite(value: Any, path: str, errors: List[str]) -> None:
    """Recursively reject non-finite floats — they survive a permissive
    load but re-serialize illegally."""
    if isinstance(value, float) and not math.isfinite(value):
        errors.append(f"{path}: non-finite float {value!r}")
    elif isinstance(value, dict):
        for k, v in value.items():
            if not isinstance(k, str):
                errors.append(f"{path}.{k}: non-string key")
            _check_finite(v, f"{path}.{k}", errors)
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            _check_finite(v, f"{path}[{i}]", errors)
    elif value is not None and not isinstance(value, (str, int, float, bool)):
        errors.append(f"{path}: non-JSON value of type "
                      f"{type(value).__name__}")


# ------------------------------------------------------------------ autotune
#: Knobs the ingest autotuner may steer (data/autotune.py) — duplicated as
#: a literal so this module stays a leaf (the import-isolation contract:
#: schema imports neither the data layer nor numpy).
#: "batch_window_ms" is the serving admission controller's knob (r17,
#: serving/controller.py — the same controller class, so its actuations
#: ride the same flight-recorder ring and must validate here).
_AUTOTUNE_KNOBS = ("native_threads", "host_prefetch", "prefetch_to_device",
                   "restart_fanout", "wire_u8", "batch_window_ms")
_AUTOTUNE_BLOCKED = ("hysteresis", "cooldown", "rail")


def validate_autotune_actuation(act: Any, where: str,
                                errors: List[str]) -> None:
    """One actuation record — the unit all three receipt trails (JSONL
    block, /autotunez history, flight black box) share."""
    if not isinstance(act, dict):
        errors.append(f"{where}: not an object")
        return
    if act.get("knob") not in _AUTOTUNE_KNOBS:
        errors.append(f"{where}: 'knob' {act.get('knob')!r} not one of "
                      f"{_AUTOTUNE_KNOBS}")
    if act.get("direction") not in ("up", "down"):
        errors.append(f"{where}: 'direction' {act.get('direction')!r} not "
                      "'up'|'down'")
    for key in ("from", "to", "window"):
        if not isinstance(act.get(key), int):
            errors.append(f"{where}: missing integer '{key}'")


def validate_autotune_block(block: Any, where: str,
                            errors: List[str]) -> None:
    """The per-window `autotune` block in trainer JSONL train records
    (IngestAutotuner.observe shape): every actuation the controller takes
    must be machine-auditable from the run log alone."""
    if not isinstance(block, dict):
        errors.append(f"{where}: 'autotune' not an object")
        return
    if not isinstance(block.get("window"), int):
        errors.append(f"{where}: missing integer 'window'")
    if not isinstance(block.get("settled"), bool):
        errors.append(f"{where}: missing boolean 'settled'")
    knobs = block.get("knobs")
    if knobs is not None:
        if not isinstance(knobs, dict):
            errors.append(f"{where}: 'knobs' not an object")
        else:
            for name, v in knobs.items():
                if name not in _AUTOTUNE_KNOBS:
                    errors.append(f"{where}.knobs: unknown knob {name!r}")
                if not isinstance(v, int):
                    errors.append(f"{where}.knobs.{name}: not an integer")
    blocked = block.get("blocked")
    if blocked is not None and blocked not in _AUTOTUNE_BLOCKED:
        errors.append(f"{where}: 'blocked' {blocked!r} not one of "
                      f"{_AUTOTUNE_BLOCKED}")
    acts = block.get("actuations")
    if acts is not None:
        if not isinstance(acts, list):
            errors.append(f"{where}: 'actuations' not a list")
        else:
            for i, act in enumerate(acts):
                validate_autotune_actuation(act, f"{where}.actuations[{i}]",
                                            errors)


def validate_autotune_receipt(receipt: Any, where: str,
                              errors: List[str]) -> None:
    """The bench-artifact / /autotunez `autotune` receipt
    (IngestAutotuner.describe shape). `settled` is the field the
    regression sentinel gates on: an artifact whose windows overlap
    actuations must refuse gating (a mid-convergence window reads as a
    false regression)."""
    if not isinstance(receipt, dict):
        errors.append(f"{where}: 'autotune' not an object")
        return
    if not isinstance(receipt.get("enabled"), bool):
        errors.append(f"{where}: missing boolean 'enabled'")
    if receipt.get("enabled"):
        if not isinstance(receipt.get("settled"), bool):
            errors.append(f"{where}: missing boolean 'settled'")
        if not isinstance(receipt.get("actuations_total"), int):
            errors.append(f"{where}: missing integer 'actuations_total'")
        hist = receipt.get("history")
        if hist is not None:
            if not isinstance(hist, list):
                errors.append(f"{where}: 'history' not a list")
            else:
                for i, act in enumerate(hist):
                    validate_autotune_actuation(
                        act, f"{where}.history[{i}]", errors)


# ------------------------------------------------------------ iterator state
#: Legal `wire` receipts in iterator-state blobs/blocks — the bench's
#: _WIRE_VALUES, duplicated here by the leaf-module contract (this module
#: imports neither the data layer nor numpy).
_ITER_STATE_WIRES = ("host_f32", "host_bf16", "u8")


def validate_iterator_state_blob(blob: Any, where: str,
                                 errors: List[str]) -> None:
    """The checkpoint-extra `iterator_state` receipt (r18,
    data/iterator_state.py capture_state shape): the serialized stream
    position a restore seeks to. Load-bearing invariants are typed here —
    cursor/epoch agreement under next-item-to-emit semantics, the
    in-flight set exactly [cursor, source_cursor) — so a drifting writer
    fails validation instead of seeking a resumed run to a wrong
    position."""
    if not isinstance(blob, dict):
        errors.append(f"{where}: 'iterator_state' not an object")
        return
    if blob.get("kind") != "ingest_iterator_state":
        errors.append(f"{where}: 'kind' {blob.get('kind')!r} != "
                      "'ingest_iterator_state'")
    for key in ("version", "cursor", "epoch", "batches_per_epoch", "seed",
                "source_cursor", "rebuilds"):
        v = blob.get(key)
        if not isinstance(v, int) or isinstance(v, bool):
            errors.append(f"{where}: missing integer '{key}'")
    cursor, bpe = blob.get("cursor"), blob.get("batches_per_epoch")
    if isinstance(cursor, int) and isinstance(bpe, int) and bpe >= 1 \
            and isinstance(blob.get("epoch"), int):
        # next-item-to-emit semantics: the batch AT cursor k*N opens
        # epoch k (the off-by-one the shared epoch_of helper pins)
        if blob["epoch"] != cursor // bpe:
            errors.append(f"{where}: epoch {blob['epoch']} != "
                          f"cursor//batches_per_epoch ({cursor // bpe}) — "
                          "cursor is next-item-to-emit, not last-emitted")
    shuffle = blob.get("shuffle")
    if not isinstance(shuffle, dict) \
            or shuffle.get("algo") != "splitmix64" \
            or not isinstance(shuffle.get("seed"), int) \
            or not isinstance(shuffle.get("epoch"), int):
        errors.append(f"{where}: 'shuffle' not "
                      "{algo: 'splitmix64', seed: int, epoch: int}")
    inflight = blob.get("in_flight")
    if not isinstance(inflight, list) \
            or not all(isinstance(c, int) for c in inflight):
        errors.append(f"{where}: 'in_flight' not a list of integers")
    elif isinstance(cursor, int) \
            and isinstance(blob.get("source_cursor"), int):
        if inflight != list(range(cursor, blob["source_cursor"])):
            errors.append(
                f"{where}: in_flight != [cursor, source_cursor) — the "
                "read-ahead transplant set must be exactly the undelivered "
                "source draws")
    wire = blob.get("wire")
    if wire is not None and wire not in _ITER_STATE_WIRES:
        errors.append(f"{where}: 'wire' {wire!r} not one of "
                      f"{_ITER_STATE_WIRES}")


def validate_iterator_state_block(block: Any, where: str,
                                  errors: List[str]) -> None:
    """The per-window `iterator_state` JSONL block (r18,
    ResumableIngest.window_receipt shape) in trainer train records."""
    if not isinstance(block, dict):
        errors.append(f"{where}: 'iterator_state' not an object")
        return
    for key in ("cursor", "source_cursor", "in_flight", "epoch",
                "rebuilds"):
        v = block.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"{where}: '{key}' not a non-negative integer")
    wire = block.get("wire")
    if wire is not None and wire not in _ITER_STATE_WIRES:
        errors.append(f"{where}: 'wire' {wire!r} not one of "
                      f"{_ITER_STATE_WIRES}")


# ------------------------------------------------------------------- elastic
#: Legal topology basis labels (r19): `static` (every pre-r19 row) or the
#: elastic resize's `elastic_<N>to<M>`. Mirrors
#: parallel/elastic.ResizePlan.topology_label — duplicated as a literal,
#: leaf-module contract as everywhere in this file.
_TOPOLOGY_RE = re.compile(r"static|elastic_\d+to\d+")

#: Legal batch-policy labels (mirrors config.ElasticConfig.batch_policy).
_BATCH_POLICIES = ("keep_global", "scale_lr")


def validate_elastic_block(block: Any, where: str,
                           errors: List[str]) -> None:
    """The per-window `elastic` JSONL block (r19, trainer train records,
    emitted only when `mesh.elastic.enabled`): the window's topology basis
    plus the cumulative resize receipts — resizes performed, total
    downtime, opt-state shards evacuated off dead ranks, data shards
    reassigned to survivors, and the active LR scale."""
    if not isinstance(block, dict):
        errors.append(f"{where}: 'elastic' not an object")
        return
    topo = block.get("topology")
    if not isinstance(topo, str) or not _TOPOLOGY_RE.fullmatch(topo):
        errors.append(f"{where}: 'topology' {topo!r} not "
                      "static|elastic_<N>to<M>")
    policy = block.get("batch_policy")
    if policy not in _BATCH_POLICIES:
        errors.append(f"{where}: 'batch_policy' {policy!r} not one of "
                      f"{_BATCH_POLICIES}")
    for key in ("resizes", "downtime_ns", "evacuated_shards",
                "reassigned_data_shards"):
        v = block.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"{where}: '{key}' not a non-negative integer")
    v = block.get("lr_scale")
    if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
        errors.append(f"{where}: 'lr_scale' not a positive number")


# ------------------------------------------------------------------- augment
def validate_augment_block(block: Any, where: str,
                           errors: List[str]) -> None:
    """The per-window `augment` block (r13, AugmentConfig.describe shape):
    the receipt that a run's augmentation diversity was DEVICE-side — in
    trainer JSONL train records and bench-artifact rows. `enabled` and
    `host_flips_disabled` are the load-bearing booleans (the flip-ownership
    contract); the knob echoes are typed so a drifting config serializer
    fails validation instead of corrupting run archives."""
    if not isinstance(block, dict):
        errors.append(f"{where}: 'augment' not an object")
        return
    for key in ("enabled", "host_flips_disabled"):
        if not isinstance(block.get(key), bool):
            errors.append(f"{where}: missing boolean '{key}'")
    if "hflip" in block and not isinstance(block["hflip"], bool):
        errors.append(f"{where}: 'hflip' not a boolean")
    for key in ("crop_jitter", "rand_ops"):
        v = block.get(key)
        if v is not None and (not isinstance(v, int)
                              or isinstance(v, bool) or v < 0):
            errors.append(f"{where}: '{key}' not a non-negative integer")
    for key in ("mixup_alpha", "cutmix_alpha"):
        v = block.get(key)
        if v is not None and (not isinstance(v, (int, float))
                              or isinstance(v, bool) or v < 0):
            errors.append(f"{where}: '{key}' not a non-negative number")
    v = block.get("rand_magnitude")
    if v is not None and (not isinstance(v, (int, float))
                          or isinstance(v, bool) or not 0 <= v <= 1):
        errors.append(f"{where}: 'rand_magnitude' not in [0, 1]")


#: Zoo models a bench row's `model` field may carry (mirrors
#: models/ingest.INGEST_DESCRIPTORS — duplicated as a literal so this
#: module stays a leaf; the drift is guarded by test).
_ZOO_MODELS = ("vggf", "vgg16", "resnet50", "vit_s16", "vggf_student")


# ---------------------------------------------------------------------- comm
#: Legal gradient-exchange sharding bases (r14, +zero3 r21; mirrors
#: config.MeshConfig.sharding_label — duplicated as a literal, leaf-module
#: contract as above).
_COMM_SHARDINGS = ("dp", "zero1", "zero2", "zero3")


def validate_comm_block(block: Any, where: str,
                        errors: List[str]) -> None:
    """The per-window `comm` block (r14, train/step.py comm_meta shape):
    the receipt for the gradient-exchange geometry a run actually traced —
    sharding basis (dp | zero1 | zero2 | zero3), whether the bucketed
    exchange was on, the bucket count, the logical collective payload
    bytes per step, and (r21) the per-step param all-gather count. In
    trainer JSONL train records and comm-bench artifact rows."""
    if not isinstance(block, dict):
        errors.append(f"{where}: 'comm' not an object")
        return
    sharding = block.get("sharding")
    if sharding not in _COMM_SHARDINGS:
        errors.append(f"{where}: 'sharding' {sharding!r} not one of "
                      f"{_COMM_SHARDINGS}")
    if not isinstance(block.get("bucketed"), bool):
        errors.append(f"{where}: missing boolean 'bucketed'")
    v = block.get("buckets")
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        errors.append(f"{where}: 'buckets' not a positive integer")
    v = block.get("bucket_mb")
    if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
        errors.append(f"{where}: 'bucket_mb' not a non-negative number")
    for key in ("wire_bytes", "scatter_bytes", "gather_bytes",
                "allreduce_bytes"):
        v = block.get(key)
        if key == "wire_bytes" and v is None:
            errors.append(f"{where}: missing 'wire_bytes'")
        if v is not None and (not isinstance(v, int) or isinstance(v, bool)
                              or v < 0):
            errors.append(f"{where}: '{key}' not a non-negative integer")
    v = block.get("grad_accum_steps")
    if v is not None and (not isinstance(v, int) or isinstance(v, bool)
                          or v < 1):
        errors.append(f"{where}: 'grad_accum_steps' not a positive integer")
    # r21 (ZeRO-3): per-step param all-gather count — 0 under dp, 1 under
    # zero1/zero2 (the trailing re-sync), num_buckets under bucketed zero3
    v = block.get("gathers")
    if v is not None and (not isinstance(v, int) or isinstance(v, bool)
                          or v < 0):
        errors.append(f"{where}: 'gathers' not a non-negative integer")


# ------------------------------------------------------------ critical path
#: Critical-path buckets (r22, trainer per-window split). Mirrors the
#: trainer's span-category mapping (infeed / checkpoint / coord→exchange /
#: device-residual) — duplicated as a literal, leaf-module contract.
_CRITICAL_PATH_PARTS = ("infeed_s", "device_s", "checkpoint_s",
                        "exchange_s")


def validate_critical_path_block(block: Any, where: str,
                                 errors: List[str]) -> None:
    """The per-window `critical_path` JSONL block (r22, trainer train
    records): the window's wall clock attributed {infeed, device,
    checkpoint, exchange} with the dominant bucket named. The load-bearing
    invariant is typed — the four parts must SUM to the window wall clock
    (the trainer computes device as the residual, so a drifting writer
    that double-counts fails here instead of producing splits that read
    as >100% of the window)."""
    if not isinstance(block, dict):
        errors.append(f"{where}: 'critical_path' not an object")
        return
    wall = block.get("window_s")
    if not isinstance(wall, (int, float)) or isinstance(wall, bool) \
            or not math.isfinite(wall) or wall < 0:
        errors.append(f"{where}: 'window_s' not a non-negative finite "
                      "number")
        return
    total = 0.0
    ok = True
    for key in _CRITICAL_PATH_PARTS:
        v = block.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not math.isfinite(v) or v < 0:
            errors.append(f"{where}: '{key}' not a non-negative finite "
                          "number")
            ok = False
        else:
            total += v
    if ok and abs(total - wall) > max(1e-3, 1e-3 * wall):
        errors.append(
            f"{where}: parts sum to {total:.6f}s but window_s is "
            f"{wall:.6f}s — the split must account for the whole window")
    dom = block.get("dominant")
    if not isinstance(dom, str) or f"{dom}_s" not in _CRITICAL_PATH_PARTS:
        errors.append(f"{where}: 'dominant' {dom!r} not one of "
                      f"{tuple(p[:-2] for p in _CRITICAL_PATH_PARTS)}")


# ------------------------------------------------------------- metrics JSONL
def validate_metrics_record(record: Any) -> List[str]:
    """One MetricLogger record (already parsed)."""
    errors: List[str] = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected object"]
    event = record.get("event")
    if not isinstance(event, str) or not event:
        errors.append("missing/empty 'event' string")
    validate_schema_version(record.get("schema_version"), "record", errors)
    if "autotune" in record:
        validate_autotune_block(record["autotune"], "record", errors)
    if event == "train" and "augment" in record:
        validate_augment_block(record["augment"], "record", errors)
    if event == "train" and "comm" in record:
        validate_comm_block(record["comm"], "record", errors)
    if event == "train" and "iterator_state" in record:
        validate_iterator_state_block(record["iterator_state"], "record",
                                      errors)
    if event == "train" and "elastic" in record:
        validate_elastic_block(record["elastic"], "record", errors)
    if event == "train" and "critical_path" in record:
        validate_critical_path_block(record["critical_path"], "record",
                                     errors)
    _check_finite(record, "record", errors)
    return errors


def validate_metrics_jsonl(path: str, max_errors: int = 20) -> List[str]:
    """Whole-file check: every line parses strictly and validates."""
    errors: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                record = _strict_loads(line)
            except ValueError as e:
                errors.append(f"line {lineno}: {e}")
            else:
                errors.extend(f"line {lineno}: {err}"
                              for err in validate_metrics_record(record))
            if len(errors) >= max_errors:
                errors.append("... (truncated)")
                break
    return errors


# -------------------------------------------------------------- Chrome trace
def validate_chrome_trace(trace: Any) -> List[str]:
    """Trace-event JSON object format (the spans.py export shape)."""
    errors: List[str] = []
    if not isinstance(trace, dict):
        return [f"trace is {type(trace).__name__}, expected object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["missing 'traceEvents' list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ev.get("name"), str):
            errors.append(f"{where}: missing 'name' string")
        # "s"/"t"/"f" are the flow-event phases the stitched multi-process
        # trace carries (r22, telemetry/stitch.py) — the arrows linking a
        # client span to the remote span that served it
        if ph not in ("X", "M", "B", "E", "i", "C", "s", "t", "f"):
            errors.append(f"{where}: unsupported ph {ph!r}")
        if not isinstance(ev.get("pid"), int):
            errors.append(f"{where}: missing integer 'pid'")
        if ph in ("s", "t", "f"):
            if not isinstance(ev.get("id"), (int, str)):
                errors.append(f"{where}: flow event missing 'id'")
            v = ev.get("ts")
            if not isinstance(v, (int, float)) or not math.isfinite(v):
                errors.append(f"{where}: 'ts' not a finite number")
            if not isinstance(ev.get("tid"), int):
                errors.append(f"{where}: missing integer 'tid'")
        if ph == "X":
            for key in ("ts", "dur"):
                v = ev.get(key)
                if not isinstance(v, (int, float)) or not math.isfinite(v):
                    errors.append(f"{where}: '{key}' not a finite number")
            if isinstance(ev.get("dur"), (int, float)) and ev["dur"] < 0:
                errors.append(f"{where}: negative duration")
            if not isinstance(ev.get("tid"), int):
                errors.append(f"{where}: missing integer 'tid'")
            if not isinstance(ev.get("cat"), str):
                errors.append(f"{where}: missing 'cat' string")
        if len(errors) >= 20:
            errors.append("... (truncated)")
            break
    return errors


def validate_trace_file(path: str) -> List[str]:
    with open(path) as f:
        try:
            trace = _strict_loads(f.read())
        except ValueError as e:
            return [f"{os.path.basename(path)}: {e}"]
    return validate_chrome_trace(trace)


# ------------------------------------------------------------ bench artifacts
#: Legal `wire` values in decode-bench rows (r8). Mirrors
#: data/dtypes.WIRE_FORMATS minus 'auto' (the bench resolves auto before
#: recording) — duplicated as a literal because this module must import
#: neither numpy nor the data layer (the import-isolation test).
_WIRE_VALUES = ("host_f32", "host_bf16", "u8")


#: Legal serving-row basis labels (r17): `off` (the default every decode
#: row gets) or the open-loop bench's `openloop_b<max_batch>`.
_SERVING_MODE_RE = re.compile(r"off|openloop_b\d+")

#: Legal serving-tier labels (r23, serving/tiers.py TIERS — duplicated as
#: a literal, leaf-module contract as _ZOO_MODELS above; drift guarded by
#: tests/test_serving_tiers.py).
_SERVING_TIERS = ("fp32", "bf16", "int8", "student")


def _check_tier_accuracy_block(row: dict, where: str,
                               errors: List[str]) -> None:
    """The per-tier accuracy-delta receipt (r23): top-1 on a fixed eval
    shard for THIS tier and for the fp32 tier of the same weights, the
    delta between them, and the configured bound the delta must respect.
    A committed row whose delta exceeds its own declared bound is not a
    receipt — it is the regression the tier ladder exists to catch, so
    validation fails it."""
    acc = row.get("accuracy")
    if acc is None:
        return
    if not isinstance(acc, dict):
        errors.append(f"{where}: 'accuracy' not an object")
        return
    for key in ("top1", "fp32_top1"):
        v = acc.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) \
                or not 0 <= v <= 1:
            errors.append(f"{where}.accuracy: '{key}' not in [0, 1]")
    for key in ("delta", "bound"):
        v = acc.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            errors.append(f"{where}.accuracy: '{key}' not a number")
    n = acc.get("eval_examples")
    if not isinstance(n, int) or isinstance(n, bool) or n < 1:
        errors.append(f"{where}.accuracy: 'eval_examples' not a positive "
                      "integer")
    delta, bound = acc.get("delta"), acc.get("bound")
    if isinstance(delta, (int, float)) and isinstance(bound, (int, float)) \
            and not isinstance(delta, bool) and not isinstance(bound, bool):
        if bound < 0:
            errors.append(f"{where}.accuracy: negative 'bound'")
        elif delta > bound:
            errors.append(
                f"{where}.accuracy: top-1 delta {delta} exceeds the "
                f"declared bound {bound} — the tier broke its accuracy "
                "contract")


def validate_serving_row(row: Any, where: str, errors: List[str]) -> None:
    """One serving-bench layout row (benchmarks/serving_bench.py shape):
    the open-loop latency/throughput receipt the r17 sentinel basis keys
    on. The load-bearing claims are typed — admitted rate positive, shed
    rates in [0, 1], latency quantiles ordered p50 <= p95 <= p99, queue
    peak bounded by the configured limit — so a drifting bench serializer
    fails validation instead of committing an unreadable receipt. Tier
    rows (r23) additionally carry the `tier` label plus the accuracy-delta
    receipt block, both typed here."""
    if not isinstance(row, dict):
        errors.append(f"{where}: not an object")
        return
    v = row.get("admitted_rps")
    if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
        errors.append(f"{where}: 'admitted_rps' not a positive number")
    tier = row.get("tier")
    if tier is not None and tier not in _SERVING_TIERS:
        errors.append(f"{where}: 'tier' {tier!r} not one of "
                      f"{_SERVING_TIERS}")
    _check_tier_accuracy_block(row, where, errors)
    sv = row.get("serving")
    if not isinstance(sv, dict):
        errors.append(f"{where}: missing 'serving' config-echo object")
    else:
        bk = sv.get("buckets")
        if not (isinstance(bk, list) and bk
                and all(isinstance(b, int) and b >= 1 for b in bk)
                and bk == sorted(set(bk))):
            errors.append(f"{where}.serving: 'buckets' not unique "
                          "ascending positive ints")
        for key in ("max_batch", "queue_limit"):
            b = sv.get(key)
            if not isinstance(b, int) or isinstance(b, bool) or b < 1:
                errors.append(f"{where}.serving: '{key}' not a positive "
                              "integer")
    qp = row.get("queue_peak")
    if qp is not None:
        if not isinstance(qp, int) or isinstance(qp, bool) or qp < 0:
            errors.append(f"{where}: 'queue_peak' not a non-negative "
                          "integer")
        elif isinstance(sv, dict) and isinstance(sv.get("queue_limit"),
                                                 int) \
                and qp > sv["queue_limit"]:
            errors.append(f"{where}: queue_peak {qp} exceeds the "
                          f"configured queue_limit {sv['queue_limit']} — "
                          "the bounded-admission contract was violated")
    stages = row.get("stages")
    if not isinstance(stages, list) or not stages:
        errors.append(f"{where}: missing non-empty 'stages' list")
        return
    for i, st in enumerate(stages):
        w = f"{where}.stages[{i}]"
        if not isinstance(st, dict):
            errors.append(f"{w}: not an object")
            continue
        for key in ("offered_rps", "duration_s"):
            v = st.get(key)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v <= 0:
                errors.append(f"{w}: '{key}' not a positive number")
        v = st.get("admitted_rps")
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            errors.append(f"{w}: 'admitted_rps' not a non-negative number")
        sr = st.get("shed_rate")
        if not isinstance(sr, (int, float)) or isinstance(sr, bool) \
                or not 0 <= sr <= 1:
            errors.append(f"{w}: 'shed_rate' not in [0, 1]")
        quant = [st.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
        present = [q for q in quant if q is not None]
        if present:
            if any(not isinstance(q, (int, float)) or isinstance(q, bool)
                   or q < 0 for q in present):
                errors.append(f"{w}: latency quantiles must be "
                              "non-negative numbers")
            elif len(present) == 3 and not (quant[0] <= quant[1]
                                            <= quant[2]):
                errors.append(f"{w}: quantiles not ordered "
                              "p50 <= p95 <= p99")


def validate_resume_row(row: Any, where: str, errors: List[str]) -> None:
    """One resume-bench layout row (r18, benchmarks/resume_bench.py
    shape): the kill-at-window-k / resume receipt. The load-bearing
    contract is typed: an `exact`-mode row MUST report zero replayed
    batches — the whole claim of position-exact resume — while a `replay`
    control row must replay exactly its cursor's epoch offset."""
    if not isinstance(row, dict):
        errors.append(f"{where}: not an object")
        return
    mode = row.get("resume_mode")
    if mode not in ("replay", "exact"):
        errors.append(f"{where}: 'resume_mode' {mode!r} not replay|exact")
    rb = row.get("replayed_batches")
    if not isinstance(rb, int) or isinstance(rb, bool) or rb < 0:
        errors.append(f"{where}: 'replayed_batches' not a non-negative "
                      "integer")
    elif mode == "exact" and rb != 0:
        errors.append(f"{where}: exact-mode resume replayed {rb} batches "
                      "— the position-exact contract is zero replay")
    for key in ("resume_seconds",):
        v = row.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v < 0:
            errors.append(f"{where}: '{key}' not a non-negative number")
    for key in ("kill_cursor", "batches_per_epoch"):
        v = row.get(key)
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errors.append(f"{where}: '{key}' not a positive integer")
    if not isinstance(row.get("first_batch_matches"), bool):
        errors.append(f"{where}: missing boolean 'first_batch_matches' "
                      "(the resumed stream's first batch vs the "
                      "uninterrupted one)")
    elif not row["first_batch_matches"]:
        errors.append(f"{where}: first_batch_matches=false — the resumed "
                      "stream diverged from the uninterrupted one")


def validate_elastic_row(row: Any, where: str, errors: List[str]) -> None:
    """One elastic-bench layout row (r19, benchmarks/elastic_bench.py
    shape): the preempt-k-of-N downtime receipt. The load-bearing
    contract is typed: the live resize must replay ZERO batches (the
    cursor-handoff claim) and must beat the restart-from-checkpoint
    control by >= 3x — a committed receipt below that is a regression,
    not a receipt."""
    if not isinstance(row, dict):
        errors.append(f"{where}: not an object")
        return
    policy = row.get("batch_policy")
    if policy not in _BATCH_POLICIES:
        errors.append(f"{where}: 'batch_policy' {policy!r} not one of "
                      f"{_BATCH_POLICIES}")
    for key in ("downtime_seconds", "restart_seconds"):
        v = row.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool) or v <= 0:
            errors.append(f"{where}: '{key}' not a positive number")
    rb = row.get("replayed_batches")
    if not isinstance(rb, int) or isinstance(rb, bool) or rb < 0:
        errors.append(f"{where}: 'replayed_batches' not a non-negative "
                      "integer")
    elif rb != 0:
        errors.append(f"{where}: elastic resize replayed {rb} batches — "
                      "the cursor-handoff contract is zero replay")
    sp = row.get("speedup_vs_restart")
    if not isinstance(sp, (int, float)) or isinstance(sp, bool) or sp <= 0:
        errors.append(f"{where}: 'speedup_vs_restart' not a positive "
                      "number")
    elif sp < 3:
        errors.append(f"{where}: speedup_vs_restart {sp} < 3 — the elastic "
                      "path must beat restart-from-checkpoint by >= 3x")
    v = row.get("resizes")
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        errors.append(f"{where}: 'resizes' not a positive integer")


def _check_decode_row(row: Any, where: str, errors: List[str]) -> None:
    """r8 wire-format fields of one decode-bench layout row, when present:
    `wire` from the legal set, `wire_bytes_per_image` a positive number,
    and the phase split (`profile`) carrying positive per-image times —
    the fields the host_r9 receipts and the README wire table read."""
    if not isinstance(row, dict):
        return
    wire = row.get("wire")
    if wire is not None and wire not in _WIRE_VALUES:
        errors.append(f"{where}: 'wire' {wire!r} not one of {_WIRE_VALUES}")
    model = row.get("model")
    if model is not None and model not in _ZOO_MODELS:
        # r13 zoo rows: the per-model basis key the regression sentinel
        # gates on — an unknown model name is a labeling bug, not a row
        errors.append(f"{where}: 'model' {model!r} not one of "
                      f"{_ZOO_MODELS}")
    if "augment" in row:
        validate_augment_block(row["augment"], where, errors)
    if "comm" in row:
        validate_comm_block(row["comm"], where, errors)
    sharding = row.get("sharding")
    if sharding is not None:
        # r14/r21 comm-bench rows: (dp|zero1|zero2|zero3)[_bucketed] basis
        # key the regression sentinel gates on
        base = str(sharding).replace("_bucketed", "")
        if base not in _COMM_SHARDINGS:
            errors.append(f"{where}: 'sharding' {sharding!r} not "
                          f"<dp|zero1|zero2|zero3>[_bucketed]")
    ingest_mode = row.get("ingest_mode")
    if ingest_mode is not None and not re.fullmatch(
            r"local|service_\d+w", str(ingest_mode)):
        # r16 disaggregated-ingest rows: the `local` | `service_<N>w`
        # topology basis the sentinel keys on (Basis.ingest)
        errors.append(f"{where}: 'ingest_mode' {ingest_mode!r} not "
                      f"local|service_<N>w")
    serving_mode = row.get("serving_mode")
    if serving_mode is not None and not _SERVING_MODE_RE.fullmatch(
            str(serving_mode)):
        # r17 serving rows: the `off` | `openloop_b<N>` admission basis
        # the sentinel keys on (Basis.serving)
        errors.append(f"{where}: 'serving_mode' {serving_mode!r} not "
                      f"off|openloop_b<N>")
    resume_mode = row.get("resume_mode")
    if resume_mode is not None and resume_mode not in ("replay", "exact"):
        # r18 resume rows: the `replay` | `exact` restart basis the
        # sentinel keys on (Basis.resume)
        errors.append(f"{where}: 'resume_mode' {resume_mode!r} not "
                      "replay|exact")
    topology = row.get("topology")
    if topology is not None and (
            not isinstance(topology, str)
            or not _TOPOLOGY_RE.fullmatch(topology)):
        # r19 elastic rows: the `static` | `elastic_<N>to<M>` topology
        # basis the sentinel keys on (Basis.topology)
        errors.append(f"{where}: 'topology' {topology!r} not "
                      "static|elastic_<N>to<M>")
    if row.get("mode") == "serving_bench":
        validate_serving_row(row, where, errors)
    if row.get("mode") == "resume_bench":
        validate_resume_row(row, where, errors)
    if row.get("mode") == "elastic_bench":
        validate_elastic_row(row, where, errors)
    bpi = row.get("wire_bytes_per_image")
    if bpi is not None and (not isinstance(bpi, (int, float)) or bpi <= 0):
        errors.append(f"{where}: 'wire_bytes_per_image' not a positive "
                      "number")
    profile = row.get("profile")
    if isinstance(profile, dict):
        for key in ("jpeg_us_per_image", "resample_us_per_image"):
            v = profile.get(key)
            if v is not None and (not isinstance(v, (int, float)) or v < 0):
                errors.append(f"{where}.profile: '{key}' not a "
                              "non-negative number")
    rst = row.get("restart_receipt")
    if isinstance(rst, dict):
        # r9 entropy-path receipt: counts non-negative ints, fractions in
        # [0, 1] (or null when the window decoded nothing)
        for key in ("images", "marker_absent", "unsupported", "misaligned",
                    "scan_failures", "excerpt_fallbacks", "no_gain",
                    "segments_used", "segments_skipped", "fanout_images"):
            v = rst.get(key)
            if v is not None and (not isinstance(v, int) or v < 0):
                errors.append(f"{where}.restart_receipt: '{key}' not a "
                              "non-negative integer")
        for key in ("engaged_fraction", "segments_skipped_fraction"):
            v = rst.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or not 0 <= v <= 1):
                errors.append(f"{where}.restart_receipt: '{key}' not in "
                              "[0, 1]")
    if row.get("mode") == "decode_bench_autotune":
        # r11 convergence row: crippled start → controller-settled rate,
        # with the actuation log as the receipt
        for key in ("settled_images_per_sec", "pinned_images_per_sec"):
            v = row.get(key)
            if v is not None and (not isinstance(v, (int, float)) or v <= 0):
                errors.append(f"{where}: '{key}' not a positive number")
        vs = row.get("vs_pinned")
        if vs is not None and (not isinstance(vs, (int, float)) or vs <= 0):
            errors.append(f"{where}: 'vs_pinned' not a positive number")
        if "autotune" not in row:
            errors.append(f"{where}: autotune row missing 'autotune' "
                          "receipt object")
        else:
            validate_autotune_receipt(row["autotune"], where, errors)
    if row.get("mode") == "decode_bench_snapshot":
        # r9 snapshot warm-vs-cold row: rates positive, hit receipts sane
        for key in ("warm_images_per_sec_per_core",
                    "cold_images_per_sec_per_core",
                    "cold_fill_images_per_sec"):
            v = row.get(key)
            if v is not None and (not isinstance(v, (int, float)) or v <= 0):
                errors.append(f"{where}: '{key}' not a positive number")
        snap = row.get("snapshot")
        if not isinstance(snap, dict):
            errors.append(f"{where}: snapshot row missing 'snapshot' "
                          "receipt object")
        else:
            for key in ("hits", "misses", "bytes_served", "items"):
                v = snap.get(key)
                if v is not None and (not isinstance(v, int) or v < 0):
                    errors.append(f"{where}.snapshot: '{key}' not a "
                                  "non-negative integer")
            hr = snap.get("hit_rate")
            if hr is not None and (not isinstance(hr, (int, float))
                                   or not 0 <= hr <= 1):
                errors.append(f"{where}.snapshot: 'hit_rate' not in [0, 1]")


def validate_bench_artifact(obj: Any) -> List[str]:
    """A --json-out style artifact: object, finite numbers, and when it
    carries a contract metric the value must be numeric — unless the
    artifact is an explicit failure record (`error` present), where a null
    value is the documented shape (bench.py writes value=null +
    error=bench_failed when the TPU run died). Decode-bench layout rows
    additionally get their r8 wire-format fields checked."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"artifact is {type(obj).__name__}, expected object"]
    _check_finite(obj, "artifact", errors)
    validate_schema_version(obj.get("schema_version"), "artifact", errors)
    if "metric" in obj and "error" not in obj \
            and not isinstance(obj.get("value"), (int, float)):
        errors.append("artifact: 'metric' present but 'value' not numeric")
    if "autotune" in obj:
        validate_autotune_receipt(obj["autotune"], "artifact", errors)
    layouts = obj.get("layouts")
    if isinstance(layouts, list):
        for i, row in enumerate(layouts):
            _check_decode_row(row, f"artifact.layouts[{i}]", errors)
    return errors


def validate_bench_artifact_file(path: str) -> List[str]:
    with open(path) as f:
        try:
            obj = _strict_loads(f.read())
        except ValueError as e:
            return [f"{os.path.basename(path)}: {e}"]
    return validate_bench_artifact(obj)


# --------------------------------------------------------- flight black box
#: Crash classes a flight-recorder black box may carry. Mirrors
#: flight.CRASH_KINDS — duplicated as a literal so the validator stays a
#: leaf module (flight.py imports schema, never the reverse).
_FLIGHT_REASONS = ("nonfinite_abort", "data_stall", "injected_crash",
                   "elastic_degraded_restart", "unhandled_exception")


def validate_flight_record(record: Any) -> List[str]:
    """One flight-recorder black box (telemetry/flight.py dump shape): the
    artifact a post-crash triage reads FIRST, so its shape drifting
    silently would break the tooling exactly when it is needed."""
    errors: List[str] = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected object"]
    if record.get("kind") != "flight_black_box":
        errors.append(f"'kind' is {record.get('kind')!r}, expected "
                      f"'flight_black_box'")
    validate_schema_version(record.get("schema_version"), "record", errors)
    if record.get("schema_version") is None:
        errors.append("missing 'schema_version' (flight records are "
                      "versioned from birth — no pre-versioned cohort)")
    if record.get("reason") not in _FLIGHT_REASONS:
        errors.append(f"'reason' {record.get('reason')!r} not one of "
                      f"{_FLIGHT_REASONS}")
    if not isinstance(record.get("process"), int):
        errors.append("missing integer 'process'")
    windows = record.get("windows")
    if not isinstance(windows, list):
        errors.append("missing 'windows' list")
    else:
        for i, w in enumerate(windows):
            where = f"windows[{i}]"
            if not isinstance(w, dict):
                errors.append(f"{where}: not an object")
                continue
            if not isinstance(w.get("step"), int):
                errors.append(f"{where}: missing integer 'step'")
            wall = w.get("wall_s")
            if not isinstance(wall, (int, float)) or wall < 0 \
                    or not math.isfinite(wall):
                errors.append(f"{where}: 'wall_s' not a non-negative "
                              "finite number")
            stall = w.get("stall")
            if stall is not None and not (
                    isinstance(stall, dict)
                    and isinstance(stall.get("verdict"), str)):
                errors.append(f"{where}: 'stall' present but carries no "
                              "'verdict' string")
            if len(errors) >= 20:
                errors.append("... (truncated)")
                break
    exc = record.get("exception")
    if exc is not None and not (isinstance(exc, dict)
                                and isinstance(exc.get("type"), str)):
        errors.append("'exception' present but carries no 'type' string")
    acts = record.get("autotune_actuations")
    if acts is not None:
        # r11: the last-N autotune actuations ride the black box so a
        # post-crash triage can see whether the controller moved before
        # the abort
        if not isinstance(acts, list):
            errors.append("'autotune_actuations' present but not a list")
        else:
            for i, act in enumerate(acts):
                validate_autotune_actuation(
                    act, f"autotune_actuations[{i}]", errors)
    _check_finite(record, "record", errors)
    return errors


def validate_flight_file(path: str) -> List[str]:
    with open(path) as f:
        try:
            record = _strict_loads(f.read())
        except ValueError as e:
            return [f"{os.path.basename(path)}: {e}"]
    return validate_flight_record(record)


# ----------------------------------------------------------- perf trajectory
def validate_trajectory(obj: Any) -> List[str]:
    """The machine-readable perf trajectory (telemetry/regress.py
    build_trajectory → benchmarks/runs/trajectory.json): per-pin committed
    evidence the regression sentinel gates against."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"trajectory is {type(obj).__name__}, expected object"]
    if obj.get("kind") != "perf_trajectory":
        errors.append(f"'kind' is {obj.get('kind')!r}, expected "
                      "'perf_trajectory'")
    validate_schema_version(obj.get("schema_version"), "trajectory", errors)

    def check_rounds(rounds, section):
        for i, r in enumerate(rounds):
            where = f"{section}[{i}]"
            if not isinstance(r, dict):
                errors.append(f"{where}: not an object")
                continue
            for key in ("pin", "round"):
                if not isinstance(r.get(key), str):
                    errors.append(f"{where}: missing '{key}' string")
            v = r.get("value")
            if not isinstance(v, (int, float)) or v <= 0:
                errors.append(f"{where}: 'value' not a positive number")
            arts = r.get("artifacts")
            if not isinstance(arts, list) or not arts:
                errors.append(f"{where}: missing non-empty 'artifacts' "
                              "list")
                continue
            for j, a in enumerate(arts):
                if not (isinstance(a, dict)
                        and isinstance(a.get("path"), str)
                        and isinstance(a.get("value"), (int, float))):
                    errors.append(f"{where}.artifacts[{j}]: needs 'path' "
                                  "string + numeric 'value'")

    rounds = obj.get("host_decode")
    if not isinstance(rounds, list) or not rounds:
        errors.append("missing non-empty 'host_decode' list")
        return errors
    check_rounds(rounds, "host_decode")
    serving = obj.get("serving")
    if serving is not None:
        # r17: the serving chain's rounds — same per-round shape, its own
        # pin sequence (absent entirely only in pre-r17 trajectories)
        if not isinstance(serving, list):
            errors.append("'serving' present but not a list")
        else:
            check_rounds(serving, "serving")
    _check_finite(obj, "trajectory", errors)
    return errors


# ------------------------------------------------------------- fleet JSONL
#: Legal per-process entry statuses in fleet records (r22,
#: telemetry/collector.py). Mirrors FleetCollector's entry lifecycle —
#: duplicated as a literal, leaf-module contract.
_FLEET_STATUSES = ("live", "stale")

#: Legal fleet/per-process verdicts — stall.VERDICTS duplicated as a
#: literal (same contract; the drift is guarded by test).
_FLEET_VERDICTS = ("guard_stalled", "checkpoint_bound", "infeed_bound",
                   "compute_bound")


def validate_fleet_record(record: Any) -> List[str]:
    """One fleet-collector JSONL cycle record (r22,
    FleetCollector.collect_once shape): the quorum verdict + per-process
    roll call the fleet log archives per scrape cycle."""
    errors: List[str] = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected object"]
    if record.get("event") != "fleet_window":
        errors.append(f"'event' is {record.get('event')!r}, expected "
                      "'fleet_window'")
    validate_schema_version(record.get("schema_version"), "record", errors)
    if record.get("schema_version") is None:
        errors.append("missing 'schema_version' (fleet records are "
                      "versioned from birth — no pre-versioned cohort)")
    v = record.get("cycle")
    if not isinstance(v, int) or isinstance(v, bool) or v < 1:
        errors.append("'cycle' not a positive integer")
    fleet = record.get("fleet")
    if not isinstance(fleet, dict):
        errors.append("missing 'fleet' object")
    else:
        verdict = fleet.get("verdict")
        if verdict is not None and verdict not in _FLEET_VERDICTS:
            errors.append(f"fleet: 'verdict' {verdict!r} not one of "
                          f"{_FLEET_VERDICTS}")
        for key in ("quorum", "of"):
            v = fleet.get(key)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                errors.append(f"fleet: '{key}' not a non-negative integer")
        if isinstance(fleet.get("quorum"), int) \
                and isinstance(fleet.get("of"), int) \
                and fleet["quorum"] > fleet["of"]:
            errors.append("fleet: quorum exceeds the process count it was "
                          "taken over")
        stragglers = fleet.get("stragglers")
        if not isinstance(stragglers, dict) or not all(
                isinstance(k, str) and s in _FLEET_VERDICTS
                for k, s in stragglers.items()):
            errors.append("fleet: 'stragglers' not an object of "
                          "name -> verdict")
        if not isinstance(fleet.get("detail"), str):
            errors.append("fleet: missing 'detail' string")
    procs = record.get("processes")
    if not isinstance(procs, list):
        errors.append("missing 'processes' list")
    else:
        for i, p in enumerate(procs):
            where = f"processes[{i}]"
            if not isinstance(p, dict):
                errors.append(f"{where}: not an object")
                continue
            if not isinstance(p.get("role"), str) or not p.get("role"):
                errors.append(f"{where}: missing 'role' string")
            if not isinstance(p.get("ident"), int) \
                    or isinstance(p.get("ident"), bool):
                errors.append(f"{where}: missing integer 'ident'")
            if p.get("status") not in _FLEET_STATUSES:
                errors.append(f"{where}: 'status' {p.get('status')!r} not "
                              f"one of {_FLEET_STATUSES}")
            verdict = p.get("verdict")
            if verdict is not None and verdict not in _FLEET_VERDICTS:
                errors.append(f"{where}: 'verdict' {verdict!r} not one of "
                              f"{_FLEET_VERDICTS}")
            age = p.get("age_s")
            if age is not None and (not isinstance(age, (int, float))
                                    or isinstance(age, bool) or age < 0
                                    or not math.isfinite(age)):
                errors.append(f"{where}: 'age_s' not a non-negative finite "
                              "number")
            if len(errors) >= 20:
                errors.append("... (truncated)")
                break
    _check_finite(record, "record", errors)
    return errors


def validate_fleet_jsonl(path: str, max_errors: int = 20) -> List[str]:
    """Whole-file check over a collector fleet log."""
    errors: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                record = _strict_loads(line)
            except ValueError as e:
                errors.append(f"line {lineno}: {e}")
            else:
                errors.extend(f"line {lineno}: {err}"
                              for err in validate_fleet_record(record))
            if len(errors) >= max_errors:
                errors.append("... (truncated)")
                break
    return errors


# ----------------------------------------------------------- stitch manifest
def validate_stitch_manifest(obj: Any) -> List[str]:
    """The stitched-trace manifest (r22, telemetry/stitch.py): which input
    traces landed at which Perfetto pids and which correlation ids became
    flow arrows — the committed receipt's machine-checkable half (the
    other half is the stitched trace itself, validate_chrome_trace)."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"manifest is {type(obj).__name__}, expected object"]
    if obj.get("kind") != "stitched_trace_manifest":
        errors.append(f"'kind' is {obj.get('kind')!r}, expected "
                      "'stitched_trace_manifest'")
    validate_schema_version(obj.get("schema_version"), "manifest", errors)
    if obj.get("schema_version") is None:
        errors.append("missing 'schema_version' (stitch manifests are "
                      "versioned from birth — no pre-versioned cohort)")
    inputs = obj.get("inputs")
    if not isinstance(inputs, list) or not inputs:
        errors.append("missing non-empty 'inputs' list")
        inputs = []
    pids = set()
    for i, inp in enumerate(inputs):
        where = f"inputs[{i}]"
        if not isinstance(inp, dict):
            errors.append(f"{where}: not an object")
            continue
        if not isinstance(inp.get("path"), str):
            errors.append(f"{where}: missing 'path' string")
        pid = inp.get("pid")
        if not isinstance(pid, int) or isinstance(pid, bool) or pid < 1:
            errors.append(f"{where}: 'pid' not a positive integer")
        elif pid in pids:
            # the whole point of the remap: two in-process workers share
            # an OS pid but MUST occupy distinct Perfetto process lanes
            errors.append(f"{where}: duplicate pid {pid} — stitched "
                          "inputs must land on distinct process lanes")
        else:
            pids.add(pid)
        if not isinstance(inp.get("process_name"), str) \
                or not inp.get("process_name"):
            errors.append(f"{where}: missing 'process_name' string")
        v = inp.get("events")
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            errors.append(f"{where}: 'events' not a non-negative integer")
    flows = obj.get("flows")
    if not isinstance(flows, list):
        errors.append("missing 'flows' list")
        flows = []
    for i, fl in enumerate(flows):
        where = f"flows[{i}]"
        if not isinstance(fl, dict):
            errors.append(f"{where}: not an object")
            continue
        v = fl.get("id")
        if not isinstance(v, int) or isinstance(v, bool) or v < 1:
            errors.append(f"{where}: 'id' not a positive integer")
        if not isinstance(fl.get("trace_id"), str) or not fl.get("trace_id"):
            errors.append(f"{where}: missing 'trace_id' string")
        src = fl.get("src")
        if not (isinstance(src, dict) and isinstance(src.get("pid"), int)
                and isinstance(src.get("name"), str)):
            errors.append(f"{where}: 'src' not {{pid: int, name: str}}")
        elif src["pid"] not in pids and pids:
            errors.append(f"{where}: src pid {src['pid']} names no input")
        dst = fl.get("dst")
        if not isinstance(dst, list) or not dst:
            errors.append(f"{where}: missing non-empty 'dst' list")
        else:
            for j, d in enumerate(dst):
                if not (isinstance(d, dict)
                        and isinstance(d.get("pid"), int)
                        and isinstance(d.get("name"), str)):
                    errors.append(f"{where}.dst[{j}]: not "
                                  "{pid: int, name: str}")
                elif d["pid"] not in pids and pids:
                    errors.append(f"{where}.dst[{j}]: pid {d['pid']} "
                                  "names no input")
        if len(errors) >= 20:
            errors.append("... (truncated)")
            break
    v = obj.get("events_total")
    if not isinstance(v, int) or isinstance(v, bool) or v < 0:
        errors.append("'events_total' not a non-negative integer")
    _check_finite(obj, "manifest", errors)
    return errors


def validate_stitch_manifest_file(path: str) -> List[str]:
    with open(path) as f:
        try:
            obj = _strict_loads(f.read())
        except ValueError as e:
            return [f"{os.path.basename(path)}: {e}"]
    return validate_stitch_manifest(obj)
