"""Record-shape validators for the telemetry surfaces — the CI tripwire
that makes schema drift fail a test instead of corrupting run archives.

Three record families, each with a `validate_*` returning a list of error
strings (empty = valid; callers assert `not errors` so a failure names every
problem at once):

- metrics JSONL (utils/logging.py MetricLogger): one JSON object per line,
  an `event` string, values JSON-legal — in particular NO bare
  ``NaN``/``Infinity`` tokens. Python's `json.loads` ACCEPTS those
  non-standard tokens by default, so the validator parses with a strict
  `parse_constant` to catch exactly the records that would break a
  spec-compliant downstream parser (jq, BigQuery, serde).
- Chrome trace-event JSON (telemetry/spans.py export): object format with a
  `traceEvents` list of `ph: "X"` complete events (plus `M` metadata), the
  shape Perfetto and chrome://tracing load.
- bench artifacts (benchmarks/host_pipeline_bench.py --json-out): a JSON
  object with a numeric `metric`/`value` pair and finite numbers
  throughout.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, List


def _strict_loads(text: str):
    """json.loads rejecting the non-standard NaN/Infinity/-Infinity tokens
    (JSON-illegal, but emitted by a naive json.dumps of a non-finite float
    — the exact bug the MetricLogger satellite fixed)."""

    def _bad(token: str):
        raise ValueError(f"JSON-illegal constant {token!r}")

    return json.loads(text, parse_constant=_bad)


def _check_finite(value: Any, path: str, errors: List[str]) -> None:
    """Recursively reject non-finite floats — they survive a permissive
    load but re-serialize illegally."""
    if isinstance(value, float) and not math.isfinite(value):
        errors.append(f"{path}: non-finite float {value!r}")
    elif isinstance(value, dict):
        for k, v in value.items():
            if not isinstance(k, str):
                errors.append(f"{path}.{k}: non-string key")
            _check_finite(v, f"{path}.{k}", errors)
    elif isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            _check_finite(v, f"{path}[{i}]", errors)
    elif value is not None and not isinstance(value, (str, int, float, bool)):
        errors.append(f"{path}: non-JSON value of type "
                      f"{type(value).__name__}")


# ------------------------------------------------------------- metrics JSONL
def validate_metrics_record(record: Any) -> List[str]:
    """One MetricLogger record (already parsed)."""
    errors: List[str] = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected object"]
    event = record.get("event")
    if not isinstance(event, str) or not event:
        errors.append("missing/empty 'event' string")
    _check_finite(record, "record", errors)
    return errors


def validate_metrics_jsonl(path: str, max_errors: int = 20) -> List[str]:
    """Whole-file check: every line parses strictly and validates."""
    errors: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                record = _strict_loads(line)
            except ValueError as e:
                errors.append(f"line {lineno}: {e}")
            else:
                errors.extend(f"line {lineno}: {err}"
                              for err in validate_metrics_record(record))
            if len(errors) >= max_errors:
                errors.append("... (truncated)")
                break
    return errors


# -------------------------------------------------------------- Chrome trace
def validate_chrome_trace(trace: Any) -> List[str]:
    """Trace-event JSON object format (the spans.py export shape)."""
    errors: List[str] = []
    if not isinstance(trace, dict):
        return [f"trace is {type(trace).__name__}, expected object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["missing 'traceEvents' list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if not isinstance(ev.get("name"), str):
            errors.append(f"{where}: missing 'name' string")
        if ph not in ("X", "M", "B", "E", "i", "C"):
            errors.append(f"{where}: unsupported ph {ph!r}")
        if not isinstance(ev.get("pid"), int):
            errors.append(f"{where}: missing integer 'pid'")
        if ph == "X":
            for key in ("ts", "dur"):
                v = ev.get(key)
                if not isinstance(v, (int, float)) or not math.isfinite(v):
                    errors.append(f"{where}: '{key}' not a finite number")
            if isinstance(ev.get("dur"), (int, float)) and ev["dur"] < 0:
                errors.append(f"{where}: negative duration")
            if not isinstance(ev.get("tid"), int):
                errors.append(f"{where}: missing integer 'tid'")
            if not isinstance(ev.get("cat"), str):
                errors.append(f"{where}: missing 'cat' string")
        if len(errors) >= 20:
            errors.append("... (truncated)")
            break
    return errors


def validate_trace_file(path: str) -> List[str]:
    with open(path) as f:
        try:
            trace = _strict_loads(f.read())
        except ValueError as e:
            return [f"{os.path.basename(path)}: {e}"]
    return validate_chrome_trace(trace)


# ------------------------------------------------------------ bench artifacts
#: Legal `wire` values in decode-bench rows (r8). Mirrors
#: data/dtypes.WIRE_FORMATS minus 'auto' (the bench resolves auto before
#: recording) — duplicated as a literal because this module must import
#: neither numpy nor the data layer (the import-isolation test).
_WIRE_VALUES = ("host_f32", "host_bf16", "u8")


def _check_decode_row(row: Any, where: str, errors: List[str]) -> None:
    """r8 wire-format fields of one decode-bench layout row, when present:
    `wire` from the legal set, `wire_bytes_per_image` a positive number,
    and the phase split (`profile`) carrying positive per-image times —
    the fields the host_r9 receipts and the README wire table read."""
    if not isinstance(row, dict):
        return
    wire = row.get("wire")
    if wire is not None and wire not in _WIRE_VALUES:
        errors.append(f"{where}: 'wire' {wire!r} not one of {_WIRE_VALUES}")
    bpi = row.get("wire_bytes_per_image")
    if bpi is not None and (not isinstance(bpi, (int, float)) or bpi <= 0):
        errors.append(f"{where}: 'wire_bytes_per_image' not a positive "
                      "number")
    profile = row.get("profile")
    if isinstance(profile, dict):
        for key in ("jpeg_us_per_image", "resample_us_per_image"):
            v = profile.get(key)
            if v is not None and (not isinstance(v, (int, float)) or v < 0):
                errors.append(f"{where}.profile: '{key}' not a "
                              "non-negative number")
    rst = row.get("restart_receipt")
    if isinstance(rst, dict):
        # r9 entropy-path receipt: counts non-negative ints, fractions in
        # [0, 1] (or null when the window decoded nothing)
        for key in ("images", "marker_absent", "unsupported", "misaligned",
                    "scan_failures", "excerpt_fallbacks", "no_gain",
                    "segments_used", "segments_skipped", "fanout_images"):
            v = rst.get(key)
            if v is not None and (not isinstance(v, int) or v < 0):
                errors.append(f"{where}.restart_receipt: '{key}' not a "
                              "non-negative integer")
        for key in ("engaged_fraction", "segments_skipped_fraction"):
            v = rst.get(key)
            if v is not None and (not isinstance(v, (int, float))
                                  or not 0 <= v <= 1):
                errors.append(f"{where}.restart_receipt: '{key}' not in "
                              "[0, 1]")
    if row.get("mode") == "decode_bench_snapshot":
        # r9 snapshot warm-vs-cold row: rates positive, hit receipts sane
        for key in ("warm_images_per_sec_per_core",
                    "cold_images_per_sec_per_core",
                    "cold_fill_images_per_sec"):
            v = row.get(key)
            if v is not None and (not isinstance(v, (int, float)) or v <= 0):
                errors.append(f"{where}: '{key}' not a positive number")
        snap = row.get("snapshot")
        if not isinstance(snap, dict):
            errors.append(f"{where}: snapshot row missing 'snapshot' "
                          "receipt object")
        else:
            for key in ("hits", "misses", "bytes_served", "items"):
                v = snap.get(key)
                if v is not None and (not isinstance(v, int) or v < 0):
                    errors.append(f"{where}.snapshot: '{key}' not a "
                                  "non-negative integer")
            hr = snap.get("hit_rate")
            if hr is not None and (not isinstance(hr, (int, float))
                                   or not 0 <= hr <= 1):
                errors.append(f"{where}.snapshot: 'hit_rate' not in [0, 1]")


def validate_bench_artifact(obj: Any) -> List[str]:
    """A --json-out style artifact: object, finite numbers, and when it
    carries a contract metric the value must be numeric — unless the
    artifact is an explicit failure record (`error` present), where a null
    value is the documented shape (bench.py writes value=null +
    error=bench_failed when the TPU run died). Decode-bench layout rows
    additionally get their r8 wire-format fields checked."""
    errors: List[str] = []
    if not isinstance(obj, dict):
        return [f"artifact is {type(obj).__name__}, expected object"]
    _check_finite(obj, "artifact", errors)
    if "metric" in obj and "error" not in obj \
            and not isinstance(obj.get("value"), (int, float)):
        errors.append("artifact: 'metric' present but 'value' not numeric")
    layouts = obj.get("layouts")
    if isinstance(layouts, list):
        for i, row in enumerate(layouts):
            _check_decode_row(row, f"artifact.layouts[{i}]", errors)
    return errors


def validate_bench_artifact_file(path: str) -> List[str]:
    with open(path) as f:
        try:
            obj = _strict_loads(f.read())
        except ValueError as e:
            return [f"{os.path.basename(path)}: {e}"]
    return validate_bench_artifact(obj)
