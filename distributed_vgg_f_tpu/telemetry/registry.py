"""Process-wide counter/gauge registry — ONE namespace for every signal the
framework already produces but used to scatter across gadgets: the native
decoder's `decode_stats`/`decode_profile`, the prefetch queue's depth and
wait time, resilience guard/watchdog/injector events, checkpoint save and
retry timings.

Three kinds of entries:

- **counters** — monotonically increasing, owned by the registry
  (`inc(name)`); `delta(consumer)` reports the change since that consumer's
  last call, which is how the trainer folds per-log-window counter activity
  into the step log without any call site knowing the cadence.
- **gauges** — last-write-wins instantaneous values (`set_gauge`), reported
  absolute (queue depth, pool hit rate).
- **pollers** — pull adapters over subsystems that keep their OWN cumulative
  state (the native .so's process-wide stats): `register_poller(ns, fn,
  cumulative=True)` namespaces `fn()`'s mapping under `ns/` and folds it
  into snapshots; cumulative pollers participate in `delta`.

Naming convention (README "Observability"): `<subsystem>/<metric>`, nested
mappings flattened with `/` — e.g. `decode/scale_histogram/4`,
`prefetch/wait_ns`, `resilience/nonfinite_skips`, `checkpoint/save_retries`,
`fault/nan`.

A poller that raises must never take the trainer down — the error is
swallowed into the `telemetry/poller_errors` counter and the poller's keys
simply go missing from that snapshot.

The namespace is PROCESS-GLOBAL by design (like the native decoder's own
decode_stats): two concurrently-live pipelines in one process — a second
Trainer, a caller-constructed prefetch iterator — share `prefetch/*` etc.
That is the same tradeoff the fixed counter names buy their greppability
with; per-instance attribution belongs in spans (which carry thread ids),
not in counter names.

Stdlib-only, like the rest of the package.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Mapping, Optional

Number = float  # ints pass through unwidened; the annotation is documentary


def _flatten(namespace: str, value, out: Dict[str, float]) -> None:
    """Flatten nested mappings into `ns/key/subkey` entries; non-numeric
    leaves are dropped (the registry is a number store — strings belong in
    the metrics log, not the counter namespace)."""
    if isinstance(value, Mapping):
        for k, v in value.items():
            _flatten(f"{namespace}/{k}", v, out)
    elif isinstance(value, bool):
        out[namespace] = int(value)
    elif isinstance(value, (int, float)):
        out[namespace] = value


class TelemetryRegistry:
    """Thread-safe named counters + gauges + pull pollers with per-consumer
    delta snapshots."""

    def __init__(self, enabled: bool = True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # ns -> (fn, cumulative)
        self._pollers: Dict[str, tuple] = {}
        # consumer -> last cumulative view handed to delta()
        self._baselines: Dict[str, Dict[str, float]] = {}

    # -------------------------------------------------------------- counters
    def counter(self, name: str) -> None:
        """Pre-create a counter at 0 so it appears in every snapshot even
        before the first increment — a zero that is VISIBLE ("no decode
        errors") reads very differently from a missing key ("decode errors
        not instrumented")."""
        with self._lock:
            self._counters.setdefault(name, 0)

    def inc(self, name: str, value: float = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + value

    def set_gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value

    # --------------------------------------------------------------- pollers
    def register_poller(self, namespace: str,
                        fn: Callable[[], Optional[Mapping]],
                        cumulative: bool = True) -> None:
        """Register (or replace) a pull adapter. `fn()` returns a mapping
        (possibly nested; possibly None when the subsystem is unavailable)
        polled at snapshot/delta time. `cumulative=True` marks the values as
        monotonically increasing since process start, which lets `delta`
        difference them like native counters; pass False for
        instantaneous readings (treated like gauges)."""
        with self._lock:
            self._pollers[namespace] = (fn, bool(cumulative))

    def unregister_poller(self, namespace: str) -> None:
        with self._lock:
            self._pollers.pop(namespace, None)

    def has_poller(self, namespace: str) -> bool:
        """Registration guards must ask the REGISTRY, not keep their own
        module flag: reset() drops pollers, and a stale module flag would
        silently sever the subsystem's counters for the process lifetime."""
        with self._lock:
            return namespace in self._pollers

    def gauge(self, name: str, default=None):
        """One gauge, read directly — NO poller sweep. The stall attributor
        reads `prefetch/queue_depth` every log window; paying a full
        snapshot() (ctypes decode_stats + profile calls) for one number
        would double the native poll per window."""
        with self._lock:
            return self._gauges.get(name, default)

    def counter_value(self, name: str, default=None):
        """One registry-owned counter, read directly — NO poller sweep
        (poller-fed values are invisible here by design). The exporter's
        /healthz reads a handful of watchdog counters per probe; sweeping
        the native pollers for each liveness poll would make health checks
        a measurable decode tax."""
        with self._lock:
            return self._counters.get(name, default)

    def _poll(self) -> tuple[Dict[str, float], Dict[str, float]]:
        """(cumulative, instantaneous) flattened poller readings."""
        with self._lock:
            pollers = list(self._pollers.items())
        cum: Dict[str, float] = {}
        inst: Dict[str, float] = {}
        for ns, (fn, cumulative) in pollers:
            try:
                value = fn()
            except Exception:
                self.inc("telemetry/poller_errors")
                continue
            if value is None:
                continue
            _flatten(ns, value, cum if cumulative else inst)
        return cum, inst

    # ------------------------------------------------------------- snapshots
    def _cumulative_view(self) -> tuple[Dict[str, float], Dict[str, float]]:
        """(all cumulative values incl. pollers, all instantaneous values)."""
        cum, inst = self._poll()
        with self._lock:
            cum.update(self._counters)
            inst.update(self._gauges)
        return cum, inst

    def snapshot(self) -> Dict[str, float]:
        """One flat mapping of everything, cumulative counters as absolutes
        — the end-of-run summary shape."""
        cum, inst = self._cumulative_view()
        return {**cum, **inst}

    def snapshot_split(self) -> Dict[str, Dict[str, float]]:
        """{"counters": cumulative values, "gauges": instantaneous values}
        — the sidecar record shape: a cross-process aggregator may SUM
        counters but must never sum gauges (summing four ranks'
        queue_depth=2 into "8" fabricates a number nobody measured)."""
        cum, inst = self._cumulative_view()
        return {"counters": cum, "gauges": inst}

    def delta(self, consumer: str = "default") -> Dict[str, float]:
        """Counter CHANGES since this consumer's previous `delta` call
        (first call: change since process start), gauges absolute. Each
        consumer keeps its own baseline, so the trainer's per-window deltas
        and a bench's per-run deltas never race each other."""
        cum, inst = self._cumulative_view()
        with self._lock:
            base = self._baselines.get(consumer, {})
            # MERGE over the prior baseline, never replace: a transient
            # poller failure drops its keys from this poll, and a wholesale
            # replacement would erase their baseline — the next successful
            # poll would then report the poller's process-lifetime totals
            # as one window's delta (code-review r8).
            self._baselines[consumer] = {**base, **cum}
        out = {k: v - base.get(k, 0) for k, v in cum.items()}
        out.update(inst)
        return out

    def reset(self) -> None:
        """Drop every counter, gauge, poller, and baseline (tests)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._pollers.clear()
            self._baselines.clear()


# --------------------------------------------------------------------------
# Process-wide default registry.
# --------------------------------------------------------------------------

_default = TelemetryRegistry()


def get_registry() -> TelemetryRegistry:
    return _default


def inc(name: str, value: float = 1) -> None:
    _default.inc(name, value)


def set_gauge(name: str, value: float) -> None:
    _default.set_gauge(name, value)


def register_poller(namespace: str, fn, cumulative: bool = True) -> None:
    _default.register_poller(namespace, fn, cumulative)
