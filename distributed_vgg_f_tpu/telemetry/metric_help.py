"""One registry of metric-family help text — the source for every
`# HELP` line the Prometheus surfaces emit (the per-process exporter's
/metrics AND the fleet collector's aggregate /metrics).

The table is keyed by NAMESPACE (the `<subsystem>/` prefix of the
registry's `<subsystem>/<metric>` names), not per-metric: per-metric prose
already lives in the README "Counter namespace" table, and duplicating it
here would rot. `help_for(name)` renders the family line a scraper shows
next to the counter.

Lint contract (tools/lint/rules.py counter-namespace-drift): the keys of
NAMESPACE_HELP must equal the namespaces of the README counter table —
a counter namespace that ships without help text (or help text for a
namespace nothing registers) fails `tools/check.sh`. The `bench/`
namespace is excluded on both sides (bench-only, never in training runs).

Leaf module by the telemetry import contract: stdlib only, imports
nothing from the package.
"""

from __future__ import annotations

#: namespace → one-line help text. Keep entries terse: Prometheus shows
#: them inline in the exposition; the README table carries the detail.
NAMESPACE_HELP = {
    "decode": "native JPEG decoder stats (images, phase times, restart "
              "entropy path, scale histogram)",
    "prefetch": "device-prefetch pipeline (batches, waits, queue depths, "
                "snapshot cache, bytes in flight)",
    "native_loader": "native batch-loader iterator",
    "resilience": "non-finite guards and the data-stall watchdog",
    "checkpoint": "checkpoint manager (saves, retries, waits, restores)",
    "fault": "chaos injectors (injected nan/stall/crash/preempt/kills)",
    "step": "jitted train-step dispatch wrapper",
    "eval": "trainer evaluation passes",
    "distributed": "cross-process coordination barriers",
    "telemetry": "the telemetry registry itself (poller faults)",
    "exporter": "per-process live HTTP observability endpoint",
    "autotune": "closed-loop ingest/admission controller (windows, "
                "actuations, rails, per-knob gauges)",
    "augment": "fused on-device augmentation stage",
    "comm": "gradient/parameter exchange (collective payload bytes, "
            "buckets, ZeRO gathers)",
    "ingest_service": "disaggregated ingest (worker serving plane + "
                      "trainer-side client)",
    "serving": "predict server (admission, sheds, batches, latency "
               "quantiles, per-tier traffic + quantiles)",
    "ingest_state": "position-exact resumable ingest (state blobs, "
                    "transplants, live rebuilds)",
    "elastic": "live elastic resize (survivor-mesh resizes, shard "
               "evacuations, downtime)",
    "collector": "fleet collector scrape loop (scrapes, faults, endpoint "
                 "liveness)",
    "fleet": "fleet-level aggregation (merged windows, live processes, "
             "stragglers)",
}


def help_for(name: str) -> str:
    """Family help line for one registry metric name. Unknown namespaces
    (dynamic/bench-only) get a generic line rather than an error — the
    exporter must render whatever the registry holds."""
    ns = name.split("/", 1)[0]
    return NAMESPACE_HELP.get(ns, f"{ns} subsystem metric")
