"""Receipt-driven perf regression sentinel — the machine half of the r5–r10
benchmarking discipline (ISSUE 8; tf.data, arXiv 2101.12127, makes the case
that pipeline guarding must ride measured, machine-checked signals, not
hand-read tables).

Three jobs, all over COMMITTED evidence:

1. **Trajectory** (`build_trajectory`): parse every committed
   `benchmarks/runs/host_r*/` decode artifact and repo-root `BENCH_r*.json`
   into one machine-readable file (`benchmarks/runs/trajectory.json`) — per
   round: the pinned constant, its provenance artifacts (the exact files the
   `HOST_DECODE_RATE_R*` docstrings cite), every other artifact in the round
   dir with its measured basis, and the tolerance band derived below.
2. **Committed-consistency check** (`check_committed`): each pin equals the
   LOWER of its provenance artifacts (the committed convention), every
   provenance artifact schema-validates and carries the pin's basis, and the
   pin sequence is monotone EXCEPT transitions carrying an explicit drift
   receipt (r6→r7 is box drift, receipted in host_r7/README.md with
   same-session worktree controls). Runs in tier-1: a PR that edits a pin
   without committing matching receipts — or commits receipts that no longer
   back the pin — fails before it merges.
3. **New-artifact gate** (`check_artifact`): a fresh `--json-out` bench
   artifact is matched to the newest gating pin with the same measured basis
   (wire, space-to-depth, source size/kind, restart markers) and must land
   within the tolerance band BELOW the pin — the pre-commit/CI gate that
   stops the next ingest PR from silently giving back r6–r10's wins.

Tolerance-band derivation (documented here because the number IS the
policy): each committed artifact records `spread` = (max−min)/median over
its min-of-N alternating windows — same-session, same-box noise on ONE
window. The committed value is the best-of-N window, whose downside noise
is roughly half the window spread (the best window sits at the top of the
window distribution; a regression has to drag the BEST window down). So

    tolerance = clamp(0.5 · max(spread over the pin's provenance runs),
                      0.02, 0.06)

floor 2 % (below that, any box hiccup would page), cap 6 % (above that the
band would swallow a real −10 % regression — the acceptance case). The band
covers SAME-BOX noise only: committed READMEs show this host drifting
±5–8 % between sessions, which is exactly why the r6–r10 protocol pairs
every claim with same-session worktree controls; a sentinel failure on a
drifted box means "re-measure with controls", not necessarily "regressed".

Stdlib-only. The pin VALUES are imported from utils/scaling_model.py (the
single source since r5) — itself stdlib-only, so the telemetry package's
import-isolation contract holds through this module too.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import re
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from distributed_vgg_f_tpu.telemetry import schema

#: The contract metric every host decode artifact carries.
HOST_METRIC = "host_native_decode_images_per_sec_per_core"

#: The contract metric of a serving open-loop receipt (r17,
#: benchmarks/serving_bench.py): peak admitted requests/sec among RPS-ramp
#: stages whose admitted p99 stayed within the SLO budget — throughput
#: that was actually served within latency, not offered load.
SERVING_METRIC = "serving_admitted_rps"

#: The contract metric of a resume receipt (r18,
#: benchmarks/resume_bench.py): batches REPLAYED by a kill-at-window-k /
#: resume cycle. The position-exact contract is value == 0 — enforced by
#: the artifact schema (an exact-mode row with replayed batches fails
#: validation), not by a pin floor: zero is a correctness claim, not a
#: rate to band.
RESUME_METRIC = "resume_replayed_batches"

#: The contract metric of an elastic-resize receipt (r19,
#: benchmarks/elastic_bench.py): seconds of downtime between the
#: preemption consensus firing and the first training step executing on
#: the survivor mesh. Schema-gated like the resume chain (the elastic_bench
#: row must replay zero batches AND beat the restart-from-checkpoint
#: control by >= 3x — validate_elastic_row), never pin-gated: the claim is
#: a ratio against a same-box control, not a rate to band.
ELASTIC_METRIC = "elastic_resize_downtime_seconds"

TOLERANCE_FLOOR = 0.02
TOLERANCE_CAP = 0.06


def tolerance_band(spreads: Sequence[float]) -> float:
    """clamp(0.5·max(spread), floor, cap) — see the module docstring for
    why half a window spread bounds the best-of-N estimator's noise."""
    worst = max([float(s) for s in spreads if s is not None] or [0.0])
    return min(TOLERANCE_CAP, max(TOLERANCE_FLOOR, 0.5 * worst))


# ---------------------------------------------------------------------------
# Basis: the measured configuration a rate is only comparable within.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Basis:
    """What the window actually measured. `wire` folds the host dtype for
    host wires (host_f32/host_bf16 ARE the dtype contract); the u8 wire's
    recorded image_dtype only names the device-finish comparison column —
    host work is identical — so it is deliberately NOT part of the key
    (the committed r9 u8 rows say float32 where the r10 rows say bfloat16,
    same host pipeline).

    r13 adds `model` and `augment` so the zoo rows gate independently of
    the VGG-F line: a vgg16-labeled row compares against the vgg16 pin,
    and an augment-on row (host flips deleted — data/augment.py owns them)
    against the augment-on pin, never cross-wise. Defaults reproduce the
    pre-r13 basis for every committed artifact that predates the fields
    (unlabeled rows measured the flagship, flips-on-host).

    r14 adds `sharding` — the gradient-exchange basis
    (<dp|zero1|zero2>[_bucketed], train/step.py comm_meta) — so step-time
    receipts for the overlapped bucketed exchange gate per layout, never
    cross-wise (a zero2_bucketed step and a dp step are different
    machines). Host-decode rows never touch the exchange, so the pre-r14
    default "dp" keeps every committed artifact on its existing key.
    r21 grows the value set with `zero3[_bucketed]` (mesh.shard_params —
    the just-in-time param-gather step IS a different machine from
    zero2's trailing re-sync); the field itself and the pre-r14 default
    are unchanged, so every committed key stays where it is.

    r16 adds `ingest` — `local` | `service_<N>w` (the disaggregated
    data-service topology, data/ingest_service.py) — so N-worker scaling
    receipts gate independently of the single-host line: a 4-worker
    aggregate rate compared against a local-decode pin would gate on
    topology, not code. Rows carry it as `ingest_mode` (the row key
    `ingest` already names the r13 per-model descriptor dict); the
    pre-r16 default `local` keeps every committed receipt on its key.

    r17 adds `serving` — `off` | `openloop_b<max_batch>` (the predict
    server's admission basis, serving/ + benchmarks/serving_bench.py; rows
    carry it as `serving_mode`) — so the open-loop RPS/latency receipts
    gate on their own chain (SERVING_PINS, SERVING_METRIC): an
    admitted-RPS number and a decode rate are different machines, and the
    admission geometry (bucket ladder) is part of what the number
    measured. The pre-r17 default `off` keeps every committed decode
    receipt on its existing key.

    r18 adds `resume` — `replay` | `exact` (the restart basis,
    data/iterator_state.py + benchmarks/resume_bench.py; rows carry it as
    `resume_mode`) — so the kill-and-resume receipts label which restart
    semantics a number was measured under. The pre-r18 default `replay`
    (the r17 behavior every committed receipt implicitly measured) keeps
    every existing key.

    r19 adds `topology` — `static` | `elastic_<N>to<M>` (the live-resize
    basis, parallel/elastic.py ResizePlan.topology_label; rows carry it as
    `topology`) — so a rate measured across an in-flight mesh shrink gates
    on its own key: a post-resize survivor mesh and a static mesh are
    different machines. The pre-r19 default `static` keeps every committed
    receipt on its existing key.

    r20 adds `tier` — `fp32` | `bf16` | `int8` | `student` (the serving
    ladder rung, serving/tiers.py; rows carry it as `tier`) — so each
    tier's admitted-RPS receipt gates against ITS OWN pin: an int8
    engine's number regressing to the fp32 pin's level is exactly the
    regression the tier exists to prevent, and it would be invisible on a
    shared key. The default `fp32` keeps every committed serving receipt
    (r17's pre-tier rows) on its existing key."""
    wire: str
    space_to_depth: bool
    source_kind: str
    source_hw: Tuple[int, int]
    restart_markers: bool
    model: str = "vggf"
    augment: bool = False
    sharding: str = "dp"
    ingest: str = "local"
    serving: str = "off"
    resume: str = "replay"
    topology: str = "static"
    tier: str = "fp32"

    def describe(self) -> dict:
        return {"wire": self.wire, "space_to_depth": self.space_to_depth,
                "source_kind": self.source_kind,
                "source_hw": list(self.source_hw),
                "restart_markers": self.restart_markers,
                "model": self.model, "augment": self.augment,
                "sharding": self.sharding, "ingest": self.ingest,
                "serving": self.serving, "resume": self.resume,
                "topology": self.topology, "tier": self.tier}


def row_basis(row: Mapping) -> Basis:
    """Basis of one decode-bench layout row. Pre-r7 artifacts carry no
    `source` (the protocol was fixed at 320x256 noise), pre-r8 ones no
    `wire` (the host dtype WAS the wire), and pre-r13 ones no `model` /
    `augment` (every row measured the flagship with host-owned flips)."""
    wire = row.get("wire")
    if wire is None:
        wire = ("host_bf16" if row.get("image_dtype") == "bfloat16"
                else "host_f32")
    src = row.get("source") or {}
    hw = tuple(src.get("source_hw") or (320, 256))
    interval = src.get("restart_interval")
    restart = (row.get("restart_kind") == "restart"
               and interval is not None and interval >= 0)
    aug = row.get("augment")
    return Basis(wire=wire, space_to_depth=bool(row.get("space_to_depth")),
                 source_kind=src.get("source_kind") or "noise",
                 source_hw=(int(hw[0]), int(hw[1])),
                 restart_markers=restart,
                 model=row.get("model") or "vggf",
                 augment=bool(isinstance(aug, Mapping)
                              and aug.get("enabled")),
                 sharding=row.get("sharding") or "dp",
                 ingest=row.get("ingest_mode") or "local",
                 serving=row.get("serving_mode") or "off",
                 resume=row.get("resume_mode") or "replay",
                 topology=row.get("topology") or "static",
                 tier=row.get("tier") or "fp32")


def artifact_contract_row(obj: Mapping) -> Optional[Mapping]:
    """The decode-bench row the top-level contract value is read against:
    the tfrecord layout when present (the frozen contract layout), else the
    first decode_bench row."""
    rows = [r for r in obj.get("layouts") or []
            if isinstance(r, Mapping) and r.get("mode") == "decode_bench"]
    if not rows:
        return None
    for r in rows:
        if r.get("layout") == "tfrecord":
            return r
    return rows[0]


def serving_contract_row(obj: Mapping) -> Optional[Mapping]:
    """The serving-bench row (r17) a SERVING_METRIC contract value is read
    against — the first (in practice only) serving_bench layout row."""
    for r in obj.get("layouts") or []:
        if isinstance(r, Mapping) and r.get("mode") == "serving_bench":
            return r
    return None


def resume_contract_row(obj: Mapping) -> Optional[Mapping]:
    """The resume-bench row (r18) a RESUME_METRIC value is read against —
    the EXACT-mode row (the contract row; the replay row is its control)."""
    rows = [r for r in obj.get("layouts") or []
            if isinstance(r, Mapping) and r.get("mode") == "resume_bench"]
    for r in rows:
        if r.get("resume_mode") == "exact":
            return r
    return rows[0] if rows else None


def elastic_contract_row(obj: Mapping) -> Optional[Mapping]:
    """The elastic-bench row (r19) an ELASTIC_METRIC value is read against
    — the first (in practice only) elastic_bench layout row."""
    for r in obj.get("layouts") or []:
        if isinstance(r, Mapping) and r.get("mode") == "elastic_bench":
            return r
    return None


# ---------------------------------------------------------------------------
# Pins: HOST_DECODE_RATE_R* with their committed provenance.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Pin:
    name: str                  # constant name in utils/scaling_model.py
    round: str                 # receipt round ("r9" = benchmarks round tag)
    run_dir: str               # repo-relative committed receipt directory
    provenance: Tuple[str, ...]  # the files the pin docstring cites
    basis: Basis
    #: False = trajectory row only, never gates a new artifact: the r5
    #: number was measured on a 1-vCPU host class that no longer exists
    #: (scaling_model docstring) — comparing this box against it would gate
    #: on hardware, not code.
    gating: bool = True
    #: Present when pin[n] < pin[n-1] on purpose: the committed receipt
    #: explaining the decrease (box drift with same-session controls).
    drift_note: Optional[str] = None


PINS: Tuple[Pin, ...] = (
    Pin("HOST_DECODE_RATE_R5", "r5", "benchmarks/runs/host_r5",
        ("host_pipeline_run1.json", "host_pipeline_run2.json"),
        Basis("host_f32", False, "noise", (320, 256), False),
        gating=False),
    Pin("HOST_DECODE_RATE_R6", "r6", "benchmarks/runs/host_r6",
        ("decode_simd_bf16s2d_run1.json", "decode_simd_bf16s2d_run2.json"),
        Basis("host_bf16", True, "noise", (320, 256), False)),
    Pin("HOST_DECODE_RATE_R7", "r7", "benchmarks/runs/host_r7",
        # runs 3/4 — the FINAL alternating drift-controlled pair the
        # constant's docstring cites; runs 1/2 were the pre-control warmup
        ("decode_r7_bf16s2d_320noise_run3.json",
         "decode_r7_bf16s2d_320noise_run4.json"),
        Basis("host_bf16", True, "noise", (320, 256), False),
        drift_note="host_r7/README.md: r7 ≡ r6 code within noise on this "
                   "config; the −3.9% step vs R6 is box drift, receipted "
                   "with same-session r6-code worktree controls "
                   "(989.3–1047.1)"),
    Pin("HOST_DECODE_RATE_R8", "r8", "benchmarks/runs/host_r9",
        ("decode_r8_u8_s2d_320noise_run1.json",
         "decode_r8_u8_s2d_320noise_run2.json"),
        Basis("u8", True, "noise", (320, 256), False)),
    Pin("HOST_DECODE_RATE_R9", "r9", "benchmarks/runs/host_r10",
        ("decode_r10_on_320noise_rst1_run1.json",
         "decode_r10_on_320noise_rst1_run2.json",
         "decode_r10_on_320noise_rst1_run3.json"),
        Basis("u8", True, "noise", (320, 256), True)),
    # r13 (feature round r10): the (model, augment) bases — zoo rows and
    # the augment-on flagship gate independently of the VGG-F
    # flips-on-host line. Each sits below HOST_DECODE_RATE_R9 because the
    # box drifted between sessions (host_r13/README.md: the SAME-session
    # augment receipt shows augment-on ≥ augment-off, and zoo host work
    # is identical to the flagship's by construction), so each carries
    # the drift note the monotone check requires.
    Pin("HOST_DECODE_RATE_R10_AUG", "r10", "benchmarks/runs/host_r13",
        ("decode_r13_augment_on_run1.json",
         "decode_r13_augment_on_run2.json"),
        Basis("u8", True, "noise", (320, 256), True, "vggf", True),
        drift_note="host_r13/README.md: new augment-on basis on a box "
                   "~9-14% below its r10-session windows; the same-session "
                   "alternating receipt (augment_overhead in run1) shows "
                   "augment-on 1209.1 vs off 1181.2 — no host cost, wire "
                   "bytes identical"),
    Pin("HOST_ZOO_RATE_R10_VGG16", "r10", "benchmarks/runs/host_r13",
        ("decode_r13_zoo_vgg16_run1.json",
         "decode_r13_zoo_vgg16_run2.json"),
        Basis("u8", False, "noise", (320, 256), True, "vgg16", False),
        drift_note="host_r13/README.md: new per-model basis (identical "
                   "host pipeline to the flagship u8 row, unpacked "
                   "descriptor) on a drifted box"),
    Pin("HOST_ZOO_RATE_R10_RESNET50", "r10", "benchmarks/runs/host_r13",
        ("decode_r13_zoo_resnet50_run1.json",
         "decode_r13_zoo_resnet50_run2.json"),
        Basis("u8", False, "noise", (320, 256), True, "resnet50", False),
        drift_note="host_r13/README.md: new per-model basis (identical "
                   "host pipeline to the flagship u8 row, unpacked "
                   "descriptor) on a drifted box"),
    Pin("HOST_ZOO_RATE_R10_VIT_S16", "r10", "benchmarks/runs/host_r13",
        ("decode_r13_zoo_vit_s16_run1.json",
         "decode_r13_zoo_vit_s16_run2.json"),
        Basis("u8", False, "noise", (320, 256), True, "vit_s16", False),
        drift_note="host_r13/README.md: new per-model basis (identical "
                   "host pipeline to the flagship u8 row, unpacked "
                   "descriptor) on a drifted box"),
)


#: The r17 serving chain — its own pin sequence with its own metric
#: (SERVING_METRIC): an admitted-RPS number must never sit in the decode
#: chain's monotone check (the two measure different machines). Same
#: committed convention: pin == LOWER of the provenance pair.
SERVING_PINS: Tuple[Pin, ...] = (
    Pin("SERVING_RPS_R14", "r14", "benchmarks/runs/host_r16",
        ("serving_openloop_run1.json", "serving_openloop_run2.json"),
        Basis("u8", False, "u8_payload", (128, 128), False, "vggf",
              serving="openloop_b8")),
    # The r18 tier ladder (benchmarks/runs/host_r23): trained weights on
    # the teacher task's native 32px geometry — where the FC heads
    # dominate (fc6_in=256), i.e. the paper's actual compute profile —
    # one pin per (vggf, tier). A new 32px basis, NOT comparable to the
    # 128px fresh-init R14 chain above; every pin carries the drift note
    # saying so.
    Pin("SERVING_RPS_R18_FP32", "r18", "benchmarks/runs/host_r23",
        ("serving_r18_tier_fp32_run1.json",
         "serving_r18_tier_fp32_run2.json"),
        Basis("u8", False, "u8_payload", (32, 32), False, "vggf",
              serving="openloop_b8"),
        drift_note="host_r23/README.md: new 32px trained-weights basis "
                   "(teacher-task geometry, FC-head-dominated) — not the "
                   "128px fresh-init R14 line"),
    Pin("SERVING_RPS_R18_BF16", "r18", "benchmarks/runs/host_r23",
        ("serving_r18_tier_bf16_run1.json",
         "serving_r18_tier_bf16_run2.json"),
        Basis("u8", False, "u8_payload", (32, 32), False, "vggf",
              serving="openloop_b8", tier="bf16"),
        drift_note="host_r23/README.md: bf16 is EMULATED on XLA:CPU "
                   "(measured within noise of fp32 at equal architecture "
                   "— no MXU to cash the narrower dtype); the tier's "
                   "latency claim is the queued MXU device row "
                   "(tpu_session_r18.sh), this pin guards the CPU "
                   "baseline only"),
    Pin("SERVING_RPS_R18_INT8", "r18", "benchmarks/runs/host_r23",
        ("serving_r18_tier_int8_run1.json",
         "serving_r18_tier_int8_run2.json"),
        Basis("u8", False, "u8_payload", (32, 32), False, "vggf",
              serving="openloop_b8", tier="int8"),
        drift_note="host_r23/README.md: own (vggf, int8) basis — "
                   "calibrated sub-LSB channel elision over the quantized "
                   "heads; strictly above the fp32 pin by the frontier "
                   "receipt"),
    Pin("SERVING_RPS_R18_STUDENT", "r18", "benchmarks/runs/host_r23",
        ("serving_r18_tier_student_run1.json",
         "serving_r18_tier_student_run2.json"),
        Basis("u8", False, "u8_payload", (32, 32), False, "vggf",
              serving="openloop_b8", tier="student"),
        drift_note="host_r23/README.md: own (vggf, student) basis — "
                   "half-width distilled vggf_student serving the "
                   "flagship route; strictly above the fp32 pin by the "
                   "frontier receipt"),
)


def pin_value(pin: Pin) -> float:
    """The constant's CURRENT value — read from utils/scaling_model.py (the
    single source), so the sentinel can never drift from what provisioning
    actually uses."""
    from distributed_vgg_f_tpu.utils import scaling_model
    return float(getattr(scaling_model, pin.name))


def gating_pin_for(basis: Basis,
                   pins: Sequence[Pin] = PINS) -> Optional[Pin]:
    """The NEWEST gating pin measured on this basis (later pins supersede
    earlier ones on the same basis — r7 supersedes r6 for bf16+s2d).
    `pins` selects the chain (decode PINS or SERVING_PINS — an artifact's
    metric decides which chain may gate it)."""
    match = None
    for pin in pins:
        if pin.gating and pin.basis == basis:
            match = pin
    return match


# ---------------------------------------------------------------------------
# Committed-artifact parsing.
# ---------------------------------------------------------------------------

def _read_json(path: str) -> Optional[Any]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _contract_value_from_jsonl(path: str) -> Optional[dict]:
    """Pre-r6 run logs (host_r4/r5) are JSONL: one line per pipeline plus
    the contract line carrying the frozen metric."""
    try:
        with open(path) as f:
            lines = [ln.strip() for ln in f if ln.strip()]
    except OSError:
        return None
    for ln in lines:
        try:
            obj = json.loads(ln)
        except ValueError:
            continue
        if isinstance(obj, dict) and obj.get("metric") == HOST_METRIC:
            return obj
    return None


def parse_host_artifact(path: str) -> Optional[dict]:
    """One committed host artifact → {path, value, spread, basis} or None
    when the file carries no contract value (READMEs, session scripts,
    telemetry-only receipts keep their value field — those pass through
    with basis from their layout rows when present)."""
    obj = _read_json(path)
    if obj is None:
        line = _contract_value_from_jsonl(path)
        if line is None:
            return None
        return {"path": path, "value": line.get("value"),
                "spread": line.get("spread"),
                "basis": Basis("host_f32", False, "noise", (320, 256),
                               False).describe(),
                "format": "contract_jsonl"}
    if not isinstance(obj, dict) or "metric" not in obj:
        return None
    if obj.get("metric") == SERVING_METRIC:
        # r17 serving receipt: the basis lives in its serving_bench row
        row = serving_contract_row(obj)
        return {"path": path, "value": obj.get("value"),
                "spread": row.get("spread") if row else None,
                "basis": row_basis(row).describe() if row else None,
                "format": "serving_bench"}
    if obj.get("metric") == RESUME_METRIC:
        # r18 resume receipt: value is REPLAYED BATCHES (0 by contract,
        # schema-enforced), never pin-gated — it rides the trajectory as
        # an unpinned round with the exact-mode row's basis
        row = resume_contract_row(obj)
        return {"path": path, "value": obj.get("value"),
                "spread": row.get("spread") if row else None,
                "basis": row_basis(row).describe() if row else None,
                "format": "resume_bench"}
    if obj.get("metric") == ELASTIC_METRIC:
        # r19 elastic receipt: value is resize DOWNTIME SECONDS; the
        # >=3x-vs-restart and zero-replay contracts are schema-enforced
        # (validate_elastic_row), never pin-gated — it rides the
        # trajectory as an unpinned round with the elastic row's basis
        row = elastic_contract_row(obj)
        return {"path": path, "value": obj.get("value"),
                "spread": row.get("spread") if row else None,
                "basis": row_basis(row).describe() if row else None,
                "format": "elastic_bench"}
    row = artifact_contract_row(obj)
    out = {"path": path, "value": obj.get("value"),
           "spread": row.get("spread") if row else None,
           "basis": row_basis(row).describe() if row else None,
           "format": "decode_bench"}
    if "telemetry_overhead" in obj:
        out["telemetry_overhead_pct"] = \
            obj["telemetry_overhead"].get("overhead_pct")
    if "exporter_overhead" in obj:
        out["exporter_overhead_pct"] = \
            obj["exporter_overhead"].get("overhead_pct")
    return out


def _round_sort_key(dirname: str):
    m = re.search(r"host_r(\d+)$", dirname)
    return int(m.group(1)) if m else 0


def build_trajectory(repo: str) -> dict:
    """Every committed host_r*/ artifact + BENCH_r*.json, one file. No
    timestamps on purpose: regeneration from the same tree is byte-stable,
    so `--check-committed` can diff the committed trajectory.json against a
    fresh build."""
    rounds: List[dict] = []
    by_dir: Dict[str, List[dict]] = {}
    for run_dir in sorted(glob.glob(os.path.join(
            repo, "benchmarks", "runs", "host_r*")), key=_round_sort_key):
        entries = []
        for path in sorted(glob.glob(os.path.join(run_dir, "*.json"))):
            parsed = parse_host_artifact(path)
            if parsed is not None:
                parsed["path"] = os.path.relpath(path, repo)
                entries.append(parsed)
        by_dir[os.path.relpath(run_dir, repo)] = entries
    def pin_round(pin: Pin) -> dict:
        entries = by_dir.get(pin.run_dir, [])
        prov_paths = {os.path.join(pin.run_dir, name)
                      for name in pin.provenance}
        spreads = []
        for e in entries:
            e_is_prov = e["path"] in prov_paths
            e["pin_provenance"] = e_is_prov
            if e_is_prov and e.get("spread") is not None:
                spreads.append(e["spread"])
        return {
            "round": pin.round, "pin": pin.name, "value": pin_value(pin),
            "gating": pin.gating, "basis": pin.basis.describe(),
            "tolerance": round(tolerance_band(spreads), 4),
            "drift_note": pin.drift_note,
            "run_dir": pin.run_dir,
            "artifacts": entries,
        }

    rounds = [pin_round(pin) for pin in PINS]
    # the r17 serving chain rides its own section: its metric and pin
    # sequence are disjoint from the decode chain's, but the artifact
    # parsing/provenance machinery is the same
    serving_rounds = [pin_round(pin) for pin in SERVING_PINS]
    # round dirs that back no pin (controls, telemetry receipts) still ride
    # the trajectory — receipts must be findable by machine, not only by
    # knowing which README cites them
    pinned_dirs = {p.run_dir for p in PINS} \
        | {p.run_dir for p in SERVING_PINS}
    extra = [{"round": os.path.basename(d).replace("host_", ""),
              "run_dir": d, "artifacts": entries}
             for d, entries in by_dir.items()
             if d not in pinned_dirs and entries]
    device = []
    for path in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        obj = _read_json(path)
        if not isinstance(obj, dict):
            continue
        parsed = obj.get("parsed") or {}
        device.append({
            "path": os.path.basename(path), "n": obj.get("n"),
            "metric": parsed.get("metric"), "value": parsed.get("value"),
            "error": parsed.get("error"),
            "last_committed": parsed.get("last_committed"),
        })
    return {"schema_version": schema.SCHEMA_VERSION,
            "kind": "perf_trajectory", "metric": HOST_METRIC,
            "serving_metric": SERVING_METRIC,
            "tolerance_rule": "clamp(0.5*max(provenance window spreads), "
                              f"{TOLERANCE_FLOOR}, {TOLERANCE_CAP}); "
                              "same-box bands — cross-session claims need "
                              "worktree controls (host_r7 README protocol)",
            "host_decode": rounds, "serving": serving_rounds,
            "unpinned_rounds": extra,
            "device": device}


# ---------------------------------------------------------------------------
# Checks.
# ---------------------------------------------------------------------------

def _check_pin_chain(repo: str, pins: Sequence[Pin],
                     errors: List[str]) -> None:
    """One pin chain's committed-consistency pass — the monotone check is
    PER CHAIN (decode rates and serving RPS are different machines; a
    cross-chain comparison would gate nothing meaningful)."""
    prev: Optional[Tuple[Pin, float]] = None
    for pin in pins:
        value = pin_value(pin)
        best_values = []
        for name in pin.provenance:
            path = os.path.join(repo, pin.run_dir, name)
            if not os.path.exists(path):
                errors.append(f"{pin.name}: provenance artifact missing: "
                              f"{pin.run_dir}/{name}")
                continue
            parsed = parse_host_artifact(path)
            if parsed is None or parsed.get("value") is None:
                errors.append(f"{pin.name}: {name} carries no contract "
                              "value")
                continue
            if parsed["format"] in ("decode_bench", "serving_bench"):
                ferrs = schema.validate_bench_artifact_file(path)
                if ferrs:
                    errors.append(f"{pin.name}: {name} fails artifact "
                                  f"schema: {ferrs[:2]}")
                if parsed.get("basis") != pin.basis.describe():
                    errors.append(
                        f"{pin.name}: {name} basis {parsed.get('basis')} "
                        f"!= pin basis {pin.basis.describe()} — the pin "
                        "cites a receipt that measured something else")
            best_values.append(float(parsed["value"]))
        if best_values:
            committed_min = min(best_values)
            # the committed convention: pin == LOWER of the provenance pair
            if abs(committed_min - value) > 0.01:
                errors.append(
                    f"{pin.name}={value} != min(provenance)="
                    f"{committed_min} — pin and receipts have drifted "
                    "apart (re-derive the constant or fix the provenance "
                    "list)")
        if prev is not None and pin.gating:
            prev_pin, prev_value = prev
            if value < prev_value and pin.drift_note is None:
                errors.append(
                    f"{pin.name}={value} < {prev_pin.name}={prev_value} "
                    "with NO drift receipt — a silent trajectory decrease "
                    "(add the controls receipt + drift_note, or fix the "
                    "regression)")
        if pin.gating or prev is None:
            prev = (pin, value)


def check_committed(repo: str) -> List[str]:
    """Consistency of pins vs committed receipts (tier-1). Returns error
    strings, [] = green."""
    errors: List[str] = []
    _check_pin_chain(repo, PINS, errors)
    _check_pin_chain(repo, SERVING_PINS, errors)
    return errors


def check_trajectory_file(repo: str,
                          path: Optional[str] = None) -> List[str]:
    """The committed trajectory.json must schema-validate AND match a fresh
    build from the committed receipts — a stale trajectory is a wrong map
    wearing a machine-readable label."""
    path = path or os.path.join(repo, "benchmarks", "runs",
                                "trajectory.json")
    if not os.path.exists(path):
        return [f"trajectory file missing: {os.path.relpath(path, repo)} "
                "(generate with benchmarks/regression_sentinel.py "
                "--write-trajectory)"]
    committed = _read_json(path)
    errors = schema.validate_trajectory(committed)
    if errors:
        return [f"trajectory: {e}" for e in errors]
    fresh = build_trajectory(repo)
    if committed != fresh:
        return ["trajectory.json is stale: a fresh build from the "
                "committed receipts differs — regenerate with "
                "benchmarks/regression_sentinel.py --write-trajectory"]
    return []


def check_artifact(obj_or_path, repo: str, *,
                   require_pin: bool = False) -> Tuple[List[str], dict]:
    """Gate one NEW --json-out artifact against the pinned trajectory.
    Returns (errors, report). `require_pin=True` makes an unmatched basis
    an error (CI wants 'this config is gated' to be a property of the
    invocation, not of whether someone remembered to pin it)."""
    if isinstance(obj_or_path, str):
        obj = _read_json(obj_or_path)
        if obj is None:
            return ([f"unreadable artifact: {obj_or_path}"], {})
        label = os.path.basename(obj_or_path)
    else:
        obj, label = obj_or_path, "<inline>"
    errors = [f"{label}: {e}" for e in schema.validate_bench_artifact(obj)]
    report: Dict[str, Any] = {"artifact": label}
    metric = obj.get("metric")
    if metric not in (HOST_METRIC, SERVING_METRIC, RESUME_METRIC,
                      ELASTIC_METRIC):
        errors.append(f"{label}: metric {metric!r} is not "
                      f"{HOST_METRIC!r}, {SERVING_METRIC!r}, "
                      f"{RESUME_METRIC!r} or {ELASTIC_METRIC!r}")
        return (errors, report)
    value = obj.get("value")
    if not isinstance(value, (int, float)):
        errors.append(f"{label}: no numeric contract value "
                      f"(error={obj.get('error')!r})")
        return (errors, report)
    if metric == RESUME_METRIC:
        # r18 resume receipts are SCHEMA-gated (the zero-replay contract
        # lives in validate_resume_row, already applied above), never
        # pin-gated — there is no rate to band, only a correctness claim.
        # The claim needs an EXACT-mode row to exist: a replay-only
        # artifact measured nothing position-exact and must not pass as
        # a resume receipt.
        row = resume_contract_row(obj)
        if row is None or row.get("resume_mode") != "exact":
            errors.append(f"{label}: no exact-mode resume_bench layout "
                          "row — the zero-replay contract was never "
                          "measured")
            return (errors, report)
        if value != row.get("replayed_batches"):
            errors.append(
                f"{label}: contract value {value} != the exact row's "
                f"replayed_batches {row.get('replayed_batches')} — the "
                "headline number must BE the measured one")
        report["basis"] = row_basis(row).describe()
        report["value"] = value
        report["pin"] = None
        report["note"] = (f"{label}: resume receipt — schema-gated "
                          "(exact mode must replay 0), not pin-gated")
        return (errors, report)
    if metric == ELASTIC_METRIC:
        # r19 elastic receipts are SCHEMA-gated (zero replay + the >=3x
        # speedup-vs-restart floor live in validate_elastic_row, already
        # applied above), never pin-gated: the claim is a same-box ratio
        # against the restart control, not a rate to band. The claim
        # needs an elastic_bench row to exist — a rowless artifact
        # measured nothing.
        row = elastic_contract_row(obj)
        if row is None:
            errors.append(f"{label}: no elastic_bench layout row — the "
                          "resize-vs-restart contract was never measured")
            return (errors, report)
        if value != row.get("downtime_seconds"):
            errors.append(
                f"{label}: contract value {value} != the elastic row's "
                f"downtime_seconds {row.get('downtime_seconds')} — the "
                "headline number must BE the measured one")
        report["basis"] = row_basis(row).describe()
        report["value"] = value
        report["pin"] = None
        report["note"] = (f"{label}: elastic receipt — schema-gated "
                          "(zero replay, >=3x vs restart), not pin-gated")
        return (errors, report)
    if metric == SERVING_METRIC:
        # the serving chain gates on its own pins; none of the decode
        # machinery below (autotune settled-state, decode rows) applies
        row = serving_contract_row(obj)
        if row is None:
            errors.append(f"{label}: no serving_bench layout row — "
                          "nothing to match a pin basis against")
            return (errors, report)
        serving_cfg = row.get("serving") or {}
        if serving_cfg.get("controller"):
            # the decode chain's refuse-to-gate-mid-autotune discipline:
            # a window the admission controller was steering mid-stage is
            # not a steady-state measurement of any one configuration
            report["controller"] = True
            errors.append(
                f"{label}: REFUSED — measured with the admission "
                "controller ON (row.serving.controller=true): the batch "
                "window was a moving knob, not a pinned basis. Re-run "
                "serving_bench without --controller to gate.")
            return (errors, report)
        return _gate_against_pin(repo, label, value, row_basis(row),
                                 SERVING_PINS, errors, report,
                                 require_pin=require_pin)
    row = artifact_contract_row(obj)
    if row is None:
        errors.append(f"{label}: no decode_bench layout row — nothing to "
                      "match a pin basis against")
        return (errors, report)
    # r11: an artifact measured while the ingest autotuner was still
    # actuating is not a steady-state number — its windows sample a moving
    # knob configuration, and a mid-convergence window would read as a
    # false regression (or mask a real one). The settled-state flag in the
    # artifact schema is the receipt; refuse to gate without it.
    at = obj.get("autotune")
    if isinstance(at, Mapping) and at.get("enabled"):
        report["autotune"] = {"enabled": True,
                              "settled": bool(at.get("settled")),
                              "actuations_total":
                                  at.get("actuations_total")}
        if not at.get("settled"):
            errors.append(
                f"{label}: REFUSED — the artifact's windows overlap "
                f"ingest-autotuner actuations (autotune.enabled=true, "
                f"settled=false, {at.get('actuations_total')} actuations): "
                f"a mid-convergence window is not a steady-state "
                f"measurement. Re-run after the controller settles, or "
                f"bench with --autotune off.")
            return (errors, report)
    return _gate_against_pin(repo, label, value, row_basis(row), PINS,
                             errors, report, require_pin=require_pin)


def _gate_against_pin(repo: str, label: str, value: float, basis: Basis,
                      pins: Sequence[Pin], errors: List[str],
                      report: Dict[str, Any], *,
                      require_pin: bool = False) -> Tuple[List[str], dict]:
    """The tolerance-band gate shared by the decode and serving chains —
    one floor policy, two pin sequences."""
    report["basis"] = basis.describe()
    report["value"] = value
    pin = gating_pin_for(basis, pins)
    if pin is None:
        report["pin"] = None
        msg = (f"{label}: no gating pin for basis {basis.describe()} — "
               "not gated")
        if require_pin:
            errors.append(msg)
        else:
            report["note"] = msg
        return (errors, report)
    pinned = pin_value(pin)
    spreads = []
    for name in pin.provenance:
        parsed = parse_host_artifact(os.path.join(repo, pin.run_dir, name))
        if parsed and parsed.get("spread") is not None:
            spreads.append(parsed["spread"])
    tol = tolerance_band(spreads)
    floor = pinned * (1.0 - tol)
    report.update({"pin": pin.name, "pin_value": pinned,
                   "tolerance": round(tol, 4),
                   "floor": round(floor, 2),
                   "vs_pin": round(value / pinned, 4)})
    if value < floor:
        errors.append(
            f"{label}: REGRESSION — {value:.2f} is "
            f"{(1 - value / pinned) * 100:.1f}% below {pin.name}="
            f"{pinned} (tolerance {tol * 100:.1f}%, floor {floor:.2f}). "
            f"If this box has drifted, re-measure with same-session "
            f"worktree controls (host_r7 README protocol) before "
            f"believing either number.")
    return (errors, report)
