"""Crash flight recorder — the "black box" half of the live observability
plane (ISSUE 8; the TF-system serving split, arXiv 1605.08695, assumes a
health/diagnosis surface that survives the process it observes).

The telemetry spine's end-of-run export (trainer fit-finally) answers "what
did the whole run look like"; this module answers the question an on-call
engineer actually has after a crash: **what were the last N windows doing**.
A bounded ring of per-log-window summaries — stall verdict, registry counter
deltas, span-category occupancy, wall seconds — is retained always-on (the
buffer costs a deque append per log window, nothing per step), and on a
diagnosed abort the whole ring plus the final registry state is dumped as a
single schema-validated JSON artifact (`telemetry/schema.py
validate_flight_record`).

Crash classes are NAMED, not guessed: the guards that raise them call
`note_crash(...)` first —

- `resilience/guard.py`  → ``nonfinite_abort`` (NonFiniteStepError),
- `data/prefetch.py`     → ``data_stall``      (DataStallError, both the
  watchdog-timeout and dead-worker sites),
- `resilience/faults.py` → ``injected_crash``  (InjectedFault),

and the trainer's fit exception path dumps with the freshest note (falling
back to ``unhandled_exception`` for anything that never announced itself).
The artifact also carries the config fingerprint and the native-decoder
ABI / metrics schema versions, so a black box can be matched to the exact
build + config that produced it without the run's logs.

Stdlib-only, like the rest of the package (the import-isolation test in
tests/test_telemetry.py covers this module too): anything jax-shaped
(process index, config dicts, ABI versions) is *passed in* by the trainer,
never imported from here.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Mapping, Optional

from distributed_vgg_f_tpu.telemetry import schema

#: The crash classes a black box can carry. "unhandled_exception" is the
#: residual for anything that never called note_crash.
CRASH_KINDS = ("nonfinite_abort", "data_stall", "injected_crash",
               "elastic_degraded_restart", "unhandled_exception")

#: A note older than this is stale: it belonged to a fault the run SURVIVED
#: (e.g. a DataStallError swallowed by a retry loop), and attributing a
#: later unrelated crash to it would be a wrong diagnosis wearing a
#: confident label.
NOTE_FRESH_S = 60.0

#: Autotune actuations retained for the black box (r11): enough to cover
#: any plausible convergence tail before an abort, small enough that the
#: artifact stays a single readable file.
ACTUATION_RING = 32


class FlightRecorder:
    """Bounded ring of per-window telemetry summaries + crash-note slot.

    One instance per process (module-level default below); thread-safe —
    windows are recorded from the trainer loop while notes may arrive from
    the prefetch worker thread.
    """

    def __init__(self, max_windows: int = 64):
        if max_windows < 1:
            raise ValueError(
                f"max_windows must be >= 1, got {max_windows}")
        self.max_windows = int(max_windows)
        self._lock = threading.Lock()
        self._windows: deque = deque(maxlen=self.max_windows)
        self._actuations: deque = deque(maxlen=ACTUATION_RING)
        self._note: Optional[dict] = None
        self._dumps = 0

    # ------------------------------------------------------------- recording
    def record_window(self, *, step: int, wall_s: float,
                      stall: Optional[Mapping] = None,
                      counters: Optional[Mapping] = None,
                      spans: Optional[Mapping] = None) -> None:
        """Append one log-window summary. `counters` are the window's
        registry DELTAS (not lifetime totals) and `spans` the per-category
        busy seconds — both already computed by the caller so the recorder
        itself stays arithmetic-free."""
        record: Dict[str, object] = {
            "step": int(step),
            "wall_s": round(float(wall_s), 4),
            "ts_unix": round(time.time(), 3),
        }
        if stall:
            record["stall"] = dict(stall)
        if counters:
            record["counters"] = dict(counters)
        if spans:
            record["spans"] = {k: round(float(v), 6)
                               for k, v in spans.items()}
        with self._lock:
            self._windows.append(record)

    def record_actuation(self, act: Mapping) -> None:
        """Retain one ingest-autotuner actuation (r11, data/autotune.py):
        a post-crash triage must be able to see whether the controller
        moved a knob just before the abort — "the autotuner shrank the
        decode pool and then the watchdog fired" is a diagnosis, "the run
        stalled" is a mystery."""
        with self._lock:
            self._actuations.append(dict(act))

    def actuations(self) -> List[dict]:
        """Copy of the retained actuation ring, oldest first."""
        with self._lock:
            return [dict(a) for a in self._actuations]

    def note_crash(self, kind: str, detail: str = "") -> None:
        """Announce an imminent diagnosed abort. Called by the guard that is
        ABOUT to raise — the dump that follows names the crash class from
        the freshest note instead of re-deriving it from exception types."""
        if kind not in CRASH_KINDS:
            raise ValueError(f"unknown crash kind {kind!r}; expected one of "
                             f"{CRASH_KINDS}")
        with self._lock:
            self._note = {"kind": kind, "detail": str(detail)[:2000],
                          "t_mono": time.monotonic()}

    # --------------------------------------------------------------- reading
    def windows(self) -> List[dict]:
        """Copy of the retained window summaries, oldest first (the /stallz
        endpoint's history payload)."""
        with self._lock:
            return [dict(w) for w in self._windows]

    def latest_stall(self) -> Optional[dict]:
        """The newest window that carried a stall verdict, or None."""
        with self._lock:
            for w in reversed(self._windows):
                if "stall" in w:
                    return dict(w)
        return None

    @property
    def dumps(self) -> int:
        with self._lock:
            return self._dumps

    def clear(self) -> None:
        with self._lock:
            self._windows.clear()
            self._actuations.clear()
            self._note = None
            self._dumps = 0

    def set_max_windows(self, max_windows: int) -> None:
        """Resize the ring (config → trainer), keeping the newest windows
        that fit — same contract as SpanRecorder.set_capacity."""
        if max_windows < 1:
            raise ValueError(
                f"max_windows must be >= 1, got {max_windows}")
        with self._lock:
            self.max_windows = int(max_windows)
            self._windows = deque(self._windows, maxlen=self.max_windows)

    # ---------------------------------------------------------------- dumping
    def _consume_note(self) -> Optional[dict]:
        with self._lock:
            note, self._note = self._note, None
        if note is None:
            return None
        if time.monotonic() - note["t_mono"] > NOTE_FRESH_S:
            return None  # survived fault, unrelated crash — don't mislabel
        return note

    def build_black_box(self, *, exc: Optional[BaseException] = None,
                        reason: Optional[str] = None,
                        process: int = 0,
                        config_fingerprint: str = "",
                        config_name: str = "",
                        versions: Optional[Mapping] = None,
                        registry=None, recorder=None) -> dict:
        """Assemble the black-box record (no I/O — `dump` writes it).

        `reason` overrides note/exception inference; otherwise the freshest
        `note_crash` wins, then the exception's class name is recorded
        verbatim under the ``unhandled_exception`` class."""
        note = self._consume_note()
        if reason is None:
            reason = note["kind"] if note else "unhandled_exception"
        record: Dict[str, object] = {
            "schema_version": schema.SCHEMA_VERSION,
            "kind": "flight_black_box",
            "reason": reason,
            "process": int(process),
            "ts_unix": round(time.time(), 3),
            "config_name": config_name,
            "config_fingerprint": config_fingerprint,
            "versions": dict(versions or {}),
            "windows": self.windows(),
        }
        if note and note.get("detail"):
            record["reason_detail"] = note["detail"]
        actuations = self.actuations()
        if actuations:
            record["autotune_actuations"] = actuations
        if exc is not None:
            record["exception"] = {"type": type(exc).__name__,
                                   "message": str(exc)[:4000]}
        if registry is not None:
            split = registry.snapshot_split()
            record["counters_final"] = split["counters"]
            record["gauges_final"] = split["gauges"]
        if recorder is not None:
            record["spans_recorded"] = recorder.recorded
            record["spans_dropped"] = recorder.dropped
        return record

    def dump(self, directory: str, **kwargs) -> str:
        """Write the black box as ``flight_p<process>.json`` under
        `directory` (atomic rename — a crash-during-the-crash-dump must
        never leave a torn artifact that poisons triage tooling). Returns
        the path. Raises only OSError-class failures; callers on the crash
        path swallow them (the dump must never mask the run exception)."""
        record = self.build_black_box(**kwargs)
        errors = schema.validate_flight_record(record)
        if errors:  # pragma: no cover — schema and builder ship together
            raise ValueError(f"flight record failed its own schema: "
                             f"{errors[:3]}")
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(
            directory, f"flight_p{int(record['process']):05d}.json")
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, allow_nan=False)
        os.replace(tmp, path)
        with self._lock:
            self._dumps += 1
        return path


# ---------------------------------------------------------------------------
# Process-wide default — the one the wired guards note into and the trainer
# dumps, so one black box shows the whole process picture.
# ---------------------------------------------------------------------------

_default = FlightRecorder()


def get_flight() -> FlightRecorder:
    return _default


def note_crash(kind: str, detail: str = "") -> None:
    _default.note_crash(kind, detail)
