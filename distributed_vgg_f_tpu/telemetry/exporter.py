"""Live observability endpoint — a config-gated background HTTP server per
process (ISSUE 8 tentpole; the TF-system serving split, arXiv 1605.08695,
assumes exactly this health/metrics surface, and the ROADMAP's autotuner and
predict-service levers both consume live signals the fit-finally export
cannot provide).

Four endpoints over the spine's existing state — the exporter OWNS no
metrics, it serves the registry/recorder/flight objects everything already
writes to:

- ``/metrics``  Prometheus text exposition (version 0.0.4) of the counter/
  gauge registry, pollers swept — what a Prometheus/Grafana scrape or a
  fleet health-checker consumes at its own cadence;
- ``/healthz``  JSON liveness: trainer heartbeat (last step + age), watchdog
  counters, uptime. HTTP 503 once the heartbeat is older than
  `stalled_after_s` — a load balancer or k8s probe needs the status IN the
  status code, not in a body it won't parse;
- ``/stallz``   the latest stall verdict plus the flight recorder's window
  history — "why is it slow" as one curl;
- ``/trace``    an on-demand Chrome-trace snapshot of the span ring (the
  same shape as the fit-finally export, but WHILE the run is alive);
- ``/autotunez`` the closed-loop ingest autotuner's live state (r11,
  data/autotune.py): knob values/rails, settled flag, and the actuation
  history — every controller decision auditable with one curl. The data
  layer REGISTERS a provider via `set_autotune_source(fn)` (never the
  reverse import — the telemetry import-isolation contract); with no
  controller registered the endpoint reports ``enabled: false``;
- ``/ingestz`` the disaggregated-ingest client's live state (r16,
  data/service_client.py): worker fleet topology, per-worker liveness and
  serve counts, failover/fallback state — registered the same provider
  way via `set_ingest_source(fn)`;
- ``/servingz`` the predict server's live admission state (r17,
  serving/server.py): per-model queue depth, bucket occupancy, shed rate,
  admission-window/controller receipts — registered the same provider way
  via `set_serving_source(fn)` (import isolation preserved: telemetry
  never imports the serving package).

Port contract: bind port 0 by default — the OS assigns a free port, the
bound port is returned from `start()`, logged by the trainer, and written to
the run sidecar (`exporter_p<rank>.jsonl`), so N processes per host (and N
hosts per job) never collide on a fixed port. A fixed `port` is for
single-process deployments that want a known scrape target.

Stdlib-only (http.server + threading), covered by the import-isolation
test. Server threads never touch jax; everything jax-shaped arrives via
`heartbeat(step)` calls from the trainer loop.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from distributed_vgg_f_tpu.telemetry.flight import get_flight
from distributed_vgg_f_tpu.telemetry.metric_help import help_for
from distributed_vgg_f_tpu.telemetry.registry import get_registry
from distributed_vgg_f_tpu.telemetry.spans import get_recorder

#: Prometheus metric names allow [a-zA-Z_:][a-zA-Z0-9_:]*; the registry's
#: `<subsystem>/<metric>` names (and histogram-bucket suffixes like
#: `decode/scale_histogram/4`) are flattened with `_`.
_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_PREFIX = "dvggf_"

#: Counters the /healthz watchdog block surfaces — the signals that say
#: "the input pipeline / guard layer is fighting" without a poller sweep.
_WATCHDOG_COUNTERS = ("prefetch/timeouts", "prefetch/dead_workers",
                      "resilience/data_stall_errors",
                      "resilience/nonfinite_skips",
                      "resilience/nonfinite_aborts")


# -- /autotunez provider -----------------------------------------------------
# The controller lives in the data layer; telemetry must not import it
# (import-isolation contract), so the live state arrives as a registered
# callable. Process-wide like the exporter singleton: one controller per
# process is the autotuner's own model.
_autotune_source = None
_autotune_lock = threading.Lock()


def set_autotune_source(fn) -> None:
    """Register (or clear, with None) the /autotunez payload provider —
    called by the trainer when it starts/stops an IngestAutotuner."""
    global _autotune_source
    with _autotune_lock:
        _autotune_source = fn


def autotune_payload() -> dict:
    with _autotune_lock:
        fn = _autotune_source
    if fn is None:
        return {"enabled": False,
                "reason": "no ingest autotuner registered in this process "
                          "(data.autotune.enabled off, DVGGF_AUTOTUNE=0, "
                          "or the run has not started)"}
    return fn()


# -- /ingestz provider -------------------------------------------------------
# Same import-isolation shape as /autotunez: the disaggregated-ingest
# client (data/service_client.py) lives in the data layer and REGISTERS a
# payload provider here — telemetry never imports it.
_ingest_source = None
_ingest_lock = threading.Lock()


def set_ingest_source(fn) -> None:
    """Register (or clear, with None) the /ingestz payload provider —
    called by the service client at construction/close."""
    global _ingest_source
    with _ingest_lock:
        _ingest_source = fn


def clear_ingest_source(fn) -> None:
    """Compare-and-clear under the lock: a closing client must only clear
    its OWN registration — a check-then-set across two lock acquisitions
    could sever a successor client's live registration."""
    global _ingest_source
    with _ingest_lock:
        if _ingest_source is fn:
            _ingest_source = None


def ingest_payload() -> dict:
    with _ingest_lock:
        fn = _ingest_source
    if fn is None:
        return {"enabled": False,
                "reason": "no disaggregated-ingest client in this process "
                          "(data.service.enabled off, or the run has not "
                          "started)"}
    return fn()


# -- /servingz provider ------------------------------------------------------
# Same import-isolation shape as /ingestz: the predict server
# (serving/server.py) lives outside telemetry and REGISTERS a payload
# provider here — telemetry never imports it.
_serving_source = None
_serving_lock = threading.Lock()


def set_serving_source(fn) -> None:
    """Register (or clear, with None) the /servingz payload provider —
    called by the predict server at start/close."""
    global _serving_source
    with _serving_lock:
        _serving_source = fn


def clear_serving_source(fn) -> None:
    """Compare-and-clear under the lock (the /ingestz contract): a closing
    server must only clear its OWN registration, never a successor's."""
    global _serving_source
    with _serving_lock:
        if _serving_source is fn:
            _serving_source = None


def serving_payload() -> dict:
    with _serving_lock:
        fn = _serving_source
    if fn is None:
        return {"enabled": False,
                "reason": "no predict server in this process "
                          "(serving.enabled off, or --mode serve not "
                          "running)"}
    return fn()


def prometheus_name(name: str) -> str:
    sanitized = _PROM_BAD.sub("_", name)
    if sanitized and sanitized[0].isdigit():
        sanitized = "_" + sanitized
    return _PROM_PREFIX + sanitized


def render_prometheus(registry) -> str:
    """Registry → Prometheus text format. Pollers ARE swept (this is the
    full-snapshot surface; /healthz is the cheap one). Counters get the
    `counter` TYPE and gauges `gauge`; every family carries a `# HELP`
    line from the shared namespace table (telemetry/metric_help.py — the
    same table the README drift lint cross-checks, exposition-format
    compliance a strict Prometheus parser wants). Name collisions after
    sanitization keep the first occurrence (and are effectively impossible
    under the `<subsystem>/<metric>` convention)."""
    split = registry.snapshot_split()
    lines = []
    seen = set()
    for type_name, family in (("counter", split["counters"]),
                              ("gauge", split["gauges"])):
        for name in sorted(family):
            value = family[name]
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                continue
            prom = prometheus_name(name)
            if prom in seen:
                continue
            seen.add(prom)
            lines.append(f"# HELP {prom} {help_for(name)}")
            lines.append(f"# TYPE {prom} {type_name}")
            # full precision, never '%g': a cumulative ns/bytes counter
            # past 1e6 would quantize, making Prometheus rate() read flat
            # runs punctuated by quantum jumps (ints stay exact, floats
            # round-trip via repr)
            lines.append(f"{prom} {value!r}"
                         if isinstance(value, float) else
                         f"{prom} {value}")
    lines.append("")
    return "\n".join(lines)


class TelemetryExporter:
    """One background HTTP server serving the process's telemetry state."""

    def __init__(self, registry=None, recorder=None, flight=None, *,
                 host: str = "127.0.0.1", port: int = 0,
                 stalled_after_s: float = 120.0, role: str = ""):
        self._registry = registry if registry is not None else get_registry()
        self._recorder = recorder if recorder is not None else get_recorder()
        self._flight = flight if flight is not None else get_flight()
        self._host = host
        self._requested_port = int(port)
        self._stalled_after_s = float(stalled_after_s)
        # process role ("trainer_rank0", "ingest_worker2", "serving") —
        # rides describe() into the discovery sidecar so the fleet
        # collector keys its registry by (role, ident) instead of
        # guessing from file names
        self.role = str(role or "")
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._started_mono = time.monotonic()
        self._start_unix: Optional[float] = None
        self._hb_lock = threading.Lock()
        self._last_step: Optional[int] = None
        self._last_step_mono: Optional[float] = None

    # ------------------------------------------------------------- lifecycle
    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    @property
    def running(self) -> bool:
        return self._server is not None

    def start(self) -> int:
        """Bind + serve in a daemon thread; returns the BOUND port (the
        only number that exists when the requested port was 0)."""
        if self._server is not None:
            return self.port
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            # the exporter must never chat on the training job's stderr
            def log_message(self, fmt, *args):  # noqa: D401, N802
                pass

            def do_GET(self):  # noqa: N802
                exporter._handle(self)

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._server.daemon_threads = True
        self._started_mono = time.monotonic()
        self._start_unix = time.time()
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="telemetry-exporter",
                                        daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # ------------------------------------------------------------- heartbeat
    def heartbeat(self, step: int) -> None:
        """Trainer-loop liveness tick (one lock + two stores per log window
        — NOT per step; the step loop must not pay a lock for the probe's
        benefit)."""
        with self._hb_lock:
            self._last_step = int(step)
            self._last_step_mono = time.monotonic()

    def health(self) -> tuple[int, dict]:
        """(http_status, payload) for /healthz. `idle` (200) before any
        heartbeat — a process that serves /metrics but has not stepped yet
        is starting up, not dead; `stalled` (503) once the heartbeat age
        crosses the threshold."""
        now = time.monotonic()
        with self._hb_lock:
            last_step = self._last_step
            last_mono = self._last_step_mono
        payload = {
            "status": "idle",
            "uptime_s": round(now - self._started_mono, 3),
            "last_step": last_step,
            "last_step_age_s": None,
            "stalled_after_s": self._stalled_after_s,
            "watchdog": {name: self._registry.counter_value(name, 0)
                         for name in _WATCHDOG_COUNTERS},
            "spans_recorded": self._recorder.recorded,
            "spans_dropped": self._recorder.dropped,
        }
        status = 200
        if last_mono is not None:
            age = now - last_mono
            payload["last_step_age_s"] = round(age, 3)
            if age > self._stalled_after_s:
                payload["status"] = "stalled"
                status = 503
            else:
                payload["status"] = "ok"
        return status, payload

    def describe(self) -> dict:
        """The sidecar/log record for this exporter (the port-discovery
        contract for multi-host scrapers). `role` + `start_unix` + `pid`
        let discovery tell a LIVE endpoint from a stale sidecar left by a
        previous run on a since-reused port (the misattribution bug the
        r22 collector fixes: port alone is not an identity)."""
        import os
        return {"host": self._host, "port": self.port, "pid": os.getpid(),
                "role": self.role,
                "start_unix": round(self._start_unix, 3)
                if self._start_unix is not None else None,
                "endpoints": ["/metrics", "/healthz", "/stallz", "/trace",
                              "/autotunez", "/ingestz", "/servingz"]}

    # -------------------------------------------------------------- handling
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        self._registry.inc("exporter/requests")
        try:
            path = req.path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/metrics":
                body = render_prometheus(self._registry).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = 200
            elif path == "/healthz":
                status, payload = self.health()
                body = json.dumps(payload, indent=1).encode()
                ctype = "application/json"
            elif path == "/stallz":
                payload = {"latest": self._flight.latest_stall(),
                           "history": self._flight.windows()}
                body = json.dumps(payload, indent=1).encode()
                ctype = "application/json"
                status = 200
            elif path == "/trace":
                trace = self._recorder.to_chrome_trace(
                    process_name="dvggf_live")
                body = json.dumps(trace).encode()
                ctype = "application/json"
                status = 200
            elif path == "/autotunez":
                body = json.dumps(autotune_payload(), indent=1).encode()
                ctype = "application/json"
                status = 200
            elif path == "/ingestz":
                body = json.dumps(ingest_payload(), indent=1).encode()
                ctype = "application/json"
                status = 200
            elif path == "/servingz":
                body = json.dumps(serving_payload(), indent=1).encode()
                ctype = "application/json"
                status = 200
            else:
                body = b'{"error": "not found", "endpoints": ' \
                       b'["/metrics", "/healthz", "/stallz", "/trace", ' \
                       b'"/autotunez", "/ingestz", "/servingz"]}'
                ctype = "application/json"
                status = 404
        except Exception as e:  # noqa: BLE001 — a probe must never kill
            self._registry.inc("exporter/errors")
            body = json.dumps({"error": repr(e)}).encode()
            ctype = "application/json"
            status = 500
        try:
            req.send_response(status)
            req.send_header("Content-Type", ctype)
            req.send_header("Content-Length", str(len(body)))
            req.end_headers()
            req.wfile.write(body)
        except (BrokenPipeError, ConnectionError):
            pass  # scraper hung up mid-response — its problem, not ours


# ---------------------------------------------------------------------------
# Process-wide singleton: many Trainers in one process (the test suite, a
# train+eval driver) must share ONE bound port, not race N binds.
# ---------------------------------------------------------------------------

_default: Optional[TelemetryExporter] = None
_default_lock = threading.Lock()


def ensure_started(*, host: str = "127.0.0.1", port: int = 0,
                   stalled_after_s: float = 120.0,
                   role: str = "") -> TelemetryExporter:
    """Start (or return the already-running) process-wide exporter. A
    second caller's host/port is ignored by design — the first bind is THE
    process's observability address, already logged and sidecar'd. A
    `role` passed to a later call fills in a still-empty role (the first
    caller with an identity names the process), never overwrites one."""
    global _default
    with _default_lock:
        if _default is None or not _default.running:
            exp = TelemetryExporter(host=host, port=port,
                                    stalled_after_s=stalled_after_s,
                                    role=role)
            exp.start()
            _default = exp
        elif role and not _default.role:
            _default.role = str(role)
        return _default


def get_exporter() -> Optional[TelemetryExporter]:
    with _default_lock:
        return _default


def stop_exporter() -> None:
    """Tests / clean shutdown: stop and forget the process-wide exporter."""
    global _default
    with _default_lock:
        exp, _default = _default, None
    if exp is not None:
        exp.stop()
