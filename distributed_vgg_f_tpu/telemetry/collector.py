"""Fleet metrics collector — the cross-process read path over the
per-process observability plane (r22; ROADMAP item 5's input side).

Every exporter endpoint in the job (trainer ranks, disaggregated ingest
workers, the serving process) is strictly per-process: each serves ITS
/metrics, /stallz, /healthz. Nobody can answer "why is step time up?"
when the cause is one slow decode worker three sockets away. This module
is the one process that can: it discovers the fleet's endpoints, scrapes
them on an interval, merges the results into one registry keyed by
(role, ident), computes a FLEET stall verdict (quorum over the
per-process verdicts, minority ranks named), appends a schema-validated
fleet JSONL log, and serves the merged view back out:

- ``/fleetz``   the full fleet state as JSON — per-process status
  (live/stale + age), verdicts, the quorum verdict with stragglers named,
  scrape health — "why is the FLEET slow", as one curl;
- ``/metrics``  ONE Prometheus exposition covering every process: each
  scraped family re-emitted with ``{role="...",ident="..."}`` labels
  (HELP/TYPE carried through from the per-process exposition — the same
  telemetry/metric_help.py table), plus the collector's own ``fleet/*``
  and ``collector/*`` families. One scrape target for the whole job;
- ``/healthz``  collector liveness (cycle count + age).

Discovery is two-source: the ``exporter_p<rank>.jsonl`` sidecars the
trainer already writes (``telemetry.sidecar_dir``) plus a static endpoint
list (``role[N]@host:port`` entries) for processes outside the sidecar
dir (a serving box, another host). Sidecar records carry pid + role +
start time (r22): a sidecar whose pid is dead is a leftover from a
previous run and is FILTERED — scraping a since-reused port would
misattribute some other process's metrics to the dead rank.

Degradation contract: a dead, hanging, or garbage endpoint becomes a
``stale`` entry with its age — ``collector/scrape_errors`` moves, the
fleet verdict is computed from the survivors, and the collector NEVER
exits on a scrape fault (the never-crash discipline every probe surface
in this repo follows).

Stdlib-only (urllib + http.server + threading), covered by the
telemetry import-isolation lint/test. Own CLI entrypoint:

    python -m distributed_vgg_f_tpu.telemetry.collector \
        --sidecar-dir /ckpts/telemetry --endpoint serving@10.0.0.7:9100 \
        --port 9090 --fleet-log /ckpts/telemetry/fleet.jsonl
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from distributed_vgg_f_tpu.telemetry.exporter import prometheus_name
from distributed_vgg_f_tpu.telemetry.metric_help import help_for
from distributed_vgg_f_tpu.telemetry.registry import TelemetryRegistry
from distributed_vgg_f_tpu.telemetry.schema import SCHEMA_VERSION
from distributed_vgg_f_tpu.telemetry.stall import VERDICTS

#: Static endpoint spec: `host:port`, `role@host:port`, `role[N]@host:port`.
_ENDPOINT_RE = re.compile(
    r"^(?:(?P<role>[a-zA-Z_][a-zA-Z0-9_]*)(?:\[(?P<ident>\d+)\])?@)?"
    r"(?P<host>[^:@\s]+):(?P<port>\d{1,5})$")

#: Hosts a pid-liveness probe is meaningful on (the sidecar writer and the
#: collector share a kernel). Remote sidecar hosts skip the probe — their
#: staleness is decided by the scrape itself.
_LOCAL_HOSTS = ("127.0.0.1", "localhost", "::1")


class EndpointSpec:
    """One discovered scrape target. `(role, ident)` is the fleet-registry
    key — the identity, where host:port is only the current address."""

    __slots__ = ("role", "ident", "host", "port", "source", "pid",
                 "start_unix")

    def __init__(self, *, role: str, ident: int, host: str, port: int,
                 source: str, pid: Optional[int] = None,
                 start_unix: Optional[float] = None):
        self.role = str(role)
        self.ident = int(ident)
        self.host = str(host)
        self.port = int(port)
        self.source = str(source)      # "sidecar" | "static"
        self.pid = pid
        self.start_unix = start_unix

    @property
    def key(self) -> Tuple[str, int]:
        return (self.role, self.ident)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


def parse_static_endpoint(spec: str, default_ident: int = 0) -> EndpointSpec:
    """`role[N]@host:port` → EndpointSpec (role defaults to "proc", N to
    the position in the static list). Raises ValueError on garbage — a
    typo'd static endpoint should fail the CLI loudly, not scrape air."""
    m = _ENDPOINT_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad endpoint {spec!r} — expected host:port, role@host:port, "
            f"or role[N]@host:port")
    port = int(m.group("port"))
    if not 0 < port <= 65535:
        raise ValueError(f"bad endpoint {spec!r} — port out of range")
    ident = m.group("ident")
    return EndpointSpec(
        role=m.group("role") or "proc",
        ident=int(ident) if ident is not None else default_ident,
        host=m.group("host"), port=port, source="static")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False
    return True


def discover_sidecar_endpoints(sidecar_dir: str,
                               registry=None) -> List[EndpointSpec]:
    """Parse every `exporter_p<rank>.jsonl` sidecar: the LAST
    `telemetry_exporter` record per file names the rank's current
    endpoint (files are append-mode across restarts, so the last record
    is the newest incarnation). Local-host records whose pid is dead are
    stale leftovers of a previous run — filtered, counted
    (`collector/stale_sidecars`), never scraped: the port may have been
    reused by an unrelated process and a scrape would MISATTRIBUTE its
    metrics to the dead rank."""
    out: List[EndpointSpec] = []
    if not sidecar_dir:
        return out
    for path in sorted(glob.glob(
            os.path.join(sidecar_dir, "exporter_p*.jsonl"))):
        last = None
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue  # torn tail write
                    if rec.get("event") == "telemetry_exporter":
                        last = rec
        except OSError:
            continue
        if last is None or not isinstance(last.get("port"), int):
            continue
        host = str(last.get("host") or "127.0.0.1")
        pid = last.get("pid")
        if isinstance(pid, int) and host in _LOCAL_HOSTS \
                and not _pid_alive(pid):
            if registry is not None:
                registry.inc("collector/stale_sidecars")
            continue
        try:
            rank = int(os.path.basename(path)[len("exporter_p"):-6])
        except ValueError:
            rank = int(last.get("process", 0) or 0)
        role = str(last.get("role") or "") or f"rank{rank}"
        out.append(EndpointSpec(
            role=role, ident=rank, host=host, port=int(last["port"]),
            source="sidecar", pid=pid if isinstance(pid, int) else None,
            start_unix=last.get("start_unix")))
    return out


# ------------------------------------------------------------------ scraping

def parse_prometheus_text(text: str) -> Tuple[Dict[str, float],
                                              Dict[str, Tuple[str, str]]]:
    """Prometheus exposition → ({sample name: value}, {family: (help,
    type)}). The HELP/TYPE meta rides through to the aggregate exposition
    so the fleet /metrics stays sourced from the ONE help table the
    per-process exporters rendered from."""
    samples: Dict[str, float] = {}
    meta: Dict[str, Tuple[str, str]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) == 4:
                name = parts[2]
                meta[name] = (parts[3], meta.get(name, ("", ""))[1])
            continue
        if line.startswith("# TYPE "):
            parts = line.split(None, 3)
            if len(parts) == 4:
                name = parts[2]
                meta[name] = (meta.get(name, ("", ""))[0], parts[3])
            continue
        if line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) != 2:
            continue
        try:
            samples[parts[0]] = float(parts[1])
        except ValueError:
            continue
    return samples, meta


def fleet_verdict(verdicts: Dict[Tuple[str, int], str]) -> dict:
    """The quorum rule: the fleet's verdict is the MAJORITY per-process
    verdict over the live entries; ties break by severity (the VERDICTS
    order — guard_stalled outranks checkpoint outranks infeed outranks
    compute, the same priority stall.classify uses). The minority entries
    are the named stragglers: "infeed_bound because workers {2} are the
    stragglers" is a diagnosis, "the fleet is slow" is a mystery."""
    if not verdicts:
        return {"verdict": None, "quorum": 0, "of": 0, "stragglers": {},
                "detail": "no live processes"}

    def severity(v: str) -> int:
        return VERDICTS.index(v) if v in VERDICTS else len(VERDICTS)

    counts: Dict[str, int] = {}
    for v in verdicts.values():
        counts[v] = counts.get(v, 0) + 1
    winner = min(counts, key=lambda v: (-counts[v], severity(v)))
    stragglers = {f"{role}[{ident}]": v
                  for (role, ident), v in sorted(verdicts.items())
                  if v != winner}
    detail = f"{winner} by quorum {counts[winner]}/{len(verdicts)}"
    if stragglers:
        names = ", ".join(sorted(stragglers))
        detail += f" — {names} are the stragglers"
    return {"verdict": winner, "quorum": counts[winner],
            "of": len(verdicts), "stragglers": stragglers,
            "detail": detail}


class FleetCollector:
    """The collector process: discovery + scrape loop + merged registry +
    /fleetz + aggregated /metrics. Never crashes on a scrape fault."""

    def __init__(self, *, sidecar_dir: str = "",
                 endpoints: Sequence[str] = (),
                 interval_s: float = 1.0,
                 stale_after_s: float = 10.0,
                 scrape_timeout_s: float = 2.0,
                 fleet_log: str = "", max_cycles: int = 0,
                 host: str = "127.0.0.1", port: int = 0):
        self.sidecar_dir = str(sidecar_dir or "")
        self.static_endpoints = [
            parse_static_endpoint(s, default_ident=i)
            for i, s in enumerate(endpoints)]
        self.interval_s = max(0.01, float(interval_s))
        self.stale_after_s = max(0.0, float(stale_after_s))
        self.scrape_timeout_s = max(0.05, float(scrape_timeout_s))
        self.fleet_log = str(fleet_log or "")
        # 0 = run forever; N = the scrape loop stops itself after exactly
        # N cycles (the --cycles CLI contract: N fleet JSONL lines, not a
        # racy N-or-N+1 depending on shutdown timing)
        self.max_cycles = max(0, int(max_cycles))
        self._host = host
        self._requested_port = int(port)
        # the collector's OWN registry (collector/* + fleet/*) — a private
        # instance, not the process-global one: an in-process collector
        # (trainer rank 0, the bench) must not fold its bookkeeping into
        # the per-process registry it is itself scraping
        self.registry = TelemetryRegistry()
        for name in ("collector/scrapes", "collector/scrape_errors",
                     "collector/stale_sidecars", "fleet/windows"):
            self.registry.counter(name)
        for name in ("collector/endpoints", "collector/stale_endpoints",
                     "fleet/live_processes", "fleet/stragglers"):
            self.registry.set_gauge(name, 0)
        self._lock = threading.Lock()
        # (role, ident) → entry dict; survives endpoint death as `stale`
        self._entries: Dict[Tuple[str, int], dict] = {}
        self._fleet: dict = fleet_verdict({})
        self._cycles = 0
        self._last_cycle_mono: Optional[float] = None
        self._started_mono = time.monotonic()
        self._closed = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._server: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ lifecycle
    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    def start(self) -> int:
        """Bind the fleet HTTP surface + start the scrape loop; returns
        the BOUND port (the repo's port-0 contract)."""
        if self._server is not None:
            return self.port
        collector = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_GET(self):  # noqa: N802
                collector._handle(self)

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._server.daemon_threads = True
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, name="fleet-collector-http",
            daemon=True)
        self._serve_thread.start()
        self._loop_thread = threading.Thread(
            target=self._loop, name="fleet-collector-scrape", daemon=True)
        self._loop_thread.start()
        return self.port

    def close(self) -> None:
        self._closed.set()
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        for t in (self._serve_thread, self._loop_thread):
            if t is not None:
                t.join(timeout=5)
        self._serve_thread = self._loop_thread = None

    def describe(self) -> dict:
        return {"host": self._host, "port": self.port, "pid": os.getpid(),
                "interval_s": self.interval_s,
                "sidecar_dir": self.sidecar_dir,
                "static_endpoints": [e.address
                                     for e in self.static_endpoints],
                "fleet_log": self.fleet_log,
                "endpoints": ["/fleetz", "/metrics", "/healthz"]}

    # ------------------------------------------------------------ the loop
    def _loop(self) -> None:
        while not self._closed.is_set():
            t0 = time.monotonic()
            try:
                self.collect_once()
            except Exception:  # noqa: BLE001 — the loop NEVER dies
                self.registry.inc("collector/scrape_errors")
            if self.max_cycles and self._cycles >= self.max_cycles:
                return
            delay = self.interval_s - (time.monotonic() - t0)
            if delay > 0:
                self._closed.wait(delay)

    def discover(self) -> List[EndpointSpec]:
        """Current scrape targets: sidecar discovery (pid-liveness
        filtered) merged over the static list; on a (role, ident) key
        collision the sidecar wins — it is the fresher record."""
        merged: Dict[Tuple[str, int], EndpointSpec] = {}
        for ep in self.static_endpoints:
            merged[ep.key] = ep
        for ep in discover_sidecar_endpoints(self.sidecar_dir,
                                             self.registry):
            merged[ep.key] = ep
        return [merged[k] for k in sorted(merged)]

    def _scrape(self, ep: EndpointSpec) -> dict:
        """One endpoint's /metrics + /stallz + /healthz. Raises on any
        transport/parse fault — collect_once turns that into a stale
        entry."""
        base = f"http://{ep.host}:{ep.port}"
        with urllib.request.urlopen(base + "/metrics",
                                    timeout=self.scrape_timeout_s) as r:
            samples, meta = parse_prometheus_text(
                r.read().decode("utf-8", "replace"))
        with urllib.request.urlopen(base + "/stallz",
                                    timeout=self.scrape_timeout_s) as r:
            stallz = json.loads(r.read().decode("utf-8", "replace"))
        try:
            # /healthz legitimately answers 503 when the process is
            # stalled — that is a PAYLOAD, not a scrape fault
            with urllib.request.urlopen(
                    base + "/healthz", timeout=self.scrape_timeout_s) as r:
                healthz = json.loads(r.read().decode("utf-8", "replace"))
        except urllib.error.HTTPError as e:
            healthz = json.loads(e.read().decode("utf-8", "replace"))
        if not isinstance(stallz, dict) or not isinstance(healthz, dict):
            raise ValueError("endpoint returned non-object JSON")
        # /stallz "latest" is a whole flight WINDOW record; the verdict
        # sits nested in its "stall" block (flight.latest_stall shape)
        latest = stallz.get("latest") or {}
        stall = latest.get("stall") if isinstance(latest, dict) else None
        if not isinstance(stall, dict):
            stall = {}
        windows = stallz.get("history") or []
        return {"samples": samples, "meta": meta,
                "verdict": stall.get("verdict")
                if isinstance(stall.get("verdict"), str) else None,
                "stall": stall,
                "health": healthz.get("status"),
                "last_step": healthz.get("last_step"),
                "windows": len(windows)
                if isinstance(windows, list) else 0}

    def collect_once(self) -> dict:
        """One full cycle: discover → scrape every endpoint → merge →
        fleet verdict → fleet JSONL append. Returns the cycle's fleet
        record (the JSONL line as a dict). Scrape faults degrade the
        entry to `stale` with age; they never propagate."""
        endpoints = self.discover()
        now_mono = time.monotonic()
        now_unix = time.time()
        live = 0
        for ep in endpoints:
            self.registry.inc("collector/scrapes")
            try:
                scraped = self._scrape(ep)
            except Exception as e:  # noqa: BLE001 — degrade, never die
                self.registry.inc("collector/scrape_errors")
                with self._lock:
                    entry = self._entries.get(ep.key)
                    if entry is None:
                        entry = {"role": ep.role, "ident": ep.ident,
                                 "endpoint": ep.address,
                                 "source": ep.source,
                                 "verdict": None, "samples": {},
                                 "meta": {}, "last_scrape_mono": None,
                                 "last_scrape_unix": None}
                        self._entries[ep.key] = entry
                    entry["status"] = "stale"
                    entry["endpoint"] = ep.address
                    entry["error"] = repr(e)
                    last = entry.get("last_scrape_mono")
                    entry["age_s"] = round(now_mono - last, 3) \
                        if last is not None else None
                continue
            live += 1
            with self._lock:
                self._entries[ep.key] = {
                    "role": ep.role, "ident": ep.ident,
                    "endpoint": ep.address, "source": ep.source,
                    "status": "live", "age_s": 0.0, "error": None,
                    "verdict": scraped["verdict"],
                    "stall": scraped["stall"],
                    "health": scraped["health"],
                    "last_step": scraped["last_step"],
                    "flight_windows": scraped["windows"],
                    "samples": scraped["samples"],
                    "meta": scraped["meta"],
                    "last_scrape_mono": now_mono,
                    "last_scrape_unix": now_unix,
                }
        with self._lock:
            # entries for endpoints that vanished from discovery decay to
            # stale too — a dead worker's sidecar filter removes the
            # TARGET, but its last-known entry must stay visible with age
            for key, entry in self._entries.items():
                last = entry.get("last_scrape_mono")
                if last is None:
                    continue
                age = now_mono - last
                if age > max(self.stale_after_s, self.interval_s):
                    entry["status"] = "stale"
                entry["age_s"] = round(age, 3)
            verdicts = {key: entry["verdict"]
                        for key, entry in self._entries.items()
                        if entry["status"] == "live"
                        and isinstance(entry["verdict"], str)}
            self._fleet = fleet_verdict(verdicts)
            stale = sum(1 for e in self._entries.values()
                        if e["status"] == "stale")
            self._cycles += 1
            self._last_cycle_mono = now_mono
            record = self._fleet_record(now_unix)
        self.registry.inc("fleet/windows")
        self.registry.set_gauge("collector/endpoints", len(endpoints))
        self.registry.set_gauge("collector/stale_endpoints", stale)
        self.registry.set_gauge("fleet/live_processes", live)
        self.registry.set_gauge("fleet/stragglers",
                                len(self._fleet.get("stragglers") or {}))
        if self.fleet_log:
            try:
                os.makedirs(os.path.dirname(
                    os.path.abspath(self.fleet_log)), exist_ok=True)
                with open(self.fleet_log, "a", buffering=1) as f:
                    f.write(json.dumps(record, allow_nan=False) + "\n")
            except (OSError, ValueError):
                self.registry.inc("collector/scrape_errors")
        return record

    def _fleet_record(self, now_unix: float) -> dict:
        """The fleet JSONL line (schema.validate_fleet_record shape).
        Caller holds the lock."""
        return {
            "event": "fleet_window",
            "schema_version": SCHEMA_VERSION,
            "t_unix": round(now_unix, 3),
            "cycle": self._cycles,
            "fleet": dict(self._fleet),
            "processes": [
                {"role": e["role"], "ident": e["ident"],
                 "endpoint": e["endpoint"], "status": e["status"],
                 "verdict": e["verdict"], "age_s": e["age_s"],
                 "health": e.get("health"),
                 "last_step": e.get("last_step")}
                for _, e in sorted(self._entries.items())],
        }

    # ------------------------------------------------------------- serving
    def fleetz_payload(self) -> dict:
        with self._lock:
            age = None
            if self._last_cycle_mono is not None:
                age = round(time.monotonic() - self._last_cycle_mono, 3)
            return {
                "cycles": self._cycles,
                "cycle_age_s": age,
                "interval_s": self.interval_s,
                "uptime_s": round(
                    time.monotonic() - self._started_mono, 3),
                "fleet": dict(self._fleet),
                "scrapes": self.registry.counter_value(
                    "collector/scrapes", 0),
                "scrape_errors": self.registry.counter_value(
                    "collector/scrape_errors", 0),
                "processes": [
                    {k: v for k, v in e.items()
                     if k not in ("samples", "meta", "last_scrape_mono")}
                    for _, e in sorted(self._entries.items())],
            }

    def render_fleet_metrics(self) -> str:
        """The aggregate Prometheus exposition: the collector's own
        families first (HELP/TYPE from the shared table), then one
        `fleet_process_up` row per known process, then every LIVE
        process's scraped samples re-emitted with {role,ident} labels —
        HELP/TYPE carried through from the per-process exposition, each
        family's meta emitted once. Stale entries contribute only their
        `up 0` row: re-emitting a dead process's last samples would
        misread as fresh."""
        lines: List[str] = []
        split = self.registry.snapshot_split()
        for type_name, family in (("counter", split["counters"]),
                                  ("gauge", split["gauges"])):
            for name in sorted(family):
                prom = prometheus_name(name)
                lines.append(f"# HELP {prom} {help_for(name)}")
                lines.append(f"# TYPE {prom} {type_name}")
                value = family[name]
                lines.append(f"{prom} {value!r}"
                             if isinstance(value, float) else
                             f"{prom} {value}")
        with self._lock:
            entries = [dict(e) for _, e in sorted(self._entries.items())]
        up = prometheus_name("fleet/process_up")
        lines.append(f"# HELP {up} {help_for('fleet/process_up')}")
        lines.append(f"# TYPE {up} gauge")
        for e in entries:
            lines.append(
                f'{up}{{role="{e["role"]}",ident="{e["ident"]}"}} '
                f'{1 if e["status"] == "live" else 0}')
        seen_meta: set = set()
        for e in entries:
            if e["status"] != "live":
                continue
            label = f'{{role="{e["role"]}",ident="{e["ident"]}"}}'
            meta = e.get("meta") or {}
            for name in sorted(e.get("samples") or {}):
                if name not in seen_meta and name in meta:
                    hlp, typ = meta[name]
                    if hlp:
                        lines.append(f"# HELP {name} {hlp}")
                    if typ:
                        lines.append(f"# TYPE {name} {typ}")
                    seen_meta.add(name)
                value = e["samples"][name]
                lines.append(f"{name}{label} {value!r}"
                             if isinstance(value, float)
                             and not value.is_integer()
                             else f"{name}{label} {int(value)}")
        lines.append("")
        return "\n".join(lines)

    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        try:
            path = req.path.split("?", 1)[0].rstrip("/") or "/"
            if path == "/fleetz":
                body = json.dumps(self.fleetz_payload(), indent=1).encode()
                ctype, status = "application/json", 200
            elif path == "/metrics":
                body = self.render_fleet_metrics().encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
                status = 200
            elif path == "/healthz":
                with self._lock:
                    age = None
                    if self._last_cycle_mono is not None:
                        age = round(
                            time.monotonic() - self._last_cycle_mono, 3)
                    payload = {"status": "ok" if self._cycles else "idle",
                               "cycles": self._cycles,
                               "cycle_age_s": age}
                body = json.dumps(payload, indent=1).encode()
                ctype, status = "application/json", 200
            else:
                body = (b'{"error": "not found", "endpoints": '
                        b'["/fleetz", "/metrics", "/healthz"]}')
                ctype, status = "application/json", 404
        except Exception as e:  # noqa: BLE001 — a probe must never kill
            self.registry.inc("collector/scrape_errors")
            body = json.dumps({"error": repr(e)}).encode()
            ctype, status = "application/json", 500
        try:
            req.send_response(status)
            req.send_header("Content-Type", ctype)
            req.send_header("Content-Length", str(len(body)))
            req.end_headers()
            req.wfile.write(body)
        except (BrokenPipeError, ConnectionError):
            pass  # scraper hung up — its problem, not ours


# ------------------------------------------------------------------- CLI

def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_vgg_f_tpu.telemetry.collector",
        description="Fleet metrics collector: scrape every per-process "
                    "exporter, serve /fleetz + one aggregated /metrics.")
    parser.add_argument("--sidecar-dir", default="",
                        help="telemetry.sidecar_dir to discover "
                             "exporter_p<rank>.jsonl endpoints from")
    parser.add_argument("--endpoint", action="append", default=[],
                        help="static endpoint (host:port, role@host:port, "
                             "or role[N]@host:port); repeatable")
    parser.add_argument("--interval", type=float, default=1.0,
                        help="scrape interval seconds")
    parser.add_argument("--stale-after", type=float, default=10.0,
                        help="seconds without a successful scrape before "
                             "an entry reads stale")
    parser.add_argument("--timeout", type=float, default=2.0,
                        help="per-request scrape timeout seconds")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind host for /fleetz + /metrics")
    parser.add_argument("--port", type=int, default=0,
                        help="bind port (0 = OS-assigned, printed)")
    parser.add_argument("--fleet-log", default="",
                        help="append the per-cycle fleet JSONL here")
    parser.add_argument("--cycles", type=int, default=0,
                        help="exit after N cycles (0 = run forever)")
    args = parser.parse_args(argv)
    if not args.sidecar_dir and not args.endpoint:
        parser.error("need --sidecar-dir and/or at least one --endpoint")
    collector = FleetCollector(
        sidecar_dir=args.sidecar_dir, endpoints=args.endpoint,
        interval_s=args.interval, stale_after_s=args.stale_after,
        scrape_timeout_s=args.timeout, fleet_log=args.fleet_log,
        max_cycles=args.cycles, host=args.host, port=args.port)
    port = collector.start()
    print(json.dumps({"event": "fleet_collector", "host": args.host,
                      "port": port, **{k: v for k, v in
                                       collector.describe().items()
                                       if k not in ("host", "port")}}),
          flush=True)
    try:
        if args.cycles > 0:
            while (collector._loop_thread is not None  # noqa: SLF001
                   and collector._loop_thread.is_alive()):  # noqa: SLF001
                time.sleep(min(0.05, collector.interval_s))
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        collector.close()
    return 0


if __name__ == "__main__":  # pragma: no cover — process entry point
    raise SystemExit(main())
