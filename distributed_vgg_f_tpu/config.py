"""Typed experiment configuration.

The reference drives everything through CLI flags (SURVEY.md §1 CLI layer, reconstructed:
TF-1.x ``tf.app.flags``/argparse cluster + hyperparameter flags). Here the equivalent is
a tree of frozen dataclasses with named presets — one preset per BASELINE.json config —
plus ``parse_cli`` for ``--key=value`` overrides.
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence


@dataclass(frozen=True)
class ModelConfig:
    name: str = "vggf"                 # key into models.registry
    num_classes: int = 1000            # classifier width (ImageNet-1k default)
    dropout_rate: float = 0.5          # FC-head dropout; 0 disables (eval always runs without)
    compute_dtype: str = "bfloat16"    # activations/conv compute; params stay float32
    # model-specific extras (e.g. ViT depth/width overrides); kept generic so the
    # trainer stays model-agnostic (SURVEY.md §7 hard parts).
    extra: Mapping[str, Any] = field(default_factory=dict)


@dataclass(frozen=True)
class OptimConfig:
    base_lr: float = 0.01              # LR at reference batch size, scaled linearly
    reference_batch_size: int = 256    # batch size base_lr was tuned at (linear-scaling anchor)
    momentum: float = 0.9              # SGD momentum coefficient
    nesterov: bool = False             # Nesterov lookahead instead of classical momentum
    weight_decay: float = 5e-4         # L2-in-loss, matching TF coupled semantics
    schedule: str = "step"             # "step" | "cosine" | "constant"
    # step schedule: multiply LR by `decay_factor` at each boundary (in epochs)
    decay_epochs: Sequence[float] = (30.0, 60.0, 80.0)
    decay_factor: float = 0.1          # per-boundary LR multiplier for the step schedule
    warmup_epochs: float = 0.0         # linear LR ramp from 0 over this many epochs; 0 = none
    grad_clip_norm: float = 0.0        # 0 disables


@dataclass(frozen=True)
class SnapshotCacheConfig:
    """Decoded-crop snapshot cache (r9 — the tf.data paper's cache/snapshot
    move, arXiv 2101.12127): the first pass over the dataset writes each
    item's post-decode crop (exactly as the native loader shipped it — u8
    raw pixels on the flagship wire) to a bounded on-disk store keyed by
    (source fingerprint, decode params, native ABI); once every item is
    present, later epochs assemble batches straight from the store with a
    fresh per-epoch horizontal flip and skip libjpeg — entropy decode
    included — entirely. A cache that survives the process serves from
    batch 0 of the NEXT run. Warm epochs re-serve the first pass's crop
    geometry (the documented cache trade; flips stay fresh), so this is a
    throughput lever for decode-bound hosts, not a default. Corrupt or
    source-drifted entries degrade per item to a sequential native decode,
    or to the r9 corrupt-image fill when that also fails — never to stale
    pixels. Counters: prefetch/snapshot_{hits,misses,bytes}."""
    enabled: bool = False   # opt-in: a throughput lever for decode-bound hosts
    # Store directory; "" places it under <data_dir>/.dvggf_snapshot.
    dir: str = ""
    # On-disk budget. Writes stop (and the cache never turns warm) rather
    # than exceed it; stale parameter generations are evicted first.
    capacity_bytes: int = 8 << 30
    # crc32-validate payloads on warm reads (source stat drift is always
    # checked; this additionally catches bit-rot in the store itself).
    validate: bool = True

    def __post_init__(self):
        if self.capacity_bytes <= 0:
            raise ValueError(
                f"data.snapshot_cache.capacity_bytes must be > 0, got "
                f"{self.capacity_bytes}")


@dataclass(frozen=True)
class AutotuneConfig:
    """Closed-loop ingest autotuner (r11, data/autotune.py — tf.data's
    AUTOTUNE, arXiv 2101.12127, with a receipt trail): a per-process
    feedback controller that consumes the stall attributor's per-window
    verdicts and tunes the live pipeline knobs — native decode workers
    (runtime pool resize, ABI v8), host prefetch depth, device ring depth,
    restart fan-out — online, retiring the hand-pinned HOST_DECODE_RATE_R*
    constants as a runtime dependency (they stay bench artifacts). Every
    actuation passes hysteresis (k_windows consecutive verdicts, cooldown,
    bounded steps, hard rails) and is recorded three ways: autotune/*
    registry counters, the trainer JSONL `autotune` block, and the live
    /autotunez endpoint. Off by default; the flagship preset turns it on;
    DVGGF_AUTOTUNE=0 kills it regardless of config (behavior then
    byte-identical to controller-absent)."""
    enabled: bool = False   # off by default; the flagship preset turns it on
    # Consecutive same-direction verdicts required before ANY actuation.
    k_windows: int = 3
    # Quiet windows after an actuation before the next one may fire.
    cooldown_windows: int = 2
    # Windows with no actuation before the controller reports settled
    # (the flag the regression sentinel requires before gating a bench
    # artifact — a mid-convergence window would read as a false
    # regression).
    settled_after_windows: int = 6
    # Sustained compute_bound windows before a controller-RAISED knob steps
    # back down toward its baseline. 0 (default) disables down-steps
    # entirely: a compute-bound workload then produces zero actuations.
    relax_after_windows: int = 0
    # Direction flips on one knob before the oscillation guard freezes it
    # for the run (receipted in autotune/oscillation_freezes).
    freeze_after_flips: int = 2
    # Actuation-log ring size (trainer JSONL carries per-window actuations;
    # this bounds the /autotunez + flight-recorder history).
    history: int = 64
    # Hard rails per knob. max_threads 0 = min(16, host vCPUs).
    min_threads: int = 1                # rail: native decode-worker floor
    max_threads: int = 0                # rail: worker ceiling; 0 = min(16, host vCPUs)
    min_prefetch: int = 1               # rail: host prefetch-depth floor
    max_prefetch: int = 8               # rail: host prefetch-depth ceiling
    min_prefetch_to_device: int = 1     # rail: device ring-depth floor
    max_prefetch_to_device: int = 4     # rail: device ring-depth ceiling
    # 1 = fan-out knob unbound (fan-out trades cores for latency; the
    # throughput-provisioned default never engages it).
    max_restart_fanout: int = 1

    def __post_init__(self):
        if self.k_windows < 1 or self.settled_after_windows < 1:
            raise ValueError(
                "data.autotune.k_windows and settled_after_windows must be "
                f">= 1, got {self.k_windows}/{self.settled_after_windows}")
        if self.cooldown_windows < 0 or self.relax_after_windows < 0:
            raise ValueError(
                "data.autotune.cooldown_windows and relax_after_windows "
                f"must be >= 0, got {self.cooldown_windows}/"
                f"{self.relax_after_windows}")
        if self.freeze_after_flips < 1:
            raise ValueError(
                f"data.autotune.freeze_after_flips must be >= 1, got "
                f"{self.freeze_after_flips}")
        if self.history < 1:
            raise ValueError(
                f"data.autotune.history must be >= 1, got {self.history}")
        # 0-means-auto exists ONLY for max_threads (resolved to
        # min(16, vCPUs) at bind time); a zero prefetch rail would bind a
        # knob with max < min that silently never steers
        if self.min_threads < 1 or (self.max_threads != 0
                                    and self.max_threads < self.min_threads):
            raise ValueError(
                f"data.autotune rails need 1 <= min_threads <= max_threads "
                f"(0 = auto), got {self.min_threads}/{self.max_threads}")
        for lo_name, hi_name in (("min_prefetch", "max_prefetch"),
                                 ("min_prefetch_to_device",
                                  "max_prefetch_to_device")):
            lo, hi = getattr(self, lo_name), getattr(self, hi_name)
            if lo < 1 or hi < lo:
                raise ValueError(
                    f"data.autotune rails need 1 <= {lo_name} <= "
                    f"{hi_name}, got {lo}/{hi}")
        if self.max_restart_fanout < 1 or self.max_restart_fanout > 64:
            raise ValueError(
                f"data.autotune.max_restart_fanout must be in [1, 64], "
                f"got {self.max_restart_fanout}")


@dataclass(frozen=True)
class ServiceConfig:
    """Disaggregated ingest (r16, ROADMAP item 4 — the tf.data-service
    split, arXiv 2101.12127): decode-worker processes run the full native
    stack (`python -m distributed_vgg_f_tpu.data.ingest_service`) and
    serve ready position-keyed crops over length-prefixed sockets; the
    training host runs a thin fetch-and-device_put client
    (data/service_client.py) in place of the local loader. Off by default
    — `enabled=false` never touches the service plane and local ingest is
    byte-identical to pre-r16 (pinned in tests/test_ingest_service.py).
    Batch cursors are split across the fleet by an epoch-keyed SplitMix64
    permutation (static within an epoch, no mid-stream handoff); a dead
    worker's cursors are reassigned to survivors, and with every worker
    dead the client degrades to the ordinary local pipeline (or raises a
    typed DataStallError when `fallback_local` is off). Counters:
    `ingest_service/*`; live state on the exporter's `/ingestz`."""
    enabled: bool = False   # kill-switch: off = local ingest, byte-identical
    # Decode-worker endpoints, "host:port" each, IN WORKER-INDEX ORDER (the
    # epoch-keyed ownership split permutes this list). Per training host:
    # multi-host runs give each trainer process its own fleet serving that
    # process's shard (the hello handshake refuses a shard mismatch).
    workers: Sequence[str] = ()
    # Batches kept in flight across the fleet; 0 = auto (3x worker count —
    # two keep each worker's decode/transfer overlapped, the third absorbs
    # delivery-order jitter; the pipelining that makes N workers aggregate
    # to ~Nx one host's rate).
    fetch_ahead: int = 0
    # Socket connect timeout per worker (startup + reconnects).
    connect_timeout_s: float = 5.0
    # Per-request timeout; a worker slower than this is treated as dead
    # and its cursors fail over (the service-plane analogue of
    # train.data_timeout_s).
    request_timeout_s: float = 60.0
    # With every worker dead, fall back to the ordinary local pipeline at
    # the exact stream position (true, default) or raise DataStallError
    # (false — fleets that would rather page than silently degrade).
    fallback_local: bool = True

    def __post_init__(self):
        # enabled-with-no-workers is rejected at client build time
        # (service_client.py), not here: `--set` overrides apply one field
        # at a time, so a cross-field check in __post_init__ would make
        # `--set data.service.enabled=true --set data.service.workers=...`
        # fail on flag ORDER.
        for e in self.workers:
            host, sep, port = str(e).rpartition(":")
            if not sep or not host or not port.isdigit():
                raise ValueError(
                    f"data.service.workers entry {e!r} is not host:port")
        if self.fetch_ahead < 0:
            raise ValueError(
                f"data.service.fetch_ahead must be >= 0 (0 = auto), got "
                f"{self.fetch_ahead}")
        if self.connect_timeout_s <= 0 or self.request_timeout_s <= 0:
            raise ValueError(
                "data.service.connect_timeout_s and request_timeout_s must "
                f"be > 0, got {self.connect_timeout_s}/"
                f"{self.request_timeout_s}")

    @property
    def label(self) -> str:
        """The ingest basis label — `local` | `service_<N>w` — stamped
        into the trainer start record, bench rows (`ingest_mode`), and the
        regression sentinel's Basis key. Delegates to the single
        formatting implementation (data/ingest_service.ingest_label) so
        the start record and the /ingestz + bench labels can never
        drift apart."""
        from distributed_vgg_f_tpu.data.ingest_service import ingest_label
        return ingest_label(len(self.workers), self.enabled)


@dataclass(frozen=True)
class IteratorStateConfig:
    """Position-exact resumable ingest (r18, data/iterator_state.py — the
    tf.data iterator-checkpointing move, arXiv 2101.12127): the trainer's
    host ingest chain is wrapped in a cursor-counting rebuild surface, a
    schema-validated iterator-state blob (epoch, SplitMix64 shuffle state,
    cursor, in-flight read-ahead set) rides every checkpoint's `extra`,
    restore dispatches on receipt-present (pre-r18 checkpoints keep the
    r17 epoch-boundary replay path unchanged), and `rebuild_live` lets the
    ingest autotuner actuate the host↔u8 wire switch mid-epoch with
    byte-identical stream continuation. `enabled=false` is the kill-switch:
    no wrapper, no blob, no wire knob — the feed path is structurally
    identical to r17 (stream identity pinned in
    tests/test_iterator_state.py)."""
    # On by default: the blob is ~a hundred bytes of JSON per checkpoint
    # and restore still degrades gracefully on receipt-absent checkpoints.
    enabled: bool = True


def resolve_serving_buckets(buckets: Sequence[int],
                            max_batch: int) -> tuple:
    """The serving batch-bucket ladder, validated — THE single
    implementation (ServingConfig validation and serving/engine.py both
    delegate here; schema.validate_serving_row keeps its own literal copy
    by the leaf-module contract). Explicit `buckets` must be unique
    ascending positive ints covering max_batch (each gets one
    AOT-compiled executable; groups pad to the nearest bucket); () = the
    power-of-two ladder up to max_batch — small buckets keep light
    traffic cheap, the top bucket IS max_batch so a full flush never
    splits."""
    if buckets:
        out = tuple(int(b) for b in buckets)
        if list(out) != sorted(set(out)) or out[0] < 1:
            raise ValueError(f"buckets must be unique ascending positive "
                             f"ints, got {list(buckets)}")
        if out[-1] < int(max_batch):
            raise ValueError(
                f"buckets {list(out)} do not cover max_batch={max_batch} "
                "— a full flush would have no executable to run on")
        return out
    out = []
    b = 1
    while b < int(max_batch):
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(sorted(set(out)))


#: The serving tier ladder, in descending-fidelity order — the router's
#: `?tier=` vocabulary (serving/tiers.py mirrors this; the schema keeps a
#: literal copy by the leaf-module contract).
SERVING_TIERS = ("fp32", "bf16", "int8", "student")


@dataclass(frozen=True)
class ServingTiersConfig:
    """Latency-tiered serving (r23, serving/tiers.py): per-tier AOT engine
    variants behind the one router — `bf16` (params cast once at load,
    bf16 activations, fp32 logits), `int8` (post-training per-out-channel
    symmetric weight quantization of the FC heads, activation scales from
    a committed calibration pass over the u8 wire; sub-LSB channels are
    elided exactly — they quantize to zero under the per-tensor activation
    scale), and `student` (the half-width `vggf_student` distilled by
    train/distill.py). `serving.tiers.enabled=false` is the kill-switch:
    the router never parses `?tier=`, /v1/models carries no ladder, and
    the server is structurally the fp32-only r22 surface (routing/lowered
    identity pinned in tests/test_serving_tiers.py)."""
    # Kill-switch: off = fp32-only server, tier machinery never imported.
    enabled: bool = False
    # Batches of synthetic u8 wire images the int8 calibration pass runs
    # to record per-layer activation ranges (serving/tiers.py).
    calibration_batches: int = 4
    # Images per calibration batch (clamped to the engine's top bucket).
    calibration_batch_size: int = 8
    # Seed for the synthetic calibration batch stream — part of the
    # committed calibration receipt, so a re-run reproduces the ranges.
    calibration_seed: int = 0
    # Per-tier accuracy contract: largest top-1 drop vs the fp32 tier a
    # committed accuracy-delta receipt may show (schema-enforced).
    max_top1_delta_bf16: float = 0.02
    max_top1_delta_int8: float = 0.05   # see max_top1_delta_bf16
    max_top1_delta_student: float = 0.10  # see max_top1_delta_bf16

    def __post_init__(self):
        if self.calibration_batches < 1 or self.calibration_batch_size < 1:
            raise ValueError(
                "serving.tiers calibration needs >= 1 batches of >= 1 "
                f"images, got {self.calibration_batches}/"
                f"{self.calibration_batch_size}")
        for name in ("max_top1_delta_bf16", "max_top1_delta_int8",
                     "max_top1_delta_student"):
            v = getattr(self, name)
            if not 0 <= v <= 1:
                raise ValueError(
                    f"serving.tiers.{name} must be in [0, 1], got {v}")


@dataclass(frozen=True)
class ServingConfig:
    """Always-on dynamic-batching predict server (r17, serving/ — ROADMAP
    item 1, the serving half of the TF-system training/serving split,
    arXiv 1605.08695): a persistent stdlib-HTTP front end over the jitted
    predict step, fed raw u8 image payloads (1 B/px off the network, the
    u8 wire contract — the device-finish prologue normalizes on device),
    with a bounded admission queue, max-latency + max-batch flush, one
    AOT-lowered executable per batch bucket, per-model routing over the
    models/ingest.py descriptor table, and explicit overload behavior
    (typed 503 shed, never unbounded latency). Off by default — with
    `enabled=false` the serving package is never imported and offline
    predict is byte-identical to r16 (pinned in tests/test_serving.py);
    `--mode serve` refuses to start without the explicit opt-in."""
    enabled: bool = False   # kill-switch: off = no server, predict untouched
    # Bind address. Loopback by default: the predict endpoint is
    # unauthenticated — fronting it beyond the host (an LB, a mesh
    # sidecar) is an explicit decision, same stance as the exporter.
    host: str = "127.0.0.1"
    # 0 = OS-assigned free port (the bound port is printed and returned
    # from start() — the exporter's port-0 contract).
    port: int = 0
    # Largest batch one flush may form; also the top batch bucket.
    max_batch: int = 32
    # Batch buckets (ascending; each gets ONE ahead-of-time-compiled
    # executable; groups pad to the nearest bucket). () = the power-of-two
    # ladder 1,2,4,...,max_batch.
    buckets: Sequence[int] = ()
    # Admission window: max milliseconds the OLDEST queued request waits
    # for company before a partial batch flushes. The controller's knob
    # baseline.
    max_latency_ms: float = 10.0
    # Bounded admission queue: arrivals past this depth shed with the
    # typed 503 payload instead of queueing unboundedly.
    queue_limit: int = 128
    # Server-side cap on one request's total wait (queue + batch + run);
    # exceeded → typed 504.
    request_timeout_s: float = 30.0
    # Retry-After hint (ms) carried in the 503 shed payload.
    shed_retry_after_ms: int = 50
    # AOT-compile every bucket at add_engine time so the first request of
    # any shape pays dispatch, not XLA compile.
    warmup: bool = True
    # Admission controller (serving/controller.py — the r11 autotuner over
    # the batch-window knob, steered by queue-depth/latency verdicts).
    controller: bool = True
    # Hard rails for the controller's admission-window knob (ms).
    window_min_ms: float = 1.0
    window_max_ms: float = 100.0   # see window_min_ms
    # Seconds between controller windows (verdict + gauges + flight ring +
    # serving heartbeat cadence).
    controller_interval_s: float = 2.0
    # Consecutive pressure windows before the controller widens the window
    # (the r11 hysteresis contract).
    controller_k_windows: int = 3
    # Quiet windows after an actuation before the next may fire.
    controller_cooldown_windows: int = 2
    # Sustained steady windows before a controller-raised window steps
    # back down toward max_latency_ms (0 disables relaxation).
    controller_relax_after_windows: int = 4
    # Queue peak (as a fraction of queue_limit) that reads as pressure
    # even before anything sheds.
    queue_pressure_fraction: float = 0.5
    # Tier a request lands on when it carries no explicit `?tier=` (the
    # per-model default class). Ignored — structurally fp32 — while
    # serving.tiers.enabled is false.
    tier_default: str = "fp32"
    # Latency tier ladder (r23): bf16/int8/student engine variants behind
    # the same router — see ServingTiersConfig.
    tiers: ServingTiersConfig = field(default_factory=ServingTiersConfig)

    def __post_init__(self):
        if self.tier_default not in SERVING_TIERS:
            raise ValueError(
                f"serving.tier_default {self.tier_default!r} not one of "
                f"{SERVING_TIERS}")
        if self.max_batch < 1:
            raise ValueError(
                f"serving.max_batch must be >= 1, got {self.max_batch}")
        # one validator for the bucket-ladder contract (shared with the
        # engine's resolution — see resolve_serving_buckets)
        resolve_serving_buckets(self.buckets, self.max_batch)
        if self.queue_limit < 1:
            raise ValueError(
                f"serving.queue_limit must be >= 1, got {self.queue_limit}")
        if self.max_latency_ms <= 0 or self.request_timeout_s <= 0:
            raise ValueError(
                "serving.max_latency_ms and request_timeout_s must be > 0, "
                f"got {self.max_latency_ms}/{self.request_timeout_s}")
        if not 0 < self.window_min_ms <= self.window_max_ms:
            raise ValueError(
                f"serving window rails need 0 < window_min_ms <= "
                f"window_max_ms, got {self.window_min_ms}/"
                f"{self.window_max_ms}")
        if not self.window_min_ms <= self.max_latency_ms \
                <= self.window_max_ms:
            raise ValueError(
                f"serving.max_latency_ms {self.max_latency_ms} outside the "
                f"controller rails [{self.window_min_ms}, "
                f"{self.window_max_ms}] — the knob baseline must be "
                "reachable")
        if self.controller_interval_s <= 0:
            raise ValueError(
                f"serving.controller_interval_s must be > 0, got "
                f"{self.controller_interval_s}")
        if self.controller_k_windows < 1 \
                or self.controller_cooldown_windows < 0 \
                or self.controller_relax_after_windows < 0:
            raise ValueError(
                "serving controller needs k_windows >= 1 and non-negative "
                "cooldown/relax windows, got "
                f"{self.controller_k_windows}/"
                f"{self.controller_cooldown_windows}/"
                f"{self.controller_relax_after_windows}")
        if not 0 < self.queue_pressure_fraction <= 1:
            raise ValueError(
                f"serving.queue_pressure_fraction must be in (0, 1], got "
                f"{self.queue_pressure_fraction}")
        if self.shed_retry_after_ms < 0:
            raise ValueError(
                f"serving.shed_retry_after_ms must be >= 0, got "
                f"{self.shed_retry_after_ms}")


@dataclass(frozen=True)
class AugmentConfig:
    """Fused on-device augmentation (r13, data/augment.py): horizontal
    flip, crop jitter, mixup/cutmix, and a RandAugment-lite elementwise
    subset, applied INSIDE the jitted train step as a pure function of
    (seed, step, replica) — the host wire stays raw u8 and augmentation
    diversity costs zero host cycles (the large-distributed-CNN study's
    host-offload argument, arXiv 1711.00705). Off by default;
    `enabled=false` is structurally absent (the step body is byte-identical
    to a build without the stage — pinned by jaxpr-equality test). The
    flagship preset ships flips + mixup.

    Flip ownership: when `enabled and hflip`, the DEVICE owns the
    horizontal flip and every host-side flip — the native decoder's
    (ABI v9 per-loader switch), tf.data's, grain's, cifar10's, and the
    snapshot cache's warm-path redraw — is disabled by this one predicate
    (`owns_hflip`), so double-flip is structurally impossible.

    Eval and predict NEVER augment: the stage exists only in the train
    step (sentinel test pins the eval jaxpr identical augment-on vs off).
    """
    enabled: bool = False
    # Device-side random horizontal flip (replaces every host flip).
    hflip: bool = True
    # Max |shift| in pixels of the per-image translation jitter (edge
    # pixels replicate). 0 disables.
    crop_jitter: int = 0
    # Beta(alpha, alpha) mixup (arXiv 1710.09412); 0 disables. Labels mix
    # as lam*CE(y) + (1-lam)*CE(y[perm]) — integer labels, no one-hot.
    mixup_alpha: float = 0.0
    # Beta(alpha, alpha) cutmix (arXiv 1905.04899); 0 disables. When both
    # mixup and cutmix are enabled, each step draws one of the two.
    cutmix_alpha: float = 0.0
    # RandAugment-lite: number of elementwise op draws per image from
    # {identity, brightness, contrast, posterize}. 0 disables.
    rand_ops: int = 0
    # Magnitude of the RandAugment-lite ops in [0, 1].
    rand_magnitude: float = 0.5

    @property
    def owns_hflip(self) -> bool:
        """True when the DEVICE owns the horizontal flip — the single
        predicate every host pipeline consults before flipping."""
        return self.enabled and self.hflip

    def describe(self) -> dict:
        """JSON-ready receipt (trainer JSONL `augment` block, bench rows)."""
        return {"enabled": self.enabled, "hflip": self.hflip,
                "crop_jitter": self.crop_jitter,
                "mixup_alpha": self.mixup_alpha,
                "cutmix_alpha": self.cutmix_alpha,
                "rand_ops": self.rand_ops,
                "rand_magnitude": self.rand_magnitude,
                "host_flips_disabled": self.owns_hflip}

    def __post_init__(self):
        if self.crop_jitter < 0:
            raise ValueError(
                f"data.augment.crop_jitter must be >= 0, got "
                f"{self.crop_jitter}")
        if self.mixup_alpha < 0 or self.cutmix_alpha < 0:
            raise ValueError(
                "data.augment.mixup_alpha and cutmix_alpha must be >= 0, "
                f"got {self.mixup_alpha}/{self.cutmix_alpha}")
        if self.rand_ops < 0:
            raise ValueError(
                f"data.augment.rand_ops must be >= 0, got {self.rand_ops}")
        if not 0.0 <= self.rand_magnitude <= 1.0:
            raise ValueError(
                f"data.augment.rand_magnitude must be in [0, 1], got "
                f"{self.rand_magnitude}")


@dataclass(frozen=True)
class DataConfig:
    name: str = "synthetic"  # "synthetic" | "cifar10" | "imagenet" | "teacher"
    data_dir: str = ""       # dataset root; "" = synthetic fallback where supported
    image_size: int = 224    # square train/eval resolution after crop+resize
    global_batch_size: int = 256   # across ALL replicas; must divide by replica count
    num_train_examples: int = 1_281_167   # ImageNet-1k default
    num_eval_examples: int = 50_000       # eval split size (ImageNet-1k val default)
    shuffle_buffer: int = 16_384   # tf.data shuffle window (native loader shuffles exactly)
    prefetch: int = 2              # device-prefetch ring depth (batches in flight)
    # dtype of batches handed to the device. "bfloat16" halves H2D volume and
    # skips the on-device cast (models compute in bf16 anyway).
    image_dtype: str = "float32"
    # Host→device ingest wire format (r8): "auto" keeps the host-normalize
    # path in `image_dtype` (eval parity, non-native backends); "host_f32" /
    # "host_bf16" force that path's dtype; "u8" ships RAW resampled uint8
    # pixels from the native loader (1 byte/pixel — 4x less wire+ring than
    # f32, ~2x less than bf16) and finishes normalize/cast/space-to-depth on
    # device, fused into the jitted step (data/device_ingest.py). u8 applies
    # to native TRAIN ingest only and falls back to the host path — with a
    # logged warning, byte-identical to pre-r8 behavior — when the native u8
    # wire is unavailable or kill-switched (DVGGF_WIRE_U8=0 env /
    # -DDVGGF_NO_WIRE_U8 build). Eval/predict always ride the host path;
    # the device-finish prologue dispatches on dtype, so mixed wires can
    # never double-normalize.
    wire: str = "auto"
    # Decode ImageNet training data with the native libjpeg loader
    # (native/jpeg_loader.cc: DCT-scaled partial decode in C++ worker threads
    # — measured ~1.3–1.6x tf.data per host core, run-to-run spread on this
    # shared host; frozen tracking baseline in benchmarks/baseline.json).
    # Covers BOTH layouts:
    # raw-JPEG directory-per-class, and TFRecords via the native indexer
    # (native/tfrecord_index.cc — JPEG byte ranges read straight out of the
    # shards, no TF/proto in the loop). Falls back to tf.data (with a logged
    # warning) when the native build is unavailable. Both streams are
    # deterministic per seed and support exact resume; they draw different
    # (but same-distribution) augmentations.
    native_jpeg: bool = True
    # Use the native loader for EVAL too (deterministic center crop, exact
    # pad-and-mask finite pass). Off by default: the native eval resamples
    # the original-resolution center crop in one bilinear step, while tf.data
    # resizes-then-crops (two steps) — same protocol, slightly different
    # pixel values, so keep the default stable for comparisons.
    native_jpeg_eval: bool = False
    # Decode worker threads for the native loader; 0 = auto (min(8, vCPUs)).
    native_threads: int = 0
    # Host input backend for the imagenet pipeline:
    #   "auto"   — native loader (per native_jpeg/native_jpeg_eval), tf.data
    #              fallback;
    #   "native" — force the native loader (train AND eval);
    #   "tfdata" — force tf.data;
    #   "grain"  — PyGrain DataLoader (data/grain_imagenet.py): deterministic
    #              index sampling + true multiprocess decode workers
    #              (grain_workers), decoding through the native single-image
    #              decoder; falls back to "auto" with a logged warning.
    backend: str = "auto"
    # Grain decode worker PROCESSES (0 = in-process). Real multi-core hosts
    # set this near the core count; tf.data threads and the native loader's
    # C++ threads share one process, grain workers do not.
    grain_workers: int = 0
    # Emit TRAIN batches in the 4x4 space-to-depth layout (S/4, S/4, 48)
    # instead of (S, S, 3) — the host side of the VGG-F stem's packed-input
    # contract (models/vggf.py Conv1SpaceToDepth dispatches on input shape;
    # skipping the on-device relayout measured +3.7% train step at batch 2048
    # on v5e). VGG-F only; eval batches stay (S, S, 3) — the model accepts
    # both. Supported by the synthetic, tf.data-imagenet, and native-loader
    # pipelines; requires image_size % 4 == 0.
    space_to_depth: bool = False
    # Teacher task only: fix the eval split's index base instead of the
    # default "starts at num_train_examples". The default couples the val
    # SET to the train-set size, so a train-size sweep would score each arm
    # on a different 1024-example sample — ±1.5 % top-1 noise, the same
    # order as the effect being measured (code-review r4). A far-offset
    # shared base keeps one fixed held-out set across all arms; must be
    # >= num_train_examples (validated in data/teacher.py).
    eval_index_base: int = 0   # 0 = legacy: num_train_examples
    # Label mapping for the flat-validation-directory ImageNet layout
    # (val/*.JPEG with no class subdirectories). "" auto-detects
    # val_labels.txt / validation_labels.txt / ILSVRC2012_validation_ground_truth.txt
    # next to the data. See data/imagenet.py for the accepted formats.
    val_labels_file: str = ""
    # Per-channel normalization constants (0-255 scale, ImageNet RGB stats);
    # every ingest path — tf.data, native, u8 device-finish — applies these.
    mean_rgb: Sequence[float] = (123.68, 116.78, 103.94)
    stddev_rgb: Sequence[float] = (58.393, 57.12, 57.375)  # see mean_rgb
    # Decoded-crop snapshot cache over the native TRAIN iterator (r9):
    # warm epochs skip libjpeg entirely. See SnapshotCacheConfig.
    snapshot_cache: SnapshotCacheConfig = field(
        default_factory=SnapshotCacheConfig)
    # Closed-loop ingest autotuner (r11): online verdict-driven tuning of
    # decode workers / prefetch depths / fan-out. See AutotuneConfig.
    autotune: AutotuneConfig = field(default_factory=AutotuneConfig)
    # Fused on-device augmentation (r13): flip/jitter/mixup/cutmix/
    # RandAugment-lite inside the jitted train step. See AugmentConfig.
    augment: AugmentConfig = field(default_factory=AugmentConfig)
    # Disaggregated ingest (r16): fetch ready crops from a decode-worker
    # fleet instead of decoding locally. See ServiceConfig; off by default
    # (local ingest byte-identical).
    service: ServiceConfig = field(default_factory=ServiceConfig)
    # Position-exact resumable ingest (r18): checkpointable iterator-state
    # blobs + live position-exact rebuild. See IteratorStateConfig; off =
    # the r17 epoch-boundary replay path, byte-identical.
    iterator_state: IteratorStateConfig = field(
        default_factory=IteratorStateConfig)

    @property
    def host_space_to_depth(self) -> bool:
        """Whether the HOST pipeline packs the 4x4 layout. With the fused
        device augmentation enabled, packing must happen AFTER the
        device-side geometric augments — the host then always ships
        unpacked (S, S, 3) and the train step packs post-augment, for the
        host wires exactly as the u8 wire always did. The single source of
        the packing split; every pipeline builder consults this, never
        `space_to_depth` directly."""
        return self.space_to_depth and not self.augment.enabled

    def __post_init__(self):
        # a typo'd backend must fail loudly, not silently behave as "auto"
        if self.backend not in ("auto", "native", "tfdata", "grain"):
            raise ValueError(
                f"data.backend {self.backend!r} not one of "
                "'auto'|'native'|'tfdata'|'grain'")
        from distributed_vgg_f_tpu.data.dtypes import WIRE_FORMATS
        if self.wire not in WIRE_FORMATS:
            raise ValueError(
                f"data.wire {self.wire!r} not one of {WIRE_FORMATS}")
        if self.image_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"data.image_dtype {self.image_dtype!r} not one of "
                "('float32', 'bfloat16') — the uint8 wire is selected via "
                "data.wire='u8', not image_dtype")


@dataclass(frozen=True)
class ElasticConfig:
    """Live elastic resize (r19, parallel/elastic.py — the cross-replica
    weight-resharding move of arXiv 2004.13336 closed into the recovery
    loop): when `PreemptConsensus` fires for k of N data shards, the
    survivors form a shrunken mesh, reshard params/opt-state in place
    through `zero.convert_opt_state` + the r14 bucket-layout receipts, and
    continue through the PR 15 cursor blob — zero replayed batches, no
    process restart. `enabled=false` is the kill-switch: preemption takes
    the r18 checkpoint-and-exit path, structurally identical to pre-r19
    (pinned in tests/test_elastic.py)."""
    # Kill-switch: off = preemption checkpoints and stops (the r18 restart
    # path), byte-identical to pre-r19; on = survivors resize and continue.
    enabled: bool = False
    # What the global batch means across a resize. "keep_global" (default):
    # dead shards' data moves to survivors — global batch and LR unchanged,
    # per-survivor batch grows, loss trajectory identical to a restart on
    # the same survivor count. "scale_lr": per-replica batch is invariant —
    # the global batch shrinks by N'/N and the LR is rescaled by the same
    # factor (linear-scaling rule), with a schedule receipt logged.
    batch_policy: str = "keep_global"
    # Fewest survivors worth resizing onto; below this the resize degrades
    # to the r18 restart path with the `elastic_degraded_restart` flight
    # class (an all-but-one-dead fleet should restart on fresh capacity,
    # not limp on one shard).
    min_survivors: int = 2

    def __post_init__(self):
        if self.batch_policy not in ("keep_global", "scale_lr"):
            raise ValueError(
                f"mesh.elastic.batch_policy {self.batch_policy!r} not one "
                "of ('keep_global', 'scale_lr')")
        if self.min_survivors < 1:
            raise ValueError(
                f"mesh.elastic.min_survivors must be >= 1, got "
                f"{self.min_survivors}")


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout. The reference is pure DP (SURVEY.md §2.3); we keep a named
    axis layout so additional axes can be introduced without touching the trainer."""
    data_axis: str = "data"   # name of the mesh's data-parallel axis
    # 0 = use all visible devices on the data axis.
    num_data: int = 0
    # Optimizer-state sharding over the data axis (ZeRO-1-style; PAPERS.md
    # "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel Training").
    shard_opt_state: bool = False
    # ZeRO-2 (r14): gradient state held only as 1/N flat shards — each
    # bucket's psum_scatter consumes its transient gradients directly and,
    # under grad accumulation, the scan accumulator is the 1/N shard (the
    # O(params) -> O(params/N) drop shown in utils/scaling_model.py
    # gradient_state_bytes_per_chip). Wire bytes are unchanged vs ZeRO-1
    # (reduce-scatter + all-gather move what the all-reduce moved);
    # requires shard_opt_state.
    shard_gradients: bool = False
    # ZeRO-3 (r21): parameters held ONLY as 1/N flat shards in the
    # TrainState — the step all-gathers each bucket just-in-time through
    # the single-sourced wire cast (mesh.reduce_dtype applies to the
    # gather leg too, unlike ZeRO-1/2's always-fp32 re-sync gather) and
    # the trailing param all-gather disappears (the optimizer updates the
    # shard in place). Persistent param state drops O(params) ->
    # O(params/N) (utils/scaling_model.py param_bytes_per_chip); the loss
    # trajectory is pinned EQUAL to ZeRO-2 (tests/test_zero3.py).
    # Requires shard_gradients; default off = the ZeRO-2 step,
    # lowered-text-identical (kill-switch pin).
    shard_params: bool = False
    # Bucketed, overlap-capable gradient exchange (r14,
    # parallel/buckets.py): partition the param tree into buckets of ~this
    # many MB in reverse-backward order and issue one collective per
    # bucket as its gradients exist, so XLA's latency-hiding scheduler can
    # run the exchange under the remaining backward (arXiv 1711.00705 /
    # 1603.02339). 0 = single monolithic exchange, byte-identical to the
    # pre-r14 step (kill-switch lowered-text identity pinned). Under
    # sharding the opt-state flat layout becomes bucket-major
    # (checkpoints migrate through parallel/zero.convert_opt_state with
    # the geometry receipt in the checkpoint's `extra`).
    comm_bucket_mb: float = 0.0
    # Gradient all-reduce wire dtype. "float32" (default) reduces at full
    # precision. "bfloat16" halves the per-step collective bytes — the
    # analytic scaling model (utils/scaling_model.py) puts the fp32 worst
    # case at VGG-16's 553 MB gradient, 0.929 no-overlap efficiency at
    # v4-128; bf16 lifts that floor to ~0.96. Opt-in because it perturbs
    # gradients by bf16 rounding (~3 decimal digits): the cast happens
    # AFTER the local backward (fp32) and BEFORE the cross-replica mean;
    # momentum/params stay fp32. ZeRO-1's param all-gather is NOT affected
    # (params must re-sync bit-exactly).
    reduce_dtype: str = "float32"
    # Live elastic resize on preemption consensus (r19,
    # parallel/elastic.py); `mesh.elastic.enabled` is the kill-switch.
    elastic: ElasticConfig = field(default_factory=ElasticConfig)

    def __post_init__(self):
        if self.reduce_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"mesh.reduce_dtype {self.reduce_dtype!r} not one of "
                f"('float32', 'bfloat16')")
        if self.comm_bucket_mb < 0:
            raise ValueError(
                f"mesh.comm_bucket_mb {self.comm_bucket_mb} < 0 (0 = "
                "single-bucket kill-switch, >0 = bucket size target)")
        if self.shard_params and not self.shard_gradients:
            raise ValueError(
                "mesh.shard_params (ZeRO-3) requires "
                "mesh.shard_gradients (ZeRO-2) — the sharding ladder is "
                "cumulative: parameter shards only exist inside the "
                "gradient-shard frame (set both, plus shard_opt_state)")

    @property
    def sharding_label(self) -> str:
        """The CONFIGURED (dp | zero1 | zero2 | zero3) basis — what this
        config ASKS for, via the same single derivation
        (parallel/buckets.sharding_basis) the step's runtime `comm`
        receipt uses. The receipt reports the EFFECTIVE basis, which can
        downgrade below this label (single-shard meshes drop zero1, and
        `shard_gradients` without `shard_opt_state` has no 1/N frame to
        live in — mirroring the trainer's downgrade, so the
        README-documented `--set mesh.shard_opt_state=false` toggle stays
        valid on presets that ship ZeRO-2/3). Receipts/sentinel rows must
        key on the runtime `comm` block, not this property."""
        from distributed_vgg_f_tpu.parallel.buckets import sharding_basis
        zero1 = self.shard_opt_state
        zero2 = zero1 and self.shard_gradients
        return sharding_basis(zero1, zero2, zero2 and self.shard_params)


@dataclass(frozen=True)
class TrainConfig:
    epochs: float = 90.0               # training length in epochs (fractional allowed)
    steps: int = 0                     # if >0 overrides epochs
    seed: int = 0                      # base RNG seed: params, data order, augmentation
    log_every: int = 100               # steps between train-metric log/JSONL records
    eval_every_steps: int = 0          # 0 = once per epoch
    checkpoint_every_steps: int = 1000 # durable-save cadence (also saves at run end)
    checkpoint_dir: str = ""           # "" disables checkpointing entirely
    keep_checkpoints: int = 3          # retained durable steps; older ones are pruned
    tensorboard_dir: str = ""          # "" disables TF summary output
    profile: bool = False              # jax.profiler trace around a few steps
    profile_dir: str = "/tmp/dvggf_profile"  # where the profiler trace lands
    profile_start_step: int = 10       # relative to the run's first step
    profile_num_steps: int = 5         # profiler window length
    debug_nans: bool = False           # jax_debug_nans (debug-only; see skip_nonfinite)
    # Non-finite step guard (resilience/guard.py; the production replacement
    # for the debug-only jax_debug_nans flag): the jitted step all-reduces an
    # isfinite(loss & grad_norm) flag and drops the optimizer update on a bad
    # step — parameters stay bit-identical, the step counter still advances.
    # After max_nonfinite_steps CONSECUTIVE skips the trainer aborts with a
    # NonFiniteStepError diagnostic instead of burning fleet time on a
    # diverged (or garbage-fed) run. Skip detection costs one select per
    # state leaf inside the step; the host poll is lagged (never blocks
    # dispatch, same idiom as parallel/preempt.py).
    skip_nonfinite: bool = True
    max_nonfinite_steps: int = 10   # consecutive-skip abort threshold (see above)
    # Data-pipeline watchdog (data/prefetch.py): per-batch timeout with
    # bounded exponential-backoff retries — a stalled or crashed host loader
    # surfaces as a typed DataStallError instead of an indefinite hang.
    # 0 disables the timeout (the dead-worker detector stays active);
    # retries double the wait each attempt, so the worst-case wall time is
    # data_timeout_s * (2^(retries+1) - 1). Requires the device-prefetch
    # thread: with prefetch_to_device=0 (or a caller-supplied dataset) the
    # watchdog cannot engage and the trainer logs data_watchdog_inactive.
    data_timeout_s: float = 0.0
    data_timeout_retries: int = 2   # backoff retries before DataStallError (see above)
    # Checkpoint resilience (checkpoint/manager.py): saves retry transient
    # I/O errors this many times (exponential backoff) before giving up;
    # durable steps get a checksum manifest and restores fall back to the
    # newest INTACT step when the latest is truncated or corrupt.
    checkpoint_save_retries: int = 2
    # Fault-injection spec (resilience/faults.py FaultPlan.parse): "" = no
    # injection (production). E.g. "nan@3,stall@5:20,preempt@8" — see the
    # module docstring for the grammar; tests/test_resilience.py is the
    # chaos suite built on it.
    fault_injection: str = ""
    # On-device batches kept ahead of compute by a background H2D thread
    # (data/prefetch.py); 0 disables the overlap and shards synchronously.
    prefetch_to_device: int = 2
    # On checkpoint resume, reproduce the uninterrupted data stream exactly
    # (SURVEY.md §5 checkpoint: data-iterator state). Pipelines with iterator
    # snapshots (imagenet tf.data: symbolic checkpoints written automatically
    # at the checkpoint cadence whenever checkpoint_dir is set) restore in
    # O(1) regardless of this flag. This flag enables the REPLAY fallback for
    # pipelines without snapshot support — one host draw per skipped step,
    # cheap for numpy/native iterators.
    resume_data_fast_forward: bool = True
    # PRNG implementation for the training dropout key. "rbg" generates random
    # bits ~1.6x faster than threefry on TPU for dropout-heavy models (ViT
    # train step measured 218→136 ms/step at batch 256 on v5e); still
    # deterministic per seed. Param init keeps the JAX default regardless.
    dropout_rng_impl: str = "rbg"
    # Micro-batch gradient accumulation inside the jitted step (lax.scan):
    # k>1 splits each device's batch into k micro-batches — 1/k activation
    # memory at an unchanged optimizer batch/LR schedule/sync schedule. The
    # per-device batch must divide by k. See train/step.py.
    grad_accum_steps: int = 1
    # ZeRO-2-flavored accumulation (requires mesh.shard_opt_state AND
    # grad_accum_steps > 1): each micro-gradient is reduce-scattered inside
    # the scan and only this replica's 1/N flat shard accumulates — the
    # persistent accumulator drops from O(params) to O(params/N), at k
    # reduce-scatters per step instead of one (k× the scatter-leg wire
    # bytes: the explicit memory-for-bandwidth trade). See train/step.py.
    grad_accum_shard: bool = False

    # Exponential moving average of params (0 disables). When on, eval and
    # predict score the EMA weights by default (the TF-era ImageNet recipe);
    # the raw weights keep training. EMA state is checkpointed; restoring a
    # pre-EMA checkpoint with EMA enabled re-seeds the average from the
    # restored params.
    ema_decay: float = 0.0

    def __post_init__(self):
        # k=0 (a typo for 10?) would silently train the full-batch path —
        # the opposite of what the user asked for memory-wise
        if self.grad_accum_steps < 1:
            raise ValueError(
                f"train.grad_accum_steps must be >= 1, got "
                f"{self.grad_accum_steps}")
        if not 0.0 <= self.ema_decay < 1.0:
            raise ValueError(
                f"train.ema_decay must be in [0, 1), got {self.ema_decay}")
        if self.max_nonfinite_steps < 1:
            raise ValueError(
                f"train.max_nonfinite_steps must be >= 1, got "
                f"{self.max_nonfinite_steps}")
        if self.data_timeout_s < 0:
            raise ValueError(
                f"train.data_timeout_s must be >= 0, got "
                f"{self.data_timeout_s}")
        if self.data_timeout_retries < 0 or self.checkpoint_save_retries < 0:
            raise ValueError(
                "train.data_timeout_retries and train.checkpoint_save_"
                "retries must be >= 0, got "
                f"{self.data_timeout_retries}/{self.checkpoint_save_retries}")
        # parse errors in a chaos spec must fail at config time, not after
        # the mesh is up and the first steps have run
        from distributed_vgg_f_tpu.resilience.faults import FaultPlan
        FaultPlan.parse(self.fault_injection)
    # Keep the best-eval-top1 checkpoint under <checkpoint_dir>/best (one
    # slot, replaced whenever a periodic eval during fit() sets a new best;
    # Orbax best-metric retention, score in the metadata). Restore it with
    # `train.restore_from_best=true` (eval/predict modes included). Eval
    # results are identical on every host (psum), so the collective save
    # decision is consistent in multi-host runs.
    track_best_eval: bool = True
    # Restore from the best-eval slot (selected by recorded score) instead
    # of the latest checkpoint — for `--mode eval|predict` on the best
    # model, or to branch training from it. Falls back to the latest
    # checkpoint (with a logged notice) when no best slot exists.
    restore_from_best: bool = False
    # Graceful preemption: on SIGTERM (the TPU-VM / k8s preemption signal),
    # finish the in-flight step, force-save a checkpoint, and exit cleanly so
    # the next incarnation resumes exactly where this one stopped. Multi-host
    # runs reach stop-consensus via a per-step asynchronous one-scalar
    # collective (parallel/preempt.py): all hosts stop at the same step
    # within ~3 steps of the signal, independent of log_every and of the
    # logging cadence generally.
    handle_preemption: bool = True


@dataclass(frozen=True)
class CollectorConfig:
    """Fleet metrics collector (r22, telemetry/collector.py): ONE process
    that scrapes every per-process exporter endpoint and serves the merged
    fleet view (/fleetz, one aggregated /metrics, quorum stall verdict
    with stragglers named). Off by default: big fleets run it as its own
    process (`python -m distributed_vgg_f_tpu.telemetry.collector`);
    enabling it here starts an in-process collector on rank 0."""
    # Start the in-process collector on rank 0 (requires telemetry.enabled
    # and, to have anything to scrape, telemetry.exporter on the ranks).
    enabled: bool = False
    # Scrape interval in seconds — every endpoint is polled once per cycle.
    interval_s: float = 1.0
    # Bind host for the fleet view; loopback by default (unauthenticated
    # process internals, same contract as the per-process exporter).
    host: str = "127.0.0.1"
    # Bind port for /fleetz + aggregated /metrics (0 = OS-assigned, logged).
    port: int = 0
    # Static scrape targets beyond sidecar discovery: `host:port`,
    # `role@host:port`, or `role[N]@host:port` entries (a serving box,
    # workers on another host).
    endpoints: Sequence[str] = ()
    # Directory holding exporter_p<rank>.jsonl discovery sidecars
    # ("" = use telemetry.sidecar_dir).
    sidecar_dir: str = ""
    # Append the per-cycle schema-validated fleet JSONL here ("" = off).
    fleet_log: str = ""
    # Seconds without a successful scrape before an endpoint's entry reads
    # `stale` (the entry keeps its last verdict + an age, never vanishes).
    stale_after_s: float = 10.0
    # Per-request scrape timeout — a hanging endpoint costs one cycle this
    # much, then degrades to stale; it never wedges the collector.
    scrape_timeout_s: float = 2.0

    def __post_init__(self):
        if self.interval_s <= 0:
            raise ValueError(
                f"telemetry.collector.interval_s must be > 0, got "
                f"{self.interval_s}")
        if not 0 <= self.port <= 65535:
            raise ValueError(
                f"telemetry.collector.port must be in [0, 65535], got "
                f"{self.port}")
        if self.stale_after_s < 0:
            raise ValueError(
                f"telemetry.collector.stale_after_s must be >= 0, got "
                f"{self.stale_after_s}")
        if self.scrape_timeout_s <= 0:
            raise ValueError(
                f"telemetry.collector.scrape_timeout_s must be > 0, got "
                f"{self.scrape_timeout_s}")


@dataclass(frozen=True)
class TelemetryConfig:
    """Unified observability layer (distributed_vgg_f_tpu/telemetry/):
    always-on span ring buffer + counter registry + per-step stall
    attribution. On by default — the whole design point is that it is cheap
    enough to leave on (the host bench's telemetry-overhead receipt is the
    proof); `enabled=false` is the kill-switch."""
    enabled: bool = True
    # Span ring-buffer capacity (spans, not bytes; ~100 B each). The ring
    # keeps the NEWEST spans — the window a stall diagnosis needs.
    span_capacity: int = 8192
    # Write the span buffer as Chrome trace-event JSON here at the end of
    # fit() ("" = off). Loadable in Perfetto next to (or instead of) a
    # jax.profiler window; multi-process runs insert `_p<rank>` before the
    # extension.
    trace_export: str = ""
    # Per-process telemetry JSONL sidecars under this directory ("" = off):
    # each process writes telemetry_p<rank>.jsonl (full registry snapshot +
    # span stats); process 0 additionally aggregates counters across hosts
    # into telemetry_aggregate.json.
    sidecar_dir: str = ""
    # Per-log-window stall attribution in the "train" step records
    # (telemetry/stall.py verdict taxonomy).
    stall_attribution: bool = True
    # Fraction of a log window spent blocked on the input pipeline /
    # checkpoint machinery before the window is attributed to it.
    infeed_threshold: float = 0.25
    checkpoint_threshold: float = 0.25   # same contract, checkpoint machinery
    # Live observability endpoint (telemetry/exporter.py): a per-process
    # background HTTP server serving /metrics (Prometheus text), /healthz,
    # /stallz, and /trace WHILE the run is alive. Off by default (the
    # fit-finally export covers offline analysis); the committed
    # scrape-under-load receipt (benchmarks/runs/) is the proof it fits
    # the <2 % telemetry budget when on.
    exporter: bool = False
    # 0 = bind an OS-assigned free port (the multi-host default — N
    # processes per host never collide); the bound port is logged and
    # written to the run sidecar (exporter_p<rank>.jsonl).
    exporter_port: int = 0
    # Loopback by default: the exporter serves unauthenticated process
    # internals — exposing it beyond the host is an explicit decision.
    exporter_host: str = "127.0.0.1"
    # /healthz flips to "stalled" (HTTP 503) once the trainer heartbeat is
    # older than this many seconds.
    exporter_stalled_after_s: float = 120.0
    # Flight recorder (telemetry/flight.py): always-on bounded ring of
    # per-log-window summaries, dumped as a schema-validated black box on
    # diagnosed aborts (non-finite abort, data stall, injected crash,
    # unhandled exception).
    flight_windows: int = 64
    # Where the black box lands ("" = first configured of sidecar_dir,
    # then <checkpoint_dir>/flight; with neither, the dump is skipped with
    # a logged event — the ring still serves /stallz).
    flight_dir: str = ""
    # Fleet collector (r22): the cross-process aggregation plane over the
    # per-process exporters — see CollectorConfig.
    collector: CollectorConfig = field(default_factory=CollectorConfig)

    def __post_init__(self):
        if self.span_capacity < 1:
            raise ValueError(
                f"telemetry.span_capacity must be >= 1, got "
                f"{self.span_capacity}")
        for name in ("infeed_threshold", "checkpoint_threshold"):
            v = getattr(self, name)
            if not 0.0 < v <= 1.0:
                raise ValueError(
                    f"telemetry.{name} must be in (0, 1], got {v}")
        if not 0 <= self.exporter_port <= 65535:
            raise ValueError(
                f"telemetry.exporter_port must be in [0, 65535], got "
                f"{self.exporter_port}")
        if self.exporter_stalled_after_s <= 0:
            raise ValueError(
                f"telemetry.exporter_stalled_after_s must be > 0, got "
                f"{self.exporter_stalled_after_s}")
        if self.flight_windows < 1:
            raise ValueError(
                f"telemetry.flight_windows must be >= 1, got "
                f"{self.flight_windows}")


@dataclass(frozen=True)
class ExperimentConfig:
    """The config-tree root: one section dataclass per subsystem, addressed
    from the CLI as `--set <section>.<field>=<value>` (`name` labels the
    preset/run). Sections: `model`, `optim`, `data`, `mesh`, `train`,
    `telemetry`, `serving`."""
    name: str = "vggf_synthetic"
    model: ModelConfig = field(default_factory=ModelConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    data: DataConfig = field(default_factory=DataConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    telemetry: TelemetryConfig = field(default_factory=TelemetryConfig)
    # Always-on dynamic-batching predict server (r17, serving/): off by
    # default; `--mode serve` requires the explicit serving.enabled opt-in.
    serving: ServingConfig = field(default_factory=ServingConfig)

    @property
    def steps_per_epoch(self) -> int:
        return max(1, self.data.num_train_examples // self.data.global_batch_size)

    @property
    def total_steps(self) -> int:
        if self.train.steps > 0:
            return self.train.steps
        return int(self.train.epochs * self.steps_per_epoch)

    @property
    def scaled_lr(self) -> float:
        """Linear LR scaling with global batch (Goyal et al. practice)."""
        return self.optim.base_lr * (
            self.data.global_batch_size / self.optim.reference_batch_size
        )


def _replace(cfg, **kw):
    return dataclasses.replace(cfg, **kw)


#: Datasets whose host pipeline actually implements the packed layout. A
#: dataset outside this set combined with space_to_depth=True must be
#: rejected, not silently fed unpacked (ADVICE r2: cifar10 passed the
#: model/size guard but its builder ignores the flag).
SPACE_TO_DEPTH_DATASETS = frozenset({"synthetic", "imagenet"})


def supports_space_to_depth(model_name: str, image_size: int,
                            dataset_name: str | None = None) -> bool:
    """Packed-input eligibility — the single definition of which configs may
    set `data.space_to_depth`. The MODEL half now comes from the per-model
    ingest descriptor (models/ingest.py, r13: the zoo contract table that
    replaced the VGGF-only wiring); the trainer validates against this and
    the benches use it so they measure the same layout production trains
    with. Pass `dataset_name` to also require a host pipeline that
    implements packing."""
    from distributed_vgg_f_tpu.models.ingest import ingest_descriptor
    return ingest_descriptor(model_name).space_to_depth \
        and image_size % 4 == 0 and (
            dataset_name is None or dataset_name in SPACE_TO_DEPTH_DATASETS)


def zoo_data(base: DataConfig, model_name: str) -> DataConfig:
    """Derive one zoo preset's data config from the flagship's by applying
    the model's ingest descriptor (models/ingest.py) — wire, packed-layout
    eligibility, and normalize constants all come from the per-model
    table, so presets no longer hand-override `data` per model (the r12
    'override `data` back to the raw layout' wiring this replaces). The
    u8 wire, snapshot cache, autotuner, and device-side augmentation all
    ride along unchanged: ONE ingest contract for the whole zoo."""
    from distributed_vgg_f_tpu.models.ingest import ingest_descriptor
    d = ingest_descriptor(model_name)
    return _replace(base, wire=d.wire, space_to_depth=d.space_to_depth,
                    mean_rgb=tuple(d.mean_rgb), stddev_rgb=tuple(d.stddev_rgb))


# ---------------------------------------------------------------------------
# Presets — one per BASELINE.json "configs" entry.
# ---------------------------------------------------------------------------

def _vggf_cifar10_smoke() -> ExperimentConfig:
    """BASELINE config #1: VGG-F on CIFAR-10, single process (CPU/1-chip smoke)."""
    return ExperimentConfig(
        name="vggf_cifar10_smoke",
        model=ModelConfig(name="vggf", num_classes=10, compute_dtype="float32"),
        optim=OptimConfig(base_lr=0.01, weight_decay=5e-4,
                          decay_epochs=(40.0, 70.0), reference_batch_size=128),
        data=DataConfig(name="cifar10", image_size=32, global_batch_size=128,
                        num_train_examples=50_000, num_eval_examples=10_000,
                        mean_rgb=(125.3, 123.0, 113.9), stddev_rgb=(63.0, 62.1, 66.7)),
        train=TrainConfig(epochs=10.0, log_every=50, checkpoint_every_steps=500,
                          resume_data_fast_forward=True),
    )


def _vggf_imagenet_dp() -> ExperimentConfig:
    """BASELINE config #2: VGG-F ImageNet-1k, DP over the full mesh (psum all-reduce)."""
    return ExperimentConfig(
        name="vggf_imagenet_dp",
        model=ModelConfig(name="vggf", num_classes=1000),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=256,
                          weight_decay=5e-4, decay_epochs=(30.0, 60.0, 80.0)),
        # space_to_depth: the stem consumes the packed 4x4 layout (+3.7%
        # device step; per-model declaration in models/ingest.py — the
        # derived zoo presets below apply THEIR descriptors via zoo_data).
        # wire='u8' (r8): the flagship ships the uint8 ingest wire — raw
        # pixels on the host, normalize/cast/s2d fused into the device
        # step — the basis of HOST_DECODE_RATE_R8 and the provisioning
        # table; refused builds fall back to the host wire with a logged
        # warning.
        # autotune on (r11): the flagship self-tunes its ingest from the
        # stall attributor's verdicts instead of inheriting one box's bench
        # pins — heterogeneous host classes feeding the same mesh each
        # converge to their own knob settings. DVGGF_AUTOTUNE=0 kills it.
        # augment (r13): fused on-device flips + mixup — diversity at zero
        # host cost (the host never flips; data/augment.py owns it inside
        # the jitted step). data.augment.enabled=false is the kill-switch
        # (structurally absent, byte-identical trajectory — pinned).
        data=zoo_data(
            DataConfig(name="imagenet", image_size=224,
                       global_batch_size=1024,
                       autotune=AutotuneConfig(enabled=True),
                       augment=AugmentConfig(enabled=True, hflip=True,
                                             mixup_alpha=0.2)),
            "vggf"),
        # ZeRO-1 optimizer-state sharding (r13, ROADMAP item 4 first
        # slice): ~90% of VGG-F's params sit in three FC layers (arXiv
        # 2004.13336's exact workload) — replicated momentum burns per-chip
        # HBM the sharded update reclaims. The step body and checkpoint
        # retopology already compose (parallel/zero.py, r1–r5 tests); this
        # flips the flagship on, with the CPU-mesh loss-trajectory parity
        # pin in tests/test_zero1.py. Single-process CPU smoke runs
        # downgrade themselves (one shard = replicated). The device HBM
        # receipt stays queued for the next TPU grant (tpu_session_r10.sh).
        # ZeRO-2 + bucketed overlap (r14): gradients held only as 1/N
        # shards and the exchange issued as 4 MB buckets in
        # reverse-backward order, so the scatter runs under the remaining
        # backward instead of after it (parallel/buckets.py; CPU
        # loss-trajectory parity + lowered-HLO overlap evidence pinned in
        # tests/test_comm_buckets.py; step-time/HBM receipts queued in
        # tpu_session_r11.sh).
        mesh=MeshConfig(shard_opt_state=True, shard_gradients=True,
                        comm_bucket_mb=4.0),
        train=TrainConfig(epochs=90.0),
    )


def _vgg16_imagenet() -> ExperimentConfig:
    """BASELINE config #3: VGG-16 ImageNet-1k (deeper conv stack, same DP path)."""
    base = _vggf_imagenet_dp()
    return _replace(
        base,
        name="vgg16_imagenet",
        model=ModelConfig(name="vgg16", num_classes=1000),
        optim=OptimConfig(base_lr=0.01, reference_batch_size=256, weight_decay=5e-4,
                          decay_epochs=(30.0, 60.0, 80.0), warmup_epochs=2.0),
        # first-class consumer of the SAME u8-wire + device-ingest contract
        # (r13): the model's ingest descriptor decides layout/constants —
        # no hand-override back to the raw layout
        data=zoo_data(base.data, "vgg16"),
    )


def _resnet50_imagenet() -> ExperimentConfig:
    """BASELINE config #4: ResNet-50 ImageNet-1k with cross-replica sync-BN."""
    base = _vggf_imagenet_dp()
    return _replace(
        base,
        name="resnet50_imagenet",
        model=ModelConfig(name="resnet50", num_classes=1000, dropout_rate=0.0),
        optim=OptimConfig(base_lr=0.1, reference_batch_size=256, weight_decay=1e-4,
                          decay_epochs=(30.0, 60.0, 80.0), warmup_epochs=5.0),
        # first-class consumer of the SAME u8-wire + device-ingest contract
        # (r13): the model's ingest descriptor decides layout/constants
        data=zoo_data(base.data, "resnet50"),
    )


def _vit_s16_imagenet() -> ExperimentConfig:
    """BASELINE config #5: ViT-S/16 ImageNet-1k under the same DP all-reduce."""
    base = _vggf_imagenet_dp()
    return _replace(
        base,
        name="vit_s16_imagenet",
        # dropout 0.1 on MLP/residual/embedding; attention-WEIGHT dropout is
        # 0.0 by model default (canonical DeiT-S / official ViT recipes; the
        # (B,H,197,197) mask RNG cost ~10% of the TPU step — r3 trace).
        # Re-enable with --set model.extra.attention_dropout_rate=0.1.
        model=ModelConfig(name="vit_s16", num_classes=1000, dropout_rate=0.1),
        optim=OptimConfig(base_lr=1e-3, reference_batch_size=1024, momentum=0.9,
                          weight_decay=1e-4, schedule="cosine", warmup_epochs=5.0),
        # first-class consumer of the SAME u8-wire + device-ingest contract
        # (r13): the model's ingest descriptor decides layout/constants
        data=zoo_data(base.data, "vit_s16"),
        train=TrainConfig(epochs=300.0),
    )


def _vggf_synthetic() -> ExperimentConfig:
    """Synthetic-data variant used by tests and the throughput benchmark."""
    return ExperimentConfig(
        name="vggf_synthetic",
        model=ModelConfig(name="vggf", num_classes=1000),
        data=DataConfig(name="synthetic", image_size=224, global_batch_size=256,
                        num_train_examples=100_000),
        train=TrainConfig(steps=100, log_every=10),
    )


def _vggf_teacher() -> ExperimentConfig:
    """Offline generalization config (data/teacher.py): fixed random teacher
    labels, augmented+noisy train split, disjoint clean val split — the run
    that demonstrates a real train/val gap without external data
    (VERDICT r2 #3; benchmarks/teacher_generalization.py)."""
    return ExperimentConfig(
        name="vggf_teacher",
        # Tuned to the task's measured dynamics (loss plateaus ~250 steps
        # before breaking through): weight_decay well below the CIFAR preset
        # (a 5e-4 L2 term matches the CE loss in magnitude and pins the net
        # at the zero function — top-1 stuck ≈ 0.13), lr modest (0.05
        # produced a grad spike that killed the ReLUs — gnorm 24 → 0.006),
        # clipping as the spike guard.
        model=ModelConfig(name="vggf", num_classes=10,
                          compute_dtype="float32", dropout_rate=0.2),
        optim=OptimConfig(base_lr=0.02, reference_batch_size=64,
                          weight_decay=5e-5, warmup_epochs=1.0,
                          grad_clip_norm=1.0, decay_epochs=(24.0, 30.0)),
        data=DataConfig(name="teacher", image_size=32, global_batch_size=64,
                        num_train_examples=4096, num_eval_examples=1024),
        train=TrainConfig(epochs=32.0, log_every=64,
                          eval_every_steps=256),
    )


PRESETS = {
    "vggf_cifar10_smoke": _vggf_cifar10_smoke,
    "vggf_imagenet_dp": _vggf_imagenet_dp,
    "vgg16_imagenet": _vgg16_imagenet,
    "resnet50_imagenet": _resnet50_imagenet,
    "vit_s16_imagenet": _vit_s16_imagenet,
    "vggf_synthetic": _vggf_synthetic,
    "vggf_teacher": _vggf_teacher,
}


def get_config(name: str) -> ExperimentConfig:
    try:
        return PRESETS[name]()
    except KeyError:
        raise KeyError(f"unknown config {name!r}; available: {sorted(PRESETS)}")


_BOOL_WORDS = {"true": True, "1": True, "yes": True, "on": True,
               "false": False, "0": False, "no": False, "off": False}


def _coerce_override(current: Any, value: Any) -> Any:
    """Cast a CLI override string to the type of the field it replaces.

    bool must be handled before int (bool is an int subclass) and never via
    ``bool(str)``, which is True for any non-empty string including "false".
    Sequence fields accept comma-separated values typed like their current
    elements (e.g. ``optim.decay_epochs=20,40`` -> ``(20.0, 40.0)``).
    """
    if current is None:
        return value
    same_boolness = isinstance(value, bool) == isinstance(current, bool)
    if isinstance(value, type(current)) and same_boolness:
        return value
    if isinstance(current, bool):
        word = str(value).strip().lower()
        if word not in _BOOL_WORDS:
            raise ValueError(
                f"boolean override needs true/false/1/0/yes/no/on/off, got {value!r}")
        return _BOOL_WORDS[word]
    if isinstance(current, (int, float)):
        return type(current)(value)
    if isinstance(current, str):
        return str(value)
    if isinstance(current, Sequence) and not isinstance(current, (str, bytes)):
        elem_type = type(current[0]) if len(current) else str
        if isinstance(value, str):
            return tuple(elem_type(v.strip()) for v in value.split(",") if v.strip())
        if not isinstance(value, Sequence):
            value = (value,)
        return tuple(elem_type(v) for v in value)
    return value


def parse_extra_value(value: Any) -> Any:
    """Public alias of `_parse_literal` for out-of-package callers that
    accept `model.extra`-style KEY=VALUE strings (bench.py --model-extra)."""
    return _parse_literal(value)


def _parse_literal(value: Any) -> Any:
    """Best-effort typing for dict entries with no existing value to mirror
    (e.g. a fresh ``model.extra`` key): numbers first, then the WORD-only
    bool spellings, then the raw string. "1"/"0" must parse as ints here —
    with no existing value there is nothing marking them booleans, and a
    numeric key silently becoming `True` breaks dtype inference downstream
    (code-review r3)."""
    if not isinstance(value, str):
        return value
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            continue
    word = value.strip().lower()
    # (the numeric casts above already returned for "1"/"0", which is what
    # guarantees they parse as ints even though _BOOL_WORDS lists them)
    if word in _BOOL_WORDS:
        return _BOOL_WORDS[word]
    return value


def _set_path(obj: Any, parts: Sequence[str], value: Any) -> Any:
    """Immutably set a dotted path through dataclasses AND Mappings (the
    ``model.extra`` dict takes model-specific keys, so overrides like
    ``model.extra.attention_dropout_rate=0.1`` must descend into it)."""
    name = parts[0]
    if isinstance(obj, Mapping):
        current = obj.get(name)
        if len(parts) == 1:
            new_leaf = (_parse_literal(value) if current is None
                        or isinstance(current, Mapping)
                        else _coerce_override(current, value))
            return {**obj, name: new_leaf}
        if current is None:
            raise KeyError(
                f"cannot descend into missing dict key {name!r} "
                f"(remaining path: {'.'.join(parts[1:])})")
        return {**obj, name: _set_path(current, parts[1:], value)}
    current = getattr(obj, name)
    if len(parts) == 1:
        if not isinstance(current, Mapping):
            value = _coerce_override(current, value)
        return dataclasses.replace(obj, **{name: value})
    return dataclasses.replace(obj, **{name: _set_path(current, parts[1:], value)})


def apply_overrides(cfg: ExperimentConfig, overrides: Mapping[str, Any]) -> ExperimentConfig:
    """Apply dotted-path overrides, e.g. {"data.global_batch_size": 512}."""
    for path, value in overrides.items():
        cfg = _set_path(cfg, path.split("."), value)
    return cfg


def fold_override_items(items: Sequence[str] | None) -> dict:
    """`--set KEY=VALUE` entries → the overrides dict `apply_overrides`
    takes. The ONE folding implementation shared by the trainer CLI
    (parse_cli) and bench.py's --set — duplicate loops drifted on
    validation (one rejected '='-less items, one silently took them as
    empty-string overrides)."""
    overrides = {}
    for item in items or ():
        key, sep, value = item.partition("=")
        if not sep or not key:
            raise ValueError(f"override needs KEY=VALUE, got {item!r}")
        overrides[key] = value
    return overrides


def parse_cli(argv: Sequence[str] | None = None, *, with_mode: bool = False):
    parser = argparse.ArgumentParser(description="distributed_vgg_f_tpu trainer")
    parser.add_argument("--config", default="vggf_cifar10_smoke",
                        help=f"preset name, one of {sorted(PRESETS)}")
    parser.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                        help="dotted override, e.g. --set data.global_batch_size=512")
    parser.add_argument("--mode",
                        choices=("train", "eval", "predict", "serve"),
                        default="train",
                        help="train (default), a standalone eval pass from "
                             "the latest checkpoint, predict: classify "
                             "--images files with the latest checkpoint, "
                             "or serve: the always-on dynamic-batching "
                             "predict server (serving/, requires "
                             "serving.enabled=true)")
    parser.add_argument("--images", nargs="*", default=[], metavar="PATH",
                        help="predict mode: JPEG files and/or directories "
                             "(searched for *.jpg/*.jpeg/*.JPEG)")
    args = parser.parse_args(argv)
    cfg = get_config(args.config)
    try:
        cfg = apply_overrides(cfg, fold_override_items(args.set))
    except ValueError as e:
        parser.error(str(e))
    return (cfg, args) if with_mode else cfg
