"""distributed_vgg_f_tpu — a TPU-native synchronous data-parallel training framework.

A from-scratch JAX/XLA rebuild of the capability surface of the reference repo
``edwhere/Distributed-VGG-F`` (see SURVEY.md; the reference mount was empty at survey
time, so the blueprint is the reconstructed survey + BASELINE.json north_star):

- VGG-F / VGG-16 / ResNet-50 (sync-BN) / ViT-S/16 image classifiers (``models/``),
- softmax-CE + L2 loss, top-1/top-5 metrics, LRN op (``ops/``),
- synchronous data parallelism over a ``jax.sharding.Mesh`` with explicit
  ``lax.pmean`` gradient all-reduce inside one jitted train step (``parallel/``,
  ``train/``) — the TPU-native equivalent of the reference's NCCL/MPI ring
  all-reduce worker sync step,
- host-side input pipelines (``data/``), Orbax checkpointing (``checkpoint/``),
- structured metrics/throughput logging (``utils/``).
"""

__version__ = "0.1.0"
