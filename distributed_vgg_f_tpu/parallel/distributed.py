"""Multi-host runtime initialization.

Reference equivalent (SURVEY.md §3.2): MPI_Init / tf.train.Server role dispatch.
On TPU all hosts are symmetric SPMD workers: `jax.distributed.initialize()` wires
the coordination service; afterwards `jax.devices()` spans every chip in the slice
and meshes built over it ride ICI within a slice and DCN across slices.
"""

from __future__ import annotations

import glob
import json
import logging
import os

import jax

from distributed_vgg_f_tpu import telemetry

log = logging.getLogger(__name__)


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Initialize the JAX distributed runtime when running multi-host.

    No-op when single-process (the common case on this machine, and in tests).
    On Cloud TPU VMs, `jax.distributed.initialize()` with no arguments
    auto-discovers the cluster from the TPU metadata — the moral equivalent of
    `mpirun` wiring up ranks in the reference.
    """
    explicit = coordinator_address is not None
    auto = any(os.environ.get(v) for v in
               ("MEGASCALE_COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS"))
    if not (explicit or auto):
        # IMPORTANT: return without touching jax at all — even
        # jax.process_count() initializes the XLA backend, after which
        # jax.distributed.initialize refuses to run (caught by
        # tests/test_multihost.py).
        log.info("single-process run; skipping jax.distributed.initialize")
        return
    kwargs = {}
    if explicit:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    # jaxlib 0.4.x builds the CPU client WITHOUT a cross-process collectives
    # layer unless told which one to use — multiprocess CPU compiles then
    # fail with "Multiprocess computations aren't implemented on the CPU
    # backend" (hit by the dryrun gloo phase and the two-process tests on
    # this image). Newer jax defaults the option to gloo and eventually
    # drops it, so set it best-effort; TPU clients ignore it.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # option gone (newer jax) — default
        pass                              # is already gloo there
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # Already initialized (e.g. the Trainer's no-arg call after the CLI
        # already wired the cluster), or backend already up in a
        # single-process tool — proceed rather than abort.
        log.warning("jax.distributed.initialize skipped: %s", e)
        return
    log.info("distributed initialized: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def coordination_barrier(tag: str, *, timeout_ms: int = 600_000) -> bool:
    """Align every process at a named barrier via the coordination service —
    plain gRPC to the coordinator, NOT a device collective.

    Why it exists: the first collective execution of a run triggers Gloo's
    TCP rendezvous, which has a fixed ~30 s key-value deadline, while ranks
    can reach that first collective with much larger skew (per-rank dataset
    build, tracing, contended-host compilation — observed >30 s on this
    1-vCPU box with 4 ranks, failing Gloo context init with
    DEADLINE_EXCEEDED). This barrier carries an explicit long timeout, so
    aligning on it first keeps the subsequent rendezvous skew to
    milliseconds. Returns False (no-op) when single-process or no
    coordination client is wired.
    """
    from jax._src import distributed as _dist  # no public barrier API
    client = getattr(_dist.global_state, "client", None)
    if client is None:
        return False
    # "coord" span: barrier wait time IS the inter-rank skew — on the trace
    # it shows which rank the others were waiting for.
    with telemetry.span(f"barrier_{tag}", "coord"):
        client.wait_at_barrier(f"dvggf_{tag}", timeout_ms)
    telemetry.inc("distributed/barriers")
    return True


# ---------------------------------------------------------------------------
# Telemetry sidecars: per-process JSONL, process 0 aggregates.
# ---------------------------------------------------------------------------

def telemetry_sidecar_path(base_dir: str, prefix: str = "telemetry") -> str:
    """This process's telemetry sidecar file. One file per process — hosts
    never contend on a shared writer; the rank is in the name so the
    aggregate (and a human) can attribute counters to hosts."""
    return os.path.join(base_dir, f"{prefix}_p{jax.process_index():05d}.jsonl")


def write_telemetry_sidecar(base_dir: str, record: dict,
                            prefix: str = "telemetry") -> str:
    """Append one JSON record (registry snapshot + span stats, stamped with
    the process index) to this process's sidecar. Returns the path."""
    os.makedirs(base_dir, exist_ok=True)
    path = telemetry_sidecar_path(base_dir, prefix)
    with open(path, "a", buffering=1) as f:
        f.write(json.dumps({"process": jax.process_index(), **record},
                           allow_nan=False) + "\n")
    return path


def aggregate_telemetry_sidecars(base_dir: str,
                                 prefix: str = "telemetry",
                                 expected_processes: int | None = None,
                                 ) -> dict:
    """Process-0 aggregation over every sidecar present (shared filesystem,
    the same contract Orbax relies on): COUNTERS summed across processes;
    GAUGES kept per-rank (summing instantaneous values — four ranks'
    queue_depth=2 → "8" — would fabricate a number nobody measured).
    Best-effort by design — a crashed rank's missing sidecar degrades the
    aggregate instead of hanging the survivors.

    `expected_processes` (the live run passes jax.process_count()) caps the
    rank range: a run reusing a sidecar_dir left by a LARGER previous run
    must not fold the stale ranks' files into its own aggregate (the
    current ranks' files are append-mode, so taking each file's LAST
    record already excludes their old runs). Offline analysis of a
    finished run's directory omits it and reads every rank."""
    processes = {}
    counters: dict = {}
    gauges: dict = {}
    for path in sorted(glob.glob(
            os.path.join(base_dir, f"{prefix}_p*.jsonl"))):
        if expected_processes is not None:
            try:
                rank = int(os.path.basename(path)[len(prefix) + 2:-6])
            except ValueError:
                continue
            if rank >= expected_processes:
                continue  # stale sidecar from a larger previous run
        last = None
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        last = json.loads(line)
                    except ValueError:
                        continue  # torn tail write from a dying rank
        if last is None:
            continue
        proc = int(last.get("process", -1))
        processes[proc] = os.path.basename(path)
        for name, value in (last.get("counters") or {}).items():
            if isinstance(value, (int, float)):
                counters[name] = counters.get(name, 0) + value
        for name, value in (last.get("gauges") or {}).items():
            if isinstance(value, (int, float)):
                gauges.setdefault(name, {})[str(proc)] = value
    return {"processes": len(processes), "counters": counters,
            "gauges_by_process": gauges,
            "sidecars": [processes[p] for p in sorted(processes)]}
