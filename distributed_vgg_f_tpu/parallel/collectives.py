"""Cross-replica collectives used inside jitted SPMD computations.

The reference synchronizes exactly once per step: a ring all-reduce of gradients
before the optimizer apply (SURVEY.md §3.1 [SYNC] point). Here that is a
`lax.pmean` over the mesh's data axis, executed *inside* the single XLA train-step
computation so XLA schedules the ICI all-reduce and overlaps it with backward
compute where possible.

`pmean` (not `psum`) is chosen deliberately: the reference applies averaged
gradients (synchronous replicated SGD semantics — SURVEY.md §2.4), and pmean keeps
the update invariant to the number of replicas.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax


def cast_to_wire(x, wire_dtype):
    """THE gradient-wire cast (mesh.reduce_dtype): every exchange leg —
    per-leaf pmean, bucketed pmean, flat and per-bucket psum_scatter —
    narrows through this one function, so the cast-before-collective
    ordering (and the clip-after-cast semantics it implies, pinned in
    tests/test_comm_buckets.py) cannot drift between paths. None/same
    dtype = no-op; the param all-gather leg never calls it (params must
    re-sync bit-exactly)."""
    if wire_dtype is None:
        return x
    import jax.numpy as jnp

    wire = jnp.dtype(wire_dtype)
    return x if x.dtype == wire else x.astype(wire)


def cast_from_wire(x, dtype):
    """Inverse leg of `cast_to_wire`: bring a reduced wire-dtype payload
    back to the compute dtype for the optimizer."""
    return x if x.dtype == dtype else x.astype(dtype)


def all_reduce_gradients(grads: Any, axis_name: str = "data",
                         reduce_dtype: Any = None) -> Any:
    """Mean-all-reduce a gradient pytree across the named mesh axis.

    TPU-native equivalent of the reference's NCCL/MPI ring all-reduce worker sync
    step. Must be called inside a computation that binds `axis_name`
    (shard_map'd train step).

    `reduce_dtype` (e.g. jnp.bfloat16; mesh.reduce_dtype) casts each leaf
    for the WIRE only — halving collective bytes — and casts back to the
    leaf's own dtype for the optimizer. fp32 leaves lose ~16 mantissa bits
    of gradient precision; momentum and params are untouched. None/same
    dtype = no-op."""
    if reduce_dtype is None:
        return lax.pmean(grads, axis_name=axis_name)

    def reduce_leaf(g):
        return cast_from_wire(
            lax.pmean(cast_to_wire(g, reduce_dtype), axis_name=axis_name),
            g.dtype)

    return jax.tree.map(reduce_leaf, grads)


def cross_replica_sum(x: Any, axis_name: str = "data") -> Any:
    return lax.psum(x, axis_name=axis_name)


def cross_replica_mean(x: Any, axis_name: str = "data") -> Any:
    return lax.pmean(x, axis_name=axis_name)


def replica_index(axis_name: str = "data"):
    """Index of this replica along the data axis (the reference's MPI rank
    analogue); used e.g. to fold per-replica dropout RNG keys."""
    return lax.axis_index(axis_name)


def fold_rng_per_replica(rng: jax.Array, axis_name: str = "data") -> jax.Array:
    """Derive a per-replica RNG key so dropout masks differ across replicas —
    the classic SPMD correctness trap (SURVEY.md §7 hard parts)."""
    return jax.random.fold_in(rng, replica_index(axis_name))
