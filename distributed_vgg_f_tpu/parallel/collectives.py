"""Cross-replica collectives used inside jitted SPMD computations.

The reference synchronizes exactly once per step: a ring all-reduce of gradients
before the optimizer apply (SURVEY.md §3.1 [SYNC] point). Here that is a
`lax.pmean` over the mesh's data axis, executed *inside* the single XLA train-step
computation so XLA schedules the ICI all-reduce and overlaps it with backward
compute where possible.

`pmean` (not `psum`) is chosen deliberately: the reference applies averaged
gradients (synchronous replicated SGD semantics — SURVEY.md §2.4), and pmean keeps
the update invariant to the number of replicas.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax


def all_reduce_gradients(grads: Any, axis_name: str = "data",
                         reduce_dtype: Any = None) -> Any:
    """Mean-all-reduce a gradient pytree across the named mesh axis.

    TPU-native equivalent of the reference's NCCL/MPI ring all-reduce worker sync
    step. Must be called inside a computation that binds `axis_name`
    (shard_map'd train step).

    `reduce_dtype` (e.g. jnp.bfloat16; mesh.reduce_dtype) casts each leaf
    for the WIRE only — halving collective bytes — and casts back to the
    leaf's own dtype for the optimizer. fp32 leaves lose ~16 mantissa bits
    of gradient precision; momentum and params are untouched. None/same
    dtype = no-op."""
    if reduce_dtype is None:
        return lax.pmean(grads, axis_name=axis_name)
    import jax.numpy as jnp

    wire = jnp.dtype(reduce_dtype)

    def reduce_leaf(g):
        if g.dtype == wire:
            return lax.pmean(g, axis_name=axis_name)
        return lax.pmean(g.astype(wire), axis_name=axis_name).astype(g.dtype)

    return jax.tree.map(reduce_leaf, grads)


def cross_replica_sum(x: Any, axis_name: str = "data") -> Any:
    return lax.psum(x, axis_name=axis_name)


def cross_replica_mean(x: Any, axis_name: str = "data") -> Any:
    return lax.pmean(x, axis_name=axis_name)


def replica_index(axis_name: str = "data"):
    """Index of this replica along the data axis (the reference's MPI rank
    analogue); used e.g. to fold per-replica dropout RNG keys."""
    return lax.axis_index(axis_name)


def fold_rng_per_replica(rng: jax.Array, axis_name: str = "data") -> jax.Array:
    """Derive a per-replica RNG key so dropout masks differ across replicas —
    the classic SPMD correctness trap (SURVEY.md §7 hard parts)."""
    return jax.random.fold_in(rng, replica_index(axis_name))
