"""Bucketed, overlap-capable gradient exchange (ISSUE 11 / ROADMAP item 5).

The step used to run compute-then-exchange: the whole backward pass finished
before a single monolithic collective moved every gradient byte — one
`pmean` per LEAF in plain DP (dozens of small collectives, all emitted after
the full backward in trace order) and, worse, ONE flat `psum_scatter` of the
entire padded parameter vector under ZeRO sharding: a collective whose
operand depends on every backward op, i.e. a pure serial tail at pod scale.

This module is the classic fix (communication scheduling — arXiv 1711.00705,
arXiv 1603.02339): partition the parameter pytree into size-targeted
BUCKETS ordered by reverse-backward position (the last layers' gradients are
ready first, so bucket 0 can hit the wire while the convs are still
back-propagating) and issue each bucket's collective independently:

  - plain DP: one `pmean` per bucket (groups the per-leaf all-reduces into
    ICI-friendly message sizes without serializing them behind the full
    backward);
  - ZeRO-1/2: one `psum_scatter` per bucket — each bucket's gradients are
    reduce-scattered to their 1/N shard AS SOON AS THEY EXIST, so the
    full-size flat send buffer of the monolithic path never materializes
    and XLA's latency-hiding scheduler can run bucket k's collective under
    the backward compute that feeds bucket k+1.

The overlap claim is STRUCTURAL, not aspirational, and `hlo_overlap_report`
is the committed assertion: it parses a lowered step and proves that (a)
the exchange lowered to >= 2 gradient-sized collectives and (b) there
exists a (collective, backward-matmul/conv) pair with NO dependency path in
either direction — exactly the property a latency-hiding scheduler needs to
run them concurrently. The monolithic scatter fails (b) by construction
(every backward op is its ancestor).

ZeRO shard layout under bucketing
---------------------------------
Scattering per bucket changes which elements each replica owns: replica r
holds piece r OF EACH BUCKET, not the r-th contiguous slice of the
canonical (tree_leaves-order) flat vector. The persistent flat layout is
therefore **bucket-major, replica-interleaved**:

    global[(r * S) + off_b : (r * S) + off_b + s_b] = bucket_b[r*s_b : (r+1)*s_b]

with S = sum(s_b) the per-replica shard length and off_b the running shard
offset of bucket b. `to_global`/`from_global` are the exact (static, pure)
permutations between this layout and the params tree, so checkpoint
migration to/from the ZeRO-1 canonical flat layout goes through
`parallel.zero.convert_opt_state` losslessly (checkpoint/retopology.py
reads the geometry receipt the trainer stores in the checkpoint's `extra`).
`comm_bucket_mb` unset keeps the canonical single-flat layout and the
pre-r14 step byte-for-byte (the kill-switch lowered-text identity is
pinned in tests/test_comm_buckets.py).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from distributed_vgg_f_tpu.parallel.collectives import (
    cast_from_wire,
    cast_to_wire,
)

#: Gradient bytes per element used for bucket sizing — gradients are fp32 in
#: train/step.py regardless of compute dtype (the wire may narrow them, but
#: bucket GEOMETRY must not depend on mesh.reduce_dtype or flipping the wire
#: would silently re-layout a ZeRO checkpoint).
GRAD_BYTES_PER_ELEM = 4


@dataclasses.dataclass(frozen=True)
class GradBucketLayout:
    """Static bucket geometry for one (params tree, shard count, target).

    `buckets` holds canonical `jax.tree.leaves` indices in EMISSION order:
    bucket 0 contains the LAST leaves of the tree (reverse-backward
    position — their gradients exist first). All methods are pure jnp and
    traceable; geometry is decided here, once, from shapes alone, so the
    scan carry, the scatter padding, the param-shard slicing, the opt-state
    length, and the checkpoint receipt can never disagree.
    """

    num_shards: int
    bucket_bytes: int                       # configured target (> 0)
    treedef: Any                            # canonical params treedef
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    leaf_dtypes: Tuple[Any, ...]
    buckets: Tuple[Tuple[int, ...], ...]    # per bucket: canonical leaf idx

    # ------------------------------------------------------------ geometry
    @property
    def num_buckets(self) -> int:
        return len(self.buckets)

    def _leaf_size(self, idx: int) -> int:
        # math.prod(()) == 1 covers scalars; a genuinely zero-element leaf
        # must count 0 or the bucket offsets drift off the real ravel
        return int(math.prod(self.leaf_shapes[idx]))

    def bucket_sizes(self) -> Tuple[int, ...]:
        """Unpadded element count per bucket."""
        return tuple(sum(self._leaf_size(i) for i in b)
                     for b in self.buckets)

    def padded_sizes(self) -> Tuple[int, ...]:
        """Per-bucket length after padding to a multiple of num_shards."""
        return tuple(n + (-n) % self.num_shards for n in self.bucket_sizes())

    def shard_sizes(self) -> Tuple[int, ...]:
        return tuple(p // self.num_shards for p in self.padded_sizes())

    @property
    def shard_size(self) -> int:
        """Per-replica flat shard length S = sum(s_b)."""
        return sum(self.shard_sizes())

    @property
    def total_padded(self) -> int:
        """Global flat opt-state length T = N * S = sum(p_b)."""
        return sum(self.padded_sizes())

    def describe(self) -> dict:
        """The checkpoint/JSONL geometry receipt. Everything needed to
        rebuild the layout (`build_bucket_layout` is deterministic in
        (leaf shapes, num_shards, bucket_bytes)) plus `total_padded` as the
        integrity check a restore verifies before trusting the rebuild."""
        return {"kind": "bucketed_flat",
                "num_shards": self.num_shards,
                "bucket_bytes": self.bucket_bytes,
                "num_buckets": self.num_buckets,
                "total_padded": self.total_padded,
                "bucket_elems": list(self.bucket_sizes())}

    # ----------------------------------------------------- tree <-> buckets
    def _bucket_vector(self, leaves: Sequence[Any], b: int,
                       pad: bool) -> jnp.ndarray:
        parts = [jnp.ravel(leaves[i]) for i in self.buckets[b]]
        vec = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        if pad:
            p = self.padded_sizes()[b]
            if p != vec.shape[0]:
                vec = jnp.pad(vec, (0, p - vec.shape[0]))
        return vec

    def _leaves_from_bucket_vectors(self, vecs: Sequence[Any]) -> List[Any]:
        """Inverse of per-bucket ravel: padded (or unpadded) bucket vectors
        back to canonical-order leaves (C-order reshape — the exact layout
        `jnp.ravel` produced)."""
        out: List[Any] = [None] * len(self.leaf_shapes)
        for b, vec in enumerate(vecs):
            off = 0
            for i in self.buckets[b]:
                n = self._leaf_size(i)
                out[i] = jnp.reshape(vec[off:off + n],
                                     self.leaf_shapes[i]).astype(
                                         self.leaf_dtypes[i])
                off += n
        return out

    def unflatten(self, leaves: Sequence[Any]) -> Any:
        return jax.tree.unflatten(self.treedef, list(leaves))

    # -------------------------------------------------------- the DP leg
    def pmean_buckets(self, grads: Any, axis_name: str,
                      wire_dtype=None) -> Any:
        """Per-bucket mean-all-reduce of a gradient pytree: each bucket's
        leaves ride ONE collective (cast to the wire dtype through the same
        single-sourced helper as every other leg). Elementwise identical to
        the per-leaf pmean it groups — concatenation permutes nothing
        within an element — so the loss trajectory is unchanged."""
        leaves = jax.tree.leaves(grads)
        out_vecs = []
        for b in range(self.num_buckets):
            vec = self._bucket_vector(leaves, b, pad=False)
            wire = cast_to_wire(vec, wire_dtype)
            out_vecs.append(cast_from_wire(
                lax.pmean(wire, axis_name=axis_name), vec.dtype))
        return self.unflatten(self._leaves_from_bucket_vectors(out_vecs))

    # ------------------------------------------------------ the ZeRO legs
    def scatter_mean_shards(self, grads: Any, axis_name: str,
                            wire_dtype=None) -> jnp.ndarray:
        """Per-bucket [SYNC] reduce-scatter of a gradient pytree to this
        replica's fp32 mean flat shard (length S, bucket-major). Each
        bucket's collective depends only on ITS leaves' gradients — the
        overlap-capable emission. The wire may narrow per bucket
        (mesh.reduce_dtype through the single-sourced cast); the mean and
        everything downstream are fp32."""
        leaves = jax.tree.leaves(grads)
        shards = []
        for b in range(self.num_buckets):
            send = cast_to_wire(self._bucket_vector(leaves, b, pad=True),
                                wire_dtype)
            piece = lax.psum_scatter(send, axis_name, scatter_dimension=0,
                                     tiled=True)
            shards.append(cast_from_wire(piece, jnp.float32)
                          / self.num_shards)
        return shards[0] if len(shards) == 1 else jnp.concatenate(shards)

    def local_param_shard(self, params: Any, axis_name: str) -> jnp.ndarray:
        """This replica's (S,) slice of the bucket-major flat params —
        the piece the sharded optimizer updates."""
        r = lax.axis_index(axis_name)
        leaves = jax.tree.leaves(params)
        pieces = []
        for b, s_b in enumerate(self.shard_sizes()):
            vec = self._bucket_vector(leaves, b, pad=True)
            pieces.append(lax.dynamic_slice_in_dim(
                vec.astype(jnp.float32), r * s_b, s_b))
        return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)

    def gather_params(self, param_shard: jnp.ndarray,
                      axis_name: str) -> Any:
        """[SYNC] all-gather of the updated (S,) shards back to the full
        params tree — replicas re-sync exactly (always fp32; the gather leg
        is never narrowed, config.py mesh.reduce_dtype contract)."""
        full = lax.all_gather(param_shard, axis_name, tiled=True)
        return self.from_global(full)

    def gather_param_tree(self, param_shard: jnp.ndarray, axis_name: str,
                          wire_dtype=None) -> Any:
        """ZeRO-3 [SYNC] just-in-time gather: ONE `all_gather` PER BUCKET
        of this replica's (s_b,) piece back to that bucket's full leaves —
        each collective's operand is a static slice of the (S,) param
        shard (a step INPUT, no compute ancestry at all), so every gather
        carries the structural license a latency-hiding scheduler needs
        to pipeline it under the forward compute of earlier-consumed
        buckets (`hlo_overlap_report` gather witness). Unlike the ZeRO-1/2
        re-sync gather above, the wire may narrow (mesh.reduce_dtype
        through the SAME single-sourced cast as every scatter leg): the
        gathered replica is a transient of this one step, not persistent
        state — the fp32 truth lives in the shard. wire_dtype=None keeps
        the gather exact (bit-identical to the ZeRO-2 params)."""
        vecs, off = [], 0
        for b, s_b in enumerate(self.shard_sizes()):
            piece = cast_to_wire(
                lax.slice_in_dim(param_shard, off, off + s_b), wire_dtype)
            full = lax.all_gather(piece, axis_name, tiled=True)
            vecs.append(cast_from_wire(full, jnp.float32))
            off += s_b
        return self.unflatten(self._leaves_from_bucket_vectors(vecs))

    # --------------------------------------- global flat layout (opt state)
    def to_global(self, params: Any) -> jnp.ndarray:
        """Params tree -> the (T,) bucket-major replica-interleaved global
        flat vector (the ZeRO-2 opt-state/checkpoint layout; row r of the
        (N, S) view is replica r's shard)."""
        leaves = jax.tree.leaves(params)
        rows = [jnp.reshape(
            self._bucket_vector(leaves, b, pad=True).astype(jnp.float32),
            (self.num_shards, s_b))
            for b, s_b in enumerate(self.shard_sizes())]
        mat = rows[0] if len(rows) == 1 else jnp.concatenate(rows, axis=1)
        return jnp.reshape(mat, (self.total_padded,))

    def from_global(self, vec: jnp.ndarray) -> Any:
        """Inverse of `to_global`: (T,) global flat vector (or the tiled
        all_gather of per-replica shards — the same layout) -> params
        tree. Pure static slicing; padding elements are dropped."""
        mat = jnp.reshape(vec, (self.num_shards, self.shard_size))
        vecs, off = [], 0
        for b, s_b in enumerate(self.shard_sizes()):
            vecs.append(jnp.reshape(mat[:, off:off + s_b],
                                    (self.padded_sizes()[b],)))
            off += s_b
        return self.unflatten(self._leaves_from_bucket_vectors(vecs))

    # ------------------------------------------------------------- receipts
    def wire_bytes_per_step(self, *, zero: bool, wire_dtype=None,
                            shard_params: bool = False) -> Dict[str, int]:
        """Logical collective payload bytes per step per replica — the ONE
        accounting (`exchange_wire_bytes`) the monolithic paths share, so
        the bucketed and unbucketed comm receipts can never drift (bucketing
        changes the message schedule, never the byte totals)."""
        return exchange_wire_bytes(sum(self.bucket_sizes()),
                                   self.total_padded, zero=zero,
                                   wire_dtype=wire_dtype,
                                   shard_params=shard_params)


def sharding_basis(zero1: bool, shard_gradients: bool,
                   shard_params: bool = False) -> str:
    """THE (dp | zero1 | zero2 | zero3) basis derivation — the single
    source for the step's comm_meta receipt (which reports the EFFECTIVE
    basis after the trainer's single-shard downgrade) and
    config.MeshConfig's CONFIGURED label. The ladder is cumulative:
    zero3 implies zero2 implies zero1 (config validation enforces it;
    callers pass the post-downgrade flags)."""
    if zero1 and shard_gradients and shard_params:
        return "zero3"
    if zero1 and shard_gradients:
        return "zero2"
    return "zero1" if zero1 else "dp"


def exchange_wire_bytes(n_elem: int, padded_total: int, *, zero: bool,
                        wire_dtype=None,
                        shard_params: bool = False) -> Dict[str, int]:
    """Logical collective payload bytes per step per replica (algorithm
    bytes — the ring factor 2(N-1)/N lives in utils/scaling_model.py).
    DP: one all-reduce of the gradient bytes on the (possibly narrowed)
    wire. ZeRO-1/2: scatter leg on the wire dtype + fp32 param gather leg
    (the post-update re-sync — replicas must agree bit-exactly, so the
    gather never narrows). ZeRO-3 (`shard_params`): the SAME two legs,
    but the gather is the just-in-time pre-forward param fetch and rides
    the wire dtype (the gathered replica is a step transient, not
    persistent state) — under a narrowed wire ZeRO-3 is the only basis
    whose BOTH legs shrink. Shared by the bucketed layout's
    `wire_bytes_per_step` and the monolithic paths in train/step.py —
    one accounting, no drift."""
    wire_itemsize = (jnp.dtype(wire_dtype).itemsize
                     if wire_dtype is not None else 4)
    if not zero:
        b = n_elem * wire_itemsize
        return {"allreduce_bytes": b, "scatter_bytes": 0,
                "gather_bytes": 0, "wire_bytes": b}
    scatter = padded_total * wire_itemsize
    gather = padded_total * (wire_itemsize if shard_params else 4)
    return {"allreduce_bytes": 0, "scatter_bytes": scatter,
            "gather_bytes": gather, "wire_bytes": scatter + gather}


def build_bucket_layout(params: Any, num_shards: int,
                        bucket_bytes: int) -> Optional[GradBucketLayout]:
    """Partition a params pytree (concrete arrays or ShapeDtypeStructs)
    into size-targeted buckets in reverse-backward order. `bucket_bytes`
    <= 0 returns None — the single-flat kill-switch (callers keep the
    exact pre-r14 code path). Leaves are atomic (the PyTorch-DDP
    convention): a leaf larger than the target becomes its own bucket, so
    the target is a GRANULARITY floor, not a hard cap — VGG's FC layers
    each ride one bucket, the conv tail groups into few."""
    if bucket_bytes <= 0:
        return None
    leaves, treedef = jax.tree.flatten(params)
    if not leaves:
        raise ValueError("cannot bucket an empty params tree")
    shapes = tuple(tuple(getattr(l, "shape", ())) for l in leaves)
    dtypes = tuple(jnp.dtype(getattr(l, "dtype", jnp.float32))
                   for l in leaves)
    buckets: List[Tuple[int, ...]] = []
    cur: List[int] = []
    cur_bytes = 0
    # reverse-backward emission: the LAST leaves' gradients exist first
    for idx in reversed(range(len(leaves))):
        nbytes = int(math.prod(shapes[idx])) * GRAD_BYTES_PER_ELEM
        if cur and cur_bytes + nbytes > bucket_bytes:
            buckets.append(tuple(cur))
            cur, cur_bytes = [], 0
        cur.append(idx)
        cur_bytes += nbytes
    if cur:
        buckets.append(tuple(cur))
    return GradBucketLayout(num_shards=int(num_shards),
                            bucket_bytes=int(bucket_bytes),
                            treedef=treedef, leaf_shapes=shapes,
                            leaf_dtypes=dtypes, buckets=tuple(buckets))


def layout_from_receipt(params: Any, receipt: dict) -> GradBucketLayout:
    """Rebuild a layout from a checkpoint geometry receipt (`describe()`),
    verifying the reconstruction against EVERY recorded geometry field —
    total_padded, bucket count, AND the per-bucket element sizes (two
    partitions can share a padded total while permuting differently, e.g.
    two layers trading widths). A model/geometry mismatch must fail
    loudly, never silently permute a momentum vector — and it fails as
    the TYPED `GeometryReceiptError` (r19, resilience/errors.py): wrong
    layout, not corrupt bytes, so elastic restore and the flight recorder
    can tell the two apart (the class subclasses ValueError, so pre-r19
    catch sites are unchanged)."""
    from distributed_vgg_f_tpu.resilience.errors import GeometryReceiptError
    if receipt.get("kind") != "bucketed_flat":
        raise GeometryReceiptError(
            f"unknown opt-layout kind {receipt.get('kind')!r}")
    layout = build_bucket_layout(params, int(receipt["num_shards"]),
                                 int(receipt["bucket_bytes"]))
    rebuilt = None if layout is None else {
        "total_padded": layout.total_padded,
        "num_buckets": layout.num_buckets,
        "bucket_elems": list(layout.bucket_sizes())}
    recorded = {"total_padded": int(receipt["total_padded"]),
                "num_buckets": int(receipt["num_buckets"]),
                "bucket_elems": [int(n) for n in receipt["bucket_elems"]]}
    if rebuilt != recorded:
        raise GeometryReceiptError(
            f"bucket-layout receipt does not reproduce on this params "
            f"tree: rebuilt {rebuilt} != recorded {recorded} — the "
            f"checkpoint was written for a different model or geometry")
    return layout


# ---------------------------------------------------------------------------
# Lowered-HLO overlap evidence (the committed assertion, not a prose claim)
# ---------------------------------------------------------------------------

#: StableHLO collective op names that move gradient/param payloads.
COLLECTIVE_OPS = ("all_reduce", "reduce_scatter", "all_gather",
                  "all_to_all", "collective_permute")
#: The backward/forward compute ops a collective must be able to run under.
COMPUTE_OPS = ("dot_general", "convolution")

_INSTR_RE = re.compile(r"^\s*(%[\w]+)(?::\d+)?\s*=\s*(.*)$")
_OP_RE = re.compile(r"stablehlo\.([a-z_0-9]+)")
_REF_RE = re.compile(r"%([\w]+)(?:#\d+)?")
_TYPE_RE = re.compile(r"tensor<([^>]*)>")


def _tensor_elems(type_str: str) -> int:
    dims = []
    for tok in type_str.split("x"):
        if tok.isdigit():
            dims.append(int(tok))
        else:
            break                    # element type reached (f32, ui8, ...)
    return int(math.prod(dims)) if dims else 1


def _parse_functions(text: str) -> List[List[dict]]:
    """Split a StableHLO module into functions and parse each function's
    TOP-LEVEL instructions: {id, op, operands, elems}. Region bodies
    (all_reduce summation lambdas etc.) are skipped — their SSA numbers are
    function-local re-uses; the result type of a region-bearing op is read
    off its `}) : ...` closing line."""
    funcs: List[List[dict]] = []
    cur: Optional[List[dict]] = None
    depth = 0
    pending: Optional[dict] = None
    for line in text.splitlines():
        if line.lstrip().startswith("func.func"):
            cur = []
            funcs.append(cur)
            depth = 0
            pending = None
            continue
        if cur is None:
            continue
        opens = line.count("({")
        closes = line.count("})")
        if depth == 0:
            m = _INSTR_RE.match(line)
            if m:
                body = m.group(2)
                opm = _OP_RE.search(body)
                refs = [r for r in _REF_RE.findall(body)
                        if not r.startswith("arg")]
                types = _TYPE_RE.findall(line)
                instr = {"id": m.group(1).lstrip("%"),
                         "op": opm.group(1) if opm else "",
                         "operands": refs,
                         "elems": _tensor_elems(types[-1]) if types else 0}
                cur.append(instr)
                if opens > closes:
                    pending = instr        # type arrives on the `})` line
        elif depth + opens - closes == 0 and pending is not None:
            types = _TYPE_RE.findall(line)
            if types:
                pending["elems"] = _tensor_elems(types[-1])
            pending = None
        depth += opens - closes
    return funcs


def _ancestors(instrs: List[dict]) -> Dict[str, set]:
    # One forward pass in textual order: StableHLO is SSA, so every
    # operand's definition precedes its use and each instruction's
    # ancestor set is already complete when reached. Iterative on purpose
    # — a large model's longest dependency chain (resnet50 lowers to
    # thousands of chained instructions) overflows Python's recursion
    # limit under the equivalent memoized DFS.
    by_id = {i["id"]: i for i in instrs}
    memo: Dict[str, set] = {}
    for i in instrs:
        acc: set = set()
        for ref in i.get("operands", ()):
            if ref in by_id:
                acc.add(ref)
                acc |= memo.get(ref, set())
        memo[i["id"]] = acc
    return memo


def hlo_overlap_report(text: str, *, min_elems: int = 64) -> dict:
    """Analyze a lowered train step's StableHLO text for the committed
    overlap properties. Returns

      {collective_counts: {op: n}, grad_collectives: n,
       overlap_capable: bool, witness: {...} | None,
       serial_tail_collectives: n, compute_ops: n,
       gathers: n, gather_overlap_capable: bool,
       gather_witness: {...} | None}

    `grad_collectives` counts collectives whose payload carries at least
    `min_elems` elements (the metrics pmean moves scalars; gradient buckets
    move thousands). `overlap_capable` is true iff some gradient collective
    C and some dot_general/convolution D have NO dependency path in either
    direction — the structural license for a latency-hiding scheduler to
    overlap them. A monolithic flat scatter can never satisfy it: every
    compute op feeds it. `serial_tail_collectives` counts gradient
    collectives whose ancestor set contains EVERY compute op (the
    fully-serialized ones this PR exists to break up).

    r21 (ZeRO-3): `gathers` counts the gradient-sized `all_gather`
    collectives (the just-in-time param fetch — one per bucket under the
    bucketed ZeRO-3 exchange; the single re-sync gather under ZeRO-1/2),
    and `gather_witness`/`gather_overlap_capable` apply the SAME
    dependency-free-pair test restricted to the gather ops: a param
    gather that neither feeds nor is fed by some dot/conv is one a
    latency-hiding scheduler may run under the forward compute of
    already-gathered buckets.

    Scope: analyzes TOP-LEVEL instructions per function — collectives
    inside control-flow regions (the grad-accum scan's `stablehlo.while`
    body) are deliberately out of scope, so run the overlap assertions on
    a grad_accum_steps=1 lowering (the bench and tier-1 tests do)."""
    best: Optional[dict] = None
    for instrs in _parse_functions(text):
        colls = [i for i in instrs if i["op"] in COLLECTIVE_OPS]
        if not colls:
            continue
        anc = _ancestors(instrs)
        computes = [i for i in instrs if i["op"] in COMPUTE_OPS]
        compute_ids = {i["id"] for i in computes}
        grad_colls = [c for c in colls if c["elems"] >= min_elems]
        witness = None
        gather_witness = None
        serial_tail = 0
        for c in grad_colls:
            c_anc = anc.get(c["id"], set())
            if compute_ids and compute_ids <= c_anc:
                serial_tail += 1
            if witness is None or (c["op"] == "all_gather"
                                   and gather_witness is None):
                for d in computes:
                    if d["id"] not in c_anc \
                            and c["id"] not in anc.get(d["id"], set()):
                        pair = {
                            "collective": f"%{c['id']} = {c['op']} "
                                          f"({c['elems']} elems)",
                            "compute": f"%{d['id']} = {d['op']}"}
                        if witness is None:
                            witness = pair
                        if c["op"] == "all_gather" \
                                and gather_witness is None:
                            gather_witness = pair
                        break
        counts: Dict[str, int] = {}
        for c in colls:
            counts[c["op"]] = counts.get(c["op"], 0) + 1
        report = {"collective_counts": counts,
                  "grad_collectives": len(grad_colls),
                  "overlap_capable": witness is not None,
                  "witness": witness,
                  "serial_tail_collectives": serial_tail,
                  "compute_ops": len(computes),
                  # over ALL collectives, not just gradient-sized ones: the
                  # param gathers are the only all_gather ops a step emits
                  # (metrics ride all_reduce), and a tiny trailing bucket's
                  # gather must still count toward `gathers == buckets`
                  "gathers": sum(1 for c in colls
                                 if c["op"] == "all_gather"),
                  "gather_overlap_capable": gather_witness is not None,
                  "gather_witness": gather_witness}
        if best is None or report["grad_collectives"] \
                > best["grad_collectives"]:
            best = report
    return best or {"collective_counts": {}, "grad_collectives": 0,
                    "overlap_capable": False, "witness": None,
                    "serial_tail_collectives": 0, "compute_ops": 0,
                    "gathers": 0, "gather_overlap_capable": False,
                    "gather_witness": None}
