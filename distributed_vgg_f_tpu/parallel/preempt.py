"""Time-bounded multi-host preemption stop-consensus (SURVEY.md §5 failure
detection / recovery; VERDICT r2 #5).

The problem: when SIGTERM (the TPU-VM/k8s preemption signal) lands on ONE
host, every host must stop at the SAME step — a lone host acting on its local
flag would strand the others in the next collective (train step psum, Orbax
save barrier). Consensus therefore must itself be a collective, and every
host must issue it at the same loop index. Tying it to the `log_every`
cadence (round 2's design) made the reaction time a function of an unrelated
logging knob — with a large `log_every` the preemption grace window could
expire before consensus.

TPU-native fix: every step, each host asynchronously dispatches a one-scalar
cross-replica sum of its local flag over the mesh. Dispatch returns
immediately (XLA overlaps the tiny all-reduce with the step's compute); the
result is polled LAG steps later, when it has long since completed, so the
poll never blocks the dispatch pipeline the way a same-step `device_get`
would. Every host polls the same step's result, so all hosts observe the
same global flag at the same loop index and stop together — within
LAG + 1 = 3 steps of the signal, independent of `log_every`.

(A sub-step-time bound is impossible for any step-synchronized stopper: an
in-flight XLA computation cannot be abandoned without desyncing the replicas,
and the forced checkpoint must happen at a step boundary regardless.)

Restart semantics (r18→r19): the forced preemption checkpoint carries the
position-exact iterator-state blob like every other save
(data/iterator_state.py; trainer `_save_extra`), so the restarted
incarnation resumes through the SAME blob dispatch as any
restore-from-checkpoint — mid-epoch, zero replayed batches. That was the
data half of elastic resize (ROADMAP item 1); r19's parallel/elastic.py
lands the mesh half: on a decisive poll the trainer no longer has to
exit — with `mesh.elastic.enabled` the survivors read `flagged_ranks`
below, form a shrunken mesh, reshard params/opt-state in place, and
continue through the same cursor blob. Restart-from-checkpoint remains
the kill-switch-off path and the degradation fallback.
"""

from __future__ import annotations

import collections

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P


class PreemptConsensus:
    """Per-step asynchronous stop-consensus over the mesh's data axis.

    Usage (one instance per fit loop; multi-process only):

        consensus = PreemptConsensus(mesh)
        for step in ...:
            ...train step...
            if consensus.poll(local_flag):   # ~free: async dispatch +
                checkpoint_and_stop()        # lagged poll of a done result
    """

    LAG = 2  # steps between dispatch and poll; poll target is always done

    def __init__(self, mesh, data_axis: str = "data"):
        self._flag_sharding = NamedSharding(mesh, P(data_axis))
        pid = jax.process_index()
        self._num_local = sum(
            1 for d in mesh.devices.flat if d.process_index == pid)
        # sum over the sharded per-device flag vector; GSPMD emits the
        # all-reduce, output replicated on every host
        self._sum = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))
        # all-gather of the same vector (r19): WHO flagged, not just
        # whether anyone did — elastic resize needs the dead ranks to plan
        # the survivor mesh. Same dispatch/lag discipline as the sum; the
        # two collectives ride the same step's overlap window.
        self._gather = jax.jit(lambda x: x,
                               out_shardings=NamedSharding(mesh, P()))
        self._pending: collections.deque = collections.deque()
        self._decided = False
        self._flagged: "np.ndarray | None" = None

    def poll(self, local_flag: bool) -> bool:
        """Dispatch this step's consensus collective and read the one from
        LAG steps ago. Returns True once ANY host's flag has reached
        consensus — identically on every host at the same loop index."""
        if self._decided:
            return True
        local = np.full((self._num_local,), int(bool(local_flag)), np.int32)
        flags = jax.make_array_from_process_local_data(
            self._flag_sharding, local)
        self._pending.append((self._sum(flags), self._gather(flags)))
        if len(self._pending) > self.LAG:
            oldest_sum, oldest_vec = self._pending.popleft()
            if int(jax.device_get(oldest_sum)) > 0:
                self._decided = True
                self._flagged = np.asarray(
                    jax.device_get(oldest_vec)) > 0
        return self._decided

    @property
    def flagged_ranks(self) -> tuple:
        """Data-axis positions whose flag carried the decisive poll —
        identical on every host (the gather is replicated). () until a
        poll decides. Elastic resize treats these as the DEAD ranks: under
        a real SIGTERM every device of the preempted host flags, so the
        positions name exactly the capacity being reclaimed."""
        if self._flagged is None:
            return ()
        return tuple(int(i) for i in np.nonzero(self._flagged)[0])
