"""Device-mesh construction — the framework's communication topology layer.

Reference equivalent (SURVEY.md §2.4): the reference's worker sync step rides NCCL
ring all-reduce intra-node and MPI/gRPC inter-node. On TPU there is no library to
wrap — XLA emits ICI/DCN collectives from `lax.pmean`/`lax.psum` given a mesh — so
the value of this layer is (a) deterministic device ordering, (b) named-axis layout,
(c) topology reporting for the scaling-efficiency benchmark (ICI vs DCN regimes).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named-axis mesh layout. The reference workload is pure data parallelism
    (SURVEY.md §2.3), so the default is a 1-D ('data',) mesh over all devices;
    extra axes (declared but size-1 unless configured) keep the door open for
    model/sequence axes without changing trainer code."""
    axis_names: Sequence[str] = ("data",)
    axis_sizes: Sequence[int] = (0,)  # 0 = fill with all remaining devices

    def resolve_sizes(self, num_devices: int) -> tuple:
        sizes = list(self.axis_sizes)
        fill = [i for i, s in enumerate(sizes) if s in (0, -1)]
        fixed = int(np.prod([s for s in sizes if s > 0])) if any(s > 0 for s in sizes) else 1
        if num_devices % fixed != 0:
            raise ValueError(
                f"device count {num_devices} not divisible by fixed axis product {fixed}")
        remaining = num_devices // fixed
        if len(fill) > 1:
            raise ValueError("at most one mesh axis may be auto-sized (0)")
        if fill:
            sizes[fill[0]] = remaining
        elif fixed != num_devices:
            raise ValueError(
                f"axis sizes {sizes} use {fixed} devices but {num_devices} are visible")
        return tuple(int(s) for s in sizes)


def build_mesh(spec: MeshSpec | None = None,
               devices: Sequence[jax.Device] | None = None) -> Mesh:
    """Build a `jax.sharding.Mesh` over `devices` (default: all visible devices).

    Device order is `jax.devices()` order, which JAX guarantees to be consistent
    across processes in a multi-host setup — the analogue of the reference's
    rank-ordered MPI communicator.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve_sizes(len(devices))
    dev_array = np.array(devices).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(spec.axis_names))


def data_sharding(mesh: Mesh, data_axis: str = "data") -> NamedSharding:
    """Sharding for a batch: leading (batch) dim split over the data axis."""
    return NamedSharding(mesh, P(data_axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_host_batch(batch: Mapping[str, np.ndarray], mesh: Mesh,
                     data_axis: str = "data") -> Mapping[str, jax.Array]:
    """Move a process-local numpy batch onto the mesh, sharded over the data axis.

    Single-process: plain device_put with a NamedSharding. Multi-host: each
    process contributes its local shard of the global batch
    (`jax.make_array_from_process_local_data`) — the analogue of the reference's
    per-worker dataset sharding feeding per-rank GPUs (SURVEY.md §1 data layer).
    """
    sharding = NamedSharding(mesh, P(data_axis))
    return {
        k: jax.make_array_from_process_local_data(sharding, np.asarray(v))
        for k, v in batch.items()
    }


def mesh_topology_report(mesh: Mesh) -> Mapping[str, Any]:
    """Topology summary for logs/benchmarks: the scaling benchmark must separate
    ICI-only from ICI+DCN regimes (SURVEY.md §5, distributed backend)."""
    devices = list(mesh.devices.flat)
    num_processes = len({d.process_index for d in devices})
    kinds = sorted({d.device_kind for d in devices})
    return {
        "axis_names": list(mesh.axis_names),
        "axis_sizes": [int(s) for s in mesh.devices.shape],
        "num_devices": len(devices),
        "num_processes": num_processes,
        "device_kinds": kinds,
        "platform": devices[0].platform if devices else "none",
        # Single-process ⇒ all links are ICI (or host-internal); multi-process TPU
        # slices may traverse DCN between slices.
        "regime": "ici" if num_processes == 1 else "ici+dcn",
    }
