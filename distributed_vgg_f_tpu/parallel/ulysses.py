"""Ulysses-style all-to-all sequence parallelism — the ring's counterpart.

BEYOND-PARITY capability, same charter as parallel/ring_attention.py (the
reference has no long-sequence workload — SURVEY.md §5 records SP/CP
absent-by-design; the task brief asks for "ring attention or all-to-all
sequence/context parallelism" as first-class, and this module is the
all-to-all half). PAPERS.md's sequence-parallel family covers both layouts;
this is the DeepSpeed-Ulysses-shaped one, re-derived for the TPU mesh.

The layout swap: Q/K/V arrive sequence-sharded — each device holds
(B, T/n, H, D). One `lax.all_to_all` per tensor re-shards them to
HEAD-sharded (B, T, H/n, D): every device then owns the FULL sequence for
its H/n heads, so attention (including causal masking) is an ordinary
LOCAL computation — einsum softmax or the Pallas flash kernel
(ops/flash_attention.py), no streaming-softmax state machine, no per-hop
collective schedule. A final all_to_all returns the output to the
sequence-sharded layout the surrounding network expects.

Wire cost per device (bytes, s = B·(T/n)·H·D·itemsize local shard size):
  ring    — K and V each make n-1 neighbor hops:      2·s·(n-1)
  ulysses — q, k, v, o each cross one all-to-all:     4·s·(n-1)/n
i.e. the all-to-all layout moves n/2× fewer bytes. The trade is topology:
the ring's ppermute is neighbor-only (every hop rides one ICI link, and
XLA can overlap hop i+1 with block i's matmuls), while all-to-all needs
bisection bandwidth and holds the full (B, T, H/n, D) sequence per device.
Head counts that don't divide n are zero-padded to the next multiple
(exact incl. grads; a ceil(H/n)·n/H compute-and-wire overhead — 1.33× for
ViT-S/16's H=6 on n=4). The quantified rule lives in
`utils/scaling_model.py ulysses_comm_model` (rendered into the committed
artifact by `benchmarks/scaling_model.py`): prefer ulysses while its
padding-adjusted wire cost beats the ring's and T_local sits below ≈ half
the ring's break-even length
(where the ring's exposed comm exceeds the all-to-all wire time); from
there up the ring hides its hops under block compute — and it scales to
any n and keeps memory O(T/n·T/n), which ulysses's full-sequence local
activations do not.

Exactness against full attention (fp32 + bf16, causal and not, gradients,
flash and einsum local kernels, 2/4/8-device meshes) is pinned by
tests/test_ulysses.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# check_vma-kwarg-translating shim over jax.shard_map /
# jax.experimental.shard_map (parallel/compat.py)
from distributed_vgg_f_tpu.parallel.compat import axis_size, shard_map

from distributed_vgg_f_tpu.ops.flash_attention import flash_self_attention
from distributed_vgg_f_tpu.parallel.ring_attention import (
    full_attention_reference)

LOCAL_KERNELS = ("einsum", "flash")


def ulysses_self_attention(q, k, v, axis_name: str, *, causal: bool = False,
                           kernel: str = "einsum",
                           interpret: bool | None = None):
    """Exact attention over a sequence sharded on `axis_name`.

    Args (PER-SHARD, inside shard_map): q, k, v of shape (B, T_local, H, D)
    for ANY head count H: when H does not divide the axis size n, heads are
    zero-padded to ceil(H/n)·n before the all-to-alls and the pad heads are
    sliced off afterwards — exact incl. gradients (a zero head's softmax is
    uniform over zero values; the slice gives it zero cotangents), at a
    ceil(H/n)·n/H compute-and-wire overhead (1.33× for ViT-S/16's H=6 on
    n=4) that `utils/scaling_model.ulysses_comm_model` charges honestly.
    Returns this device's (B, T_local, H, D) output attending over the FULL
    sequence.

    `kernel` picks the local computation once the sequence is gathered:
    "einsum" (the O(T²)-memory oracle math — fine at moderate T) or
    "flash" (ops/flash_attention.py Pallas blocks, O(T·D) HBM — the long-T
    choice; `interpret` is forwarded for CPU testing).
    """
    if kernel not in LOCAL_KERNELS:
        raise ValueError(f"kernel {kernel!r} not one of {LOCAL_KERNELS}")
    n = axis_size(axis_name)
    h = q.shape[2]
    h_pad = -(-h // n) * n
    if h_pad != h:
        # Head padding (VERDICT r4 weak #5): H=6 on a 4/8-device axis —
        # exactly ViT-S/16's head count — used to be a hard error. Pad with
        # all-zero heads instead: heads are independent, a zero head's
        # softmax is uniform over zero values (output 0, no NaN, flash's
        # online stats are finite), and the slice below gives the padded
        # heads zero cotangents so gradients stay exact. The wasted compute
        # and wire (h_pad/h, e.g. 8/6 = 1.33x) is charged honestly by
        # utils/scaling_model.ulysses_comm_model.
        pad = ((0, 0), (0, 0), (0, h_pad - h), (0, 0))
        q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)

    def _to_heads(x):   # (B, T/n, H, D) -> (B, T, H/n, D)
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = _to_heads(q), _to_heads(k), _to_heads(v)
    if kernel == "flash":
        out = flash_self_attention(qh, kh, vh, causal=causal,
                                   interpret=interpret)
    else:
        out = full_attention_reference(qh, kh, vh, causal=causal)
    # (B, T, H/n, D) -> (B, T/n, H, D); all_to_all differentiates to the
    # inverse all_to_all, so the whole layer is transparently reverse-mode
    # differentiable (flash brings its own custom VJP).
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                         tiled=True)
    return out[:, :, :h] if h_pad != h else out


@functools.lru_cache(maxsize=16)
def _ulysses_fn(mesh: Mesh, axis_name: str, causal: bool, kernel: str,
                interpret: bool | None):
    """jit(shard_map(...)) cached per signature — fresh closures would
    retrace per call (same discipline as ring_attention._ring_fn)."""
    seq_spec = P(None, axis_name)
    return jax.jit(shard_map(
        functools.partial(ulysses_self_attention, axis_name=axis_name,
                          causal=causal, kernel=kernel, interpret=interpret),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    ))


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "data",
                      causal: bool = False, kernel: str = "einsum",
                      interpret: bool | None = None):
    """Convenience wrapper: GLOBAL (B, T, H, D) inputs sharded on T over
    `axis_name`. T must divide by the axis size (same contract as
    ring_attention — pad upstream). H need NOT divide by it: indivisible
    head counts (ViT-S/16's H=6 on n=4/8) are zero-padded to the next
    multiple per shard and sliced back — exact incl. grads, at an
    h_pad/h compute+wire overhead the comm model charges (VERDICT r4
    weak #5)."""
    n = mesh.shape[axis_name]
    if q.shape[1] % n:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name} size {n}")
    sh = NamedSharding(mesh, P(None, axis_name))
    return _ulysses_fn(mesh, axis_name, causal, kernel, interpret)(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))
