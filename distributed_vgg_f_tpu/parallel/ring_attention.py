"""Ring attention — sequence-parallel self-attention over a mesh axis.

BEYOND-PARITY capability. The reference has no long-sequence workload
(SURVEY.md §5: the only attention in scope is ViT-S/16's 197 tokens under
plain DP, and SP/CP is recorded absent-by-design), but the mesh layer was
built to leave a sequence axis open — this module demonstrates that the
door actually opens: exact attention over a sequence SHARDED across
devices, with memory per device O(T_local·T_local) instead of O(T·T) and
the K/V blocks streamed around the ring.

TPU-native design:
- `shard_map` over the mesh axis; each device holds its (B, T_local, H, D)
  shard of Q/K/V.
- The K/V block circulates with `lax.ppermute` (neighbor exchange — rides
  ICI hops, never all-to-all), overlapping the next hop with the current
  block's matmuls when XLA schedules it.
- Numerically exact streaming softmax (the flash/online formulation): a
  running row max `m`, normalizer `l`, and un-normalized accumulator are
  corrected as each block arrives — fp32 accumulation regardless of the
  input dtype, bf16 matmuls on the MXU when inputs are bf16.
- The ring length is a trace-time constant (mesh axis size), so the loop
  unrolls into a fixed schedule — no dynamic control flow inside jit.

`ring_self_attention` is the sharded function (call inside your own
shard_map); `ring_attention` wraps it with jit+shard_map for direct use.
Equality with full (gathered) attention is tested to fp32 tolerance on the
8-device CPU mesh in tests/test_ring_attention.py, plus a bf16 dtype test
and a grad test.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# check_vma-kwarg-translating shim over jax.shard_map /
# jax.experimental.shard_map (parallel/compat.py)
from distributed_vgg_f_tpu.parallel.compat import axis_size, shard_map


def ring_self_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        axis_name: str, *, causal: bool = False) -> jnp.ndarray:
    """Exact attention over a sequence sharded on `axis_name`.

    Args (PER-SHARD, inside shard_map): q, k, v of shape
    (B, T_local, H, D). Returns the (B, T_local, H, D) attention output for
    this device's query block, attending over the FULL sequence.

    `causal`: token i attends to j <= i in GLOBAL positions. K/V blocks
    travel the ring regardless (the permute schedule must be identical on
    every device), but a device contributes a block only when allowed:
    future source blocks are masked out entirely, the diagonal block gets
    the triangular mask, past blocks pass whole — so the masking costs a
    `where`, never a different collective schedule.
    """
    n = axis_size(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    qf = q * scale

    b, t_q, h, d = q.shape
    acc = jnp.zeros((b, t_q, h, d), jnp.float32)        # un-normalized out
    row_max = jnp.full((b, h, t_q), -jnp.inf, jnp.float32)
    row_sum = jnp.zeros((b, h, t_q), jnp.float32)

    my_blk = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    k_blk, v_blk = k, v
    for step in range(n):
        def _update(acc, row_max, row_sum, k_blk=k_blk, v_blk=v_blk,
                    step=step):
            # bf16 inputs keep the MXU GEMM in bf16; scores accumulate fp32
            scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk,
                                preferred_element_type=jnp.float32)
            if causal:
                # the block arriving at `step` hops started src = my - step
                src_blk = (my_blk - step) % n
                t_k = k.shape[1]
                q_pos = my_blk * t_q + jnp.arange(t_q)
                k_pos = src_blk * t_k + jnp.arange(t_k)
                allowed = q_pos[:, None] >= k_pos[None, :]    # (t_q, t_k)
                scores = jnp.where(allowed[None, None], scores, -jnp.inf)
            blk_max = jnp.max(scores, axis=-1)
            # new_max is finite from step 0 even under causal masking: step 0
            # is always the device's own DIAGONAL block (src = my - 0), where
            # every row's own position is allowed — so no -inf/-inf guard is
            # needed in the correction (code-review r3: an earlier isneginf
            # guard here was dead on every step of every device).
            new_max = jnp.maximum(row_max, blk_max)
            # correction folds previously-accumulated blocks under the new max
            correction = jnp.exp(row_max - new_max)
            probs = jnp.exp(scores - new_max[..., None])
            new_sum = row_sum * correction + jnp.sum(probs, axis=-1)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v_blk.dtype),
                             v_blk, preferred_element_type=jnp.float32)
            new_acc = acc * correction.transpose(0, 2, 1)[..., None] + ctx
            return new_acc, new_max, new_sum

        if causal and n > 1:
            # A fully-future visiting block (src > my: every key masked for
            # every local row) updates the state by EXACTLY the identity
            # (new_max = row_max, correction = 1, probs = 0 — state never
            # virgin here, step 0 is the self block). Skip both einsums
            # under lax.cond; the ppermute schedule below stays uniform, so
            # only dead local FLOPs disappear — on average half the causal
            # ring (mirrors ring_flash.py's kernel-call skip).
            # position-exact (not block-index) predicate: supports the
            # t_k != t_q shards the masking code above allows — fully
            # future ⟺ the block's FIRST key is past the LAST local query
            src_blk = (my_blk - step) % n
            acc, row_max, row_sum = lax.cond(
                src_blk * k.shape[1] > my_blk * t_q + t_q - 1,
                lambda a, m_, s: (a, m_, s), _update,
                acc, row_max, row_sum)
        else:
            acc, row_max, row_sum = _update(acc, row_max, row_sum)
        if step < n - 1:
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)

    out = acc / row_sum.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


@functools.lru_cache(maxsize=8)
def _ring_fn(mesh: Mesh, axis_name: str, causal: bool):
    """The jit(shard_map(...)) executable, cached per (mesh, axis_name,
    causal) — a fresh closure per call would retrace and recompile every
    invocation (jit caches by function identity)."""
    seq_spec = P(None, axis_name)
    return jax.jit(shard_map(
        functools.partial(ring_self_attention, axis_name=axis_name,
                          causal=causal),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    ))


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis_name: str = "data",
                   causal: bool = False) -> jnp.ndarray:
    """Convenience wrapper: GLOBAL (B, T, H, D) inputs sharded on T over
    `axis_name`; jit + shard_map + ring. T must divide evenly by the axis
    size (pad upstream — attention over padding is the caller's masking
    decision, same contract as data/eval_pad.py)."""
    if q.shape[1] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name} size {mesh.shape[axis_name]}")
    sh = NamedSharding(mesh, P(None, axis_name))
    return _ring_fn(mesh, axis_name, causal)(
        jax.device_put(q, sh), jax.device_put(k, sh), jax.device_put(v, sh))


def full_attention_reference(q: jnp.ndarray, k: jnp.ndarray,
                             v: jnp.ndarray,
                             causal: bool = False) -> jnp.ndarray:
    """The plain O(T²)-memory oracle the ring is tested against."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k,
                        preferred_element_type=jnp.float32)
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)
