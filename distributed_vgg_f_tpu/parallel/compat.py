"""JAX version-compatibility shims for the parallel layer.

`shard_map` has moved twice upstream: `jax.experimental.shard_map` →
`jax.shard_map` (≥ 0.4.35), and its replication-checking kwarg was renamed
`check_rep` → `check_vma` (≥ 0.6). Call sites in this repo use the modern
spelling; this shim resolves the newest available implementation and
translates `check_vma` for older runtimes, so one codebase runs unmodified
against both (the CI CPU image pins an older jax than the TPU fleet).
"""

from __future__ import annotations

import inspect

from jax import lax as _lax

try:  # JAX ≥ 0.4.35 exports shard_map at top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


if hasattr(_lax, "axis_size"):
    axis_size = _lax.axis_size
else:
    def axis_size(axis_name) -> int:
        """`lax.axis_size` for runtimes that predate it: a psum of ones
        over the axis — constant-folded by XLA inside shard_map, so it
        costs nothing at runtime (the per-shard size is static)."""
        return _lax.psum(1, axis_name)

_PARAMS = inspect.signature(_shard_map).parameters

if "check_vma" in _PARAMS:
    shard_map = _shard_map
else:
    def shard_map(*args, check_vma: bool | None = None, **kwargs):
        """`shard_map` accepting the modern `check_vma` kwarg on runtimes
        that still spell it `check_rep` (same semantics, renamed)."""
        if check_vma is not None and "check_rep" in _PARAMS:
            kwargs.setdefault("check_rep", check_vma)
        return _shard_map(*args, **kwargs)
