from distributed_vgg_f_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    mesh_topology_report,
)
from distributed_vgg_f_tpu.parallel.collectives import (  # noqa: F401
    all_reduce_gradients,
    cross_replica_mean,
    cross_replica_sum,
)
from distributed_vgg_f_tpu.parallel.distributed import (  # noqa: F401
    initialize_distributed,
)
