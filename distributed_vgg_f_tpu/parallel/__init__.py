from distributed_vgg_f_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    build_mesh,
    mesh_topology_report,
)
from distributed_vgg_f_tpu.parallel.collectives import (  # noqa: F401
    all_reduce_gradients,
    cross_replica_mean,
    cross_replica_sum,
)
from distributed_vgg_f_tpu.parallel.distributed import (  # noqa: F401
    initialize_distributed,
)

# Sequence-parallel attention layouts (beyond-parity; imported lazily by
# callers that need them — ring_attention / ring_flash / ulysses modules
# pull in ops.flash_attention, so they are NOT re-exported here to keep
# `import distributed_vgg_f_tpu.parallel` light for the trainer path.)
