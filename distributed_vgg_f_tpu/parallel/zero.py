"""ZeRO-1-style optimizer-state sharding over the data-parallel axis.

Reference context: the reference keeps a full optimizer-state replica per worker
(plain synchronous DP — SURVEY.md §2.3). PAPERS.md retrieved "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" against it;
SURVEY.md §2.3 marks weight-update sharding as the one stretch strategy worth
building. This module is that strategy, TPU-native:

    grads (per-replica)
      └─ flatten to one vector, pad to a multiple of N
      └─ `lax.psum_scatter`  — each replica receives its 1/N contiguous shard of
         the SUM of gradients (one reduce-scatter on ICI instead of the
         all-reduce; half the bytes moved)
      └─ optimizer update on the shard only — momentum/opt state is physically
         sharded over the data axis (1/N memory per chip)
      └─ `lax.all_gather` of the updated parameter shard — replicas re-sync

reduce-scatter + all-gather moves the same total bytes as the all-reduce they
replace (an all-reduce IS a reduce-scatter + all-gather), so step time is
unchanged while optimizer memory drops by N — the paper's observation, natively
expressed in XLA collectives.

The flat-vector layout (rather than per-leaf sharding) keeps every collective a
single large contiguous transfer — ICI-bandwidth-friendly — and makes the shard
boundary independent of parameter-tree structure.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from distributed_vgg_f_tpu.train.state import TrainState


def flat_param_count(params_shapes: Any) -> int:
    """Total element count of a params pytree (of arrays or ShapeDtypeStructs)."""
    return int(sum(math.prod(l.shape) for l in jax.tree.leaves(params_shapes)))


def padded_flat_size(total: int, num_shards: int) -> int:
    """Flat vector length after padding to a multiple of the shard count."""
    return total + (-total) % num_shards


def opt_state_specs(opt_state_shapes: Any, padded: int, data_axis: str) -> Any:
    """PartitionSpecs for a ZeRO-1 optimizer state: every leaf that is the
    padded flat vector (momentum trace, etc.) shards over the data axis;
    scalars (schedule counts) stay replicated."""
    def spec(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] == padded:
            return P(data_axis)
        return P()
    return jax.tree.map(spec, opt_state_shapes)


def train_state_specs(state_shapes: TrainState, padded: int,
                      data_axis: str) -> TrainState:
    """Full PartitionSpec tree for a TrainState with sharded optimizer state:
    step/params/batch_stats replicated, opt-state vectors sharded."""
    return TrainState(
        step=P(),
        params=jax.tree.map(lambda _: P(), state_shapes.params),
        batch_stats=jax.tree.map(lambda _: P(), state_shapes.batch_stats),
        opt_state=opt_state_specs(state_shapes.opt_state, padded, data_axis),
    )
