"""ZeRO-1-style optimizer-state sharding over the data-parallel axis.

Reference context: the reference keeps a full optimizer-state replica per worker
(plain synchronous DP — SURVEY.md §2.3). PAPERS.md retrieved "Automatic
Cross-Replica Sharding of Weight Update in Data-Parallel Training" against it;
SURVEY.md §2.3 marks weight-update sharding as the one stretch strategy worth
building. This module is that strategy, TPU-native:

    grads (per-replica)
      └─ flatten to one vector, pad to a multiple of N
      └─ `lax.psum_scatter`  — each replica receives its 1/N contiguous shard of
         the SUM of gradients (one reduce-scatter on ICI instead of the
         all-reduce; half the bytes moved)
      └─ optimizer update on the shard only — momentum/opt state is physically
         sharded over the data axis (1/N memory per chip)
      └─ `lax.all_gather` of the updated parameter shard — replicas re-sync

reduce-scatter + all-gather moves the same total bytes as the all-reduce they
replace (an all-reduce IS a reduce-scatter + all-gather), so step time is
unchanged while optimizer memory drops by N — the paper's observation, natively
expressed in XLA collectives.

The flat-vector layout (rather than per-leaf sharding) keeps every collective a
single large contiguous transfer — ICI-bandwidth-friendly — and makes the shard
boundary independent of parameter-tree structure.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # runtime import is deferred into train_state_specs:
    # train/__init__ -> trainer -> step -> this module would cycle when the
    # package is entered via `parallel.zero` first
    from distributed_vgg_f_tpu.train.state import TrainState


def flat_param_count(params_shapes: Any) -> int:
    """Total element count of a params pytree (of arrays or ShapeDtypeStructs)."""
    return int(sum(math.prod(l.shape) for l in jax.tree.leaves(params_shapes)))


def padded_flat_size(total: int, num_shards: int) -> int:
    """Flat vector length after padding to a multiple of the shard count."""
    return total + (-total) % num_shards


def opt_state_specs(opt_state_shapes: Any, padded: int, data_axis: str) -> Any:
    """PartitionSpecs for a ZeRO-1 optimizer state: every leaf that is the
    padded flat vector (momentum trace, etc.) shards over the data axis;
    scalars (schedule counts) stay replicated."""
    def spec(leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1 and shape[0] == padded:
            return P(data_axis)
        return P()
    return jax.tree.map(spec, opt_state_shapes)


def train_state_specs(state_shapes: "TrainState", padded: int,
                      data_axis: str, *,
                      shard_params: bool = False) -> "TrainState":
    """Full PartitionSpec tree for a TrainState with sharded optimizer state:
    step/batch_stats replicated, opt-state vectors sharded. Under ZeRO-3
    (`shard_params`, r21) the params (and EMA params) leaves are the padded
    flat vector too, and shard over the data axis exactly like the
    optimizer vectors."""
    from distributed_vgg_f_tpu.train.state import TrainState
    if shard_params:
        param_specs = opt_state_specs(state_shapes.params, padded, data_axis)
        ema_specs = opt_state_specs(state_shapes.ema_params, padded,
                                    data_axis)
    else:
        param_specs = jax.tree.map(lambda _: P(), state_shapes.params)
        ema_specs = jax.tree.map(lambda _: P(), state_shapes.ema_params)
    return TrainState(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree.map(lambda _: P(), state_shapes.batch_stats),
        opt_state=opt_state_specs(state_shapes.opt_state, padded, data_axis),
        ema_params=ema_specs,
        ema_batch_stats=jax.tree.map(lambda _: P(),
                                     state_shapes.ema_batch_stats),
    )


# ---------------------------------------------------------------------------
# Cross-topology layout conversion (checkpoint/retopology.py)
# ---------------------------------------------------------------------------

def opt_state_layout(opt_state: Any, total: int) -> tuple:
    """Detect an optax state's layout from leaf shapes alone (works on
    concrete arrays, ShapeDtypeStructs, and checkpoint ArrayMetadata):
    ('flat', padded_size) for the ZeRO-1 padded-flat-vector layout, else
    ('tree', None) for the replicated params-tree layout. A 1-D leaf at least
    `total` (the flat param count) long can only be the flat vector — no
    single parameter leaf holds the whole network."""
    for leaf in jax.tree.leaves(opt_state):
        shape = tuple(getattr(leaf, "shape", ()))
        if len(shape) == 1 and shape[0] >= total:
            return "flat", int(shape[0])
    return "tree", None


def _unflatten_like(vec, params_struct):
    """Inverse of `ravel_pytree` given only shapes: split `vec` into the
    params tree (tree_leaves order, C-order reshape — the exact layout
    train/step.py's ravel_pytree produces)."""
    import jax.numpy as jnp

    leaves, off = [], 0
    for l in jax.tree.leaves(params_struct):
        n = math.prod(l.shape)
        leaves.append(jnp.reshape(vec[off:off + n], l.shape).astype(l.dtype))
        off += n
    return jax.tree.unflatten(jax.tree.structure(params_struct), leaves)


def params_layout(params: Any, total: int) -> tuple:
    """Detect a params value's layout from shapes alone: ('flat', padded)
    when it is the single ZeRO-3 padded flat vector, ('tree', None) for the
    ordinary replicated params tree. Same shape argument as
    `opt_state_layout`: no single parameter leaf holds the whole network,
    so a 1-D leaf at least `total` long can only be the flat vector."""
    return opt_state_layout(params, total)


def flatten_params(params: Any, padded: int, *,
                   bucket_layout: Any = None):
    """Params tree → the ZeRO-3 flat vector: bucket-major
    (reverse-backward-order replica-interleaved, `to_global`) when a bucket
    layout is given, else the canonical tree_leaves-order ravel + zero pad.
    Pure and traceable."""
    import jax.numpy as jnp

    if bucket_layout is not None:
        return bucket_layout.to_global(params)
    vec = jnp.concatenate(
        [jnp.ravel(l).astype(jnp.float32) for l in jax.tree.leaves(params)])
    return jnp.pad(vec, (0, padded - vec.shape[0]))


def convert_params(params: Any, params_struct: Any,
                   target_padded: int | None, *,
                   src_bucket_layout: Any = None,
                   target_bucket_layout: Any = None) -> Any:
    """Layout-convert a params (or EMA params) value: replicated tree ↔
    ZeRO-3 canonical flat ↔ ZeRO-3 bucket-major flat. Pure and traceable —
    run under `jit` with target shardings as `out_shardings`, exactly like
    `convert_opt_state`. `target_padded=None` means the replicated tree
    layout; `src_bucket_layout` says how to READ a saved flat vector (None
    = canonical tree_leaves order — the pre-bucketed default, matching the
    geometry receipt's absence)."""
    p_leaves = jax.tree.leaves(params_struct)
    total = int(sum(math.prod(l.shape) for l in p_leaves))
    layout, padded_src = params_layout(params, total)
    if layout == "flat":
        if src_bucket_layout is not None:
            if padded_src != src_bucket_layout.total_padded:
                raise ValueError(
                    f"src bucket layout total_padded="
                    f"{src_bucket_layout.total_padded} does not match the "
                    f"saved flat params length {padded_src}")
            tree = src_bucket_layout.from_global(jax.tree.leaves(params)[0])
        else:
            tree = _unflatten_like(jax.tree.leaves(params)[0][:total],
                                   params_struct)
    else:
        tree = params
    if target_padded is None:
        return tree
    if target_bucket_layout is not None \
            and target_padded != target_bucket_layout.total_padded:
        raise ValueError(
            f"target_padded={target_padded} disagrees with the target "
            f"bucket layout's total_padded="
            f"{target_bucket_layout.total_padded}")
    return flatten_params(tree, target_padded,
                          bucket_layout=target_bucket_layout)


def convert_opt_state(opt_state: Any, tx, params_struct: Any,
                      target_padded: int | None, *,
                      src_bucket_layout: Any = None,
                      target_bucket_layout: Any = None) -> Any:
    """Layout-convert an optax state: replicated params-tree ↔ ZeRO-1
    padded-flat (any shard count) ↔ ZeRO-2 bucket-major flat
    (parallel/buckets.GradBucketLayout). Pure and traceable — run it under
    `jit` with the target shardings as `out_shardings` and XLA places the
    result directly into the target topology (single- or multi-host).

    `target_padded`: the target flat-vector length (`padded_flat_size`, or
    the bucket layout's `total_padded` when `target_bucket_layout` is
    given — they must agree), or None for the replicated params-tree
    layout. `src_bucket_layout`: how to READ a saved flat vector — None
    means the canonical tree_leaves-order ZeRO-1 layout; a layout object
    means the checkpoint was written by the bucketed exchange (the
    geometry receipt in the checkpoint's `extra`; checkpoint/
    retopology.py rebuilds and verifies it). Padding regions carry zeros:
    a fresh pad is exactly what the momentum trace holds there (gradients
    of padding are identically zero), so growing/shrinking/re-bucketing
    the pad is lossless.

    The walk relies on one optax-chain invariant: the source and target
    states come from the same `tx`, so their structures differ ONLY where the
    params-(sub)tree of a stateful transform is replaced by the flat vector —
    leaf order is otherwise preserved. Every leaf shape is checked; a
    transform violating the invariant fails loudly, never silently."""
    import jax.numpy as jnp

    p_leaves = jax.tree.leaves(params_struct)
    total = int(sum(math.prod(l.shape) for l in p_leaves))
    n_pleaves = len(p_leaves)
    layout, padded_src = opt_state_layout(opt_state, total)
    if src_bucket_layout is not None and layout == "flat" \
            and padded_src != src_bucket_layout.total_padded:
        raise ValueError(
            f"src bucket layout total_padded="
            f"{src_bucket_layout.total_padded} does not match the saved "
            f"flat vector length {padded_src}")
    if target_bucket_layout is not None \
            and target_padded != target_bucket_layout.total_padded:
        raise ValueError(
            f"target_padded={target_padded} disagrees with the target "
            f"bucket layout's total_padded="
            f"{target_bucket_layout.total_padded}")

    # source → canonical params-tree-grouped leaf list
    canon = []
    for leaf in jax.tree.leaves(opt_state):
        if layout == "flat" and leaf.ndim == 1 and leaf.shape[0] == padded_src:
            if src_bucket_layout is not None:
                canon.extend(jax.tree.leaves(
                    src_bucket_layout.from_global(leaf)))
            else:
                canon.extend(jax.tree.leaves(
                    _unflatten_like(leaf[:total], params_struct)))
        else:
            canon.append(leaf)

    # canonical → target layout
    if target_padded is not None:
        t_struct = jax.eval_shape(
            tx.init, jax.ShapeDtypeStruct((target_padded,), jnp.float32))
    else:
        t_struct = jax.eval_shape(tx.init, params_struct)
    out, ci = [], 0
    for f in jax.tree.leaves(t_struct):
        if target_padded is not None and f.ndim == 1 \
                and f.shape[0] == target_padded:
            group = canon[ci:ci + n_pleaves]
            ci += n_pleaves
            if target_bucket_layout is not None:
                tree = jax.tree.unflatten(jax.tree.structure(params_struct),
                                          group)
                out.append(target_bucket_layout.to_global(tree)
                           .astype(f.dtype))
            else:
                vec = jnp.concatenate([jnp.ravel(g) for g in group])
                out.append(jnp.pad(vec, (0, target_padded - total))
                           .astype(f.dtype))
        else:
            leaf = canon[ci]
            ci += 1
            if tuple(leaf.shape) != tuple(f.shape):
                raise ValueError(
                    f"opt-state leaf shape mismatch during layout "
                    f"conversion: {tuple(leaf.shape)} vs {tuple(f.shape)} — "
                    f"optimizer chain not convertible")
            out.append(jnp.asarray(leaf, f.dtype))
    if ci != len(canon):
        raise ValueError(
            f"opt-state leaf count mismatch: consumed {ci} of {len(canon)}")
    return jax.tree.unflatten(jax.tree.structure(t_struct), out)
