"""Live elastic resize — continue training on the survivors when k of N
data shards are preempted (r19, ROADMAP item 1; the cross-replica
weight-resharding move of arXiv 2004.13336 closed into the recovery loop
that arXiv 1605.08695's restart-from-checkpoint model never closes).

The pieces were all staged by earlier rounds; this module composes them
into one in-place transition:

1. **Who died** — `PreemptConsensus.flagged_ranks` (parallel/preempt.py)
   or the rank-targeted chaos token (`preempt@rankR[+R2...]:N`,
   resilience/faults.py) names the dead data-axis positions.
2. **Shrunken mesh** — `shrink_mesh` drops the dead positions from the
   device array; survivor devices keep their order, so the new mesh is
   the old one with the reclaimed capacity cut out.
3. **Param/opt-state reshard** — `reshard_train_state` generalizes the
   checkpoint-mediated retopology path (checkpoint/retopology.py) to a
   LIVE any-geometry N→N−k conversion: params/EMA/batch_stats are
   replicated (survivors already hold full replicas — nothing to
   evacuate), and the ZeRO-1/2 flat opt-state vector is re-partitioned /
   re-bucketed through `zero.convert_opt_state` with the r14
   `GradBucketLayout` geometry receipts on both sides, placed straight
   into the new topology by jit `out_shardings`. In a real multi-host
   fleet the dead ranks' shards come from the forced preemption
   checkpoint (written before the resize is attempted); single-controller
   meshes read them from the survivor-held global view directly.
4. **Data handoff** — pure cursor handoff via the PR 15 iterator-state
   blob: the trainer captures `capture_state(next_step)`, builds a FRESH
   ingest over the new topology, and `restore_from_blob` re-derives the
   stream at the exact position (every stream is a pure function of
   (seed, position)) — zero replayed batches, routing-only ownership for
   the disaggregated service (data/service_client.py already reassigns a
   dead worker's cursors without moving data).
5. **Batch semantics** — explicit, not implicit (`ResizePlan.batch_policy`
   from `mesh.elastic.batch_policy`): `keep_global` reassigns the dead
   shards' rows to survivors (global batch and LR unchanged — the loss
   trajectory is pinned equal to a restart-from-checkpoint control on the
   same survivor count); `scale_lr` keeps the per-replica batch invariant
   (survivors keep exactly their own rows via `trim_batches`) and
   rescales the LR by N′/N (linear-scaling rule), receipted in the
   `elastic_lr_rescale` log event.

Everything that can make the transition unsound refuses loudly instead:
`plan_resize` raises the typed `ElasticDegraded` (resilience/errors.py)
and the trainer falls back to the r18 restart-from-checkpoint path with
the `elastic_degraded_restart` flight class — never `unhandled_exception`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from distributed_vgg_f_tpu.resilience.errors import ElasticDegraded


@dataclasses.dataclass(frozen=True)
class ResizePlan:
    """One planned N→N−k transition, fully decided before anything moves."""

    old_size: int                 # data-axis size before the resize
    new_size: int                 # survivor count (the new data-axis size)
    dead_ranks: tuple             # data-axis positions being reclaimed
    batch_policy: str             # keep_global | scale_lr
    lr_scale: float               # 1.0 under keep_global; N'/N under scale_lr

    @property
    def topology_label(self) -> str:
        """The regression-sentinel basis label (regress.Basis.topology):
        `elastic_<N>to<M>` — a post-resize rate and a static-mesh rate are
        different machines and must never gate cross-wise."""
        return f"elastic_{self.old_size}to{self.new_size}"

    def describe(self) -> dict:
        return {"old_size": self.old_size, "new_size": self.new_size,
                "dead_ranks": list(self.dead_ranks),
                "batch_policy": self.batch_policy,
                "lr_scale": self.lr_scale,
                "topology": self.topology_label}


def plan_resize(mesh: Mesh, data_axis: str, dead_ranks: Sequence[int], *,
                elastic_cfg, global_batch: int,
                have_cursor: bool) -> ResizePlan:
    """Validate a proposed resize and freeze it into a `ResizePlan`, or
    raise `ElasticDegraded` with a machine-readable `.reason` naming why
    the fleet should restart instead. Nothing is mutated here — the plan
    is decided in full before the trainer touches any live object, so a
    refused resize leaves the r18 stop path bit-for-bit intact."""
    old_size = int(mesh.shape[data_axis])
    dead = tuple(sorted({int(r) for r in dead_ranks}))
    if not dead:
        raise ElasticDegraded(
            "unidentified_ranks",
            "preemption consensus fired but no dead rank was identified "
            "(untargeted preempt or a signal with no flagged rank) — "
            "cannot plan a survivor set")
    if any(r < 0 or r >= old_size for r in dead):
        raise ElasticDegraded(
            "rank_out_of_range",
            f"dead ranks {list(dead)} not all within the data axis "
            f"[0, {old_size})")
    if jax.process_count() > 1:
        # Honest scope: re-forming a jax.distributed world over fewer
        # processes needs a coordinator restart — the LIVE in-place resize
        # is a single-controller (one process, many devices) move; a
        # multi-controller fleet takes the checkpointed restart onto the
        # survivor slice (the checkpoint restores onto any topology,
        # checkpoint/retopology.py).
        raise ElasticDegraded(
            "multi_controller",
            f"live in-place resize is single-controller; "
            f"{jax.process_count()} processes must restart onto the "
            "survivor slice (retopology restore handles the geometry)")
    new_size = old_size - len(dead)
    if new_size < max(1, int(elastic_cfg.min_survivors)):
        raise ElasticDegraded(
            "too_few_survivors",
            f"{new_size} survivor(s) < mesh.elastic.min_survivors="
            f"{elastic_cfg.min_survivors} — restart on fresh capacity "
            "instead of limping")
    policy = elastic_cfg.batch_policy
    if policy == "keep_global":
        if global_batch % new_size != 0:
            raise ElasticDegraded(
                "indivisible_global_batch",
                f"keep_global needs data.global_batch_size={global_batch} "
                f"divisible by the survivor count {new_size}")
        lr_scale = 1.0
    else:  # scale_lr (config validated the enum)
        per_replica, rem = divmod(global_batch, old_size)
        if rem != 0:
            raise ElasticDegraded(
                "indivisible_global_batch",
                f"scale_lr needs data.global_batch_size={global_batch} "
                f"divisible by the OLD shard count {old_size} (per-replica "
                "rows must be whole)")
        lr_scale = new_size / old_size
    if not have_cursor:
        raise ElasticDegraded(
            "no_resumable_ingest",
            "elastic data handoff needs the position-exact cursor blob "
            "(data.iterator_state.enabled + a trainer-owned stream); "
            "without it a resize would replay or skip batches")
    return ResizePlan(old_size=old_size, new_size=new_size, dead_ranks=dead,
                      batch_policy=policy, lr_scale=lr_scale)


def survivor_ranks(plan: ResizePlan) -> tuple:
    dead = set(plan.dead_ranks)
    return tuple(r for r in range(plan.old_size) if r not in dead)


def shrink_mesh(mesh: Mesh, data_axis: str, plan: ResizePlan) -> Mesh:
    """The survivor mesh: the old device array with the dead data-axis
    positions removed, order preserved — every surviving device keeps its
    relative rank, so survivor-held arrays re-place without permutation."""
    axis_idx = list(mesh.axis_names).index(data_axis)
    dev_array = np.take(mesh.devices, survivor_ranks(plan), axis=axis_idx)
    return Mesh(dev_array, axis_names=tuple(mesh.axis_names))


def reshard_train_state(state, tx, *, params_struct,
                        target_padded: Optional[int],
                        src_bucket_layout: Any,
                        target_bucket_layout: Any,
                        replicated, opt_shardings,
                        target_params_padded: Optional[int] = None,
                        params_shardings: Any = None):
    """Live any-geometry reshard of a TrainState onto a new mesh.

    The state is first pulled to host as its GLOBAL value (on a
    single-controller mesh every shard is addressable; `plan_resize`
    refused anything else — a multi-host fleet reads the same global view
    out of the forced preemption checkpoint via retopology restore). The
    opt state then flows through the SAME pure converter the checkpoint
    path uses (`zero.convert_opt_state`, src/target bucket-layout receipts
    included) under jit whose `out_shardings` place the result directly
    into the new topology. Params and EMA (r21): replicated trees re-place
    with one `device_put`; ZeRO-3 flat vectors flow through the matching
    `zero.convert_params` (the N→M re-interleave is a real permutation
    when bucketed, a re-pad when canonical) onto `params_shardings` —
    `target_params_padded` None means the new topology holds params as the
    replicated tree (the zero3 → zero2/dp downgrade, e.g. a resize to one
    shard). Step/batch_stats are replicated in ALL layouts. Both the
    elastic path and a restart control therefore apply the identical
    conversion — which is what makes the chaos-grid trajectory equality a
    meaningful pin rather than a coincidence."""
    import functools

    from distributed_vgg_f_tpu.parallel.zero import (convert_opt_state,
                                                     convert_params,
                                                     flat_param_count,
                                                     params_layout)

    host_state = jax.device_get(state)
    convert = jax.jit(
        functools.partial(convert_opt_state, tx=tx,
                          params_struct=params_struct,
                          target_padded=target_padded,
                          src_bucket_layout=src_bucket_layout,
                          target_bucket_layout=target_bucket_layout),
        out_shardings=opt_shardings)
    new_opt = convert(host_state.opt_state)
    src_p_layout, _ = params_layout(host_state.params,
                                    flat_param_count(params_struct))
    if src_p_layout == "flat" or target_params_padded is not None:
        conv_p = jax.jit(
            functools.partial(convert_params, params_struct=params_struct,
                              target_padded=target_params_padded,
                              src_bucket_layout=src_bucket_layout,
                              target_bucket_layout=(
                                  target_bucket_layout
                                  if target_params_padded is not None
                                  else None)),
            out_shardings=(params_shardings
                           if params_shardings is not None else replicated))
        new_params = conv_p(host_state.params)
        new_ema = (conv_p(host_state.ema_params)
                   if host_state.ema_params is not None
                   else host_state.ema_params)
        host_state = host_state.replace(params=None, ema_params=None)
        placed = jax.tree.map(lambda l: jax.device_put(l, replicated),
                              host_state.replace(opt_state=None))
        return placed.replace(opt_state=new_opt, params=new_params,
                              ema_params=new_ema)
    placed = jax.tree.map(lambda l: jax.device_put(l, replicated),
                          host_state.replace(opt_state=None))
    return placed.replace(opt_state=new_opt)


def trim_batches(source: Iterator, plan: ResizePlan,
                 global_batch: int) -> Iterator:
    """The `scale_lr` host-batch adapter: each survivor keeps exactly ITS
    OWN contiguous per-replica rows; the dead ranks' rows are dropped (the
    global batch shrinks by N′/N — the LR rescale compensates). No
    mid-stream rebatching: regrouping rows would fork the SplitMix64
    shuffle basis the cursor blob names, so the stream stays a pure
    function of (seed, position) and cursor counting is unchanged."""
    per = global_batch // plan.old_size
    keep = np.concatenate([np.arange(r * per, (r + 1) * per)
                           for r in survivor_ranks(plan)])

    def gen():
        for batch in source:
            yield {k: np.asarray(v)[keep] for k, v in batch.items()}

    return gen()
