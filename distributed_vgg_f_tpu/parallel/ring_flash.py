"""Ring × flash: sequence-parallel attention with Pallas block kernels.

`parallel/ring_attention.py` proves the mesh's sequence axis opens (SURVEY.md
§5 long-context) with einsum block math; its one cost is autodiff residuals —
jax saves each ring step's (B, H, T_loc, T_loc) probs, so backward memory is
O(T_loc · T_global) per device. This module composes the same ppermute ring
schedule with the Pallas blockwise kernels (ops/flash_attention.py
`flash_block_update` / `flash_block_grads`) under a custom VJP:

  forward: K/V blocks circulate the ring; each step folds the visiting block
    into online-softmax state (acc, m, l) INSIDE the kernel — nothing
    quadratic ever exists. Residuals: q, k, v, out, logsumexp — O(T_loc · D).
  backward: K/V blocks circulate again (recompute, the flash trade), each
    paired with fp32 dK/dV accumulators that TRAVEL WITH their block; every
    device adds its contribution as the block visits, and one final hop
    returns each accumulator to its owner. dQ accumulates locally.

Same collective schedule as the einsum ring (causal masking by global
position never changes who sends what to whom — ring_attention.py's
documented design rule); the q-block offset is a traced `axis_index`
product, which is why the block kernels take dynamic offsets via SMEM.

Exactness (vs full attention, INCLUDING gradients) is tested on 2/4/8-device
CPU meshes with interpreted kernels: tests/test_ring_flash.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

# check_vma-kwarg-translating shim over jax.shard_map /
# jax.experimental.shard_map (parallel/compat.py)
from distributed_vgg_f_tpu.parallel.compat import axis_size, shard_map

from distributed_vgg_f_tpu.ops import flash_attention as _fa
from distributed_vgg_f_tpu.ops.flash_attention import (
    _bh_layout, _bthd_layout, flash_block_grads, flash_block_update,
    pad_to_block)


@functools.lru_cache(maxsize=16)
def _local_fn(axis_name: str, causal: bool, interpret: bool,
              kv_len: int | None = None):
    """The per-device function run under shard_map, with its custom VJP.

    `kv_len`: when the local shard was padded to a block multiple
    (pad_to_block — prime-ish t_loc like 197 would otherwise degrade the
    kernels to block-1 grids, VERDICT r4 weak #4), the first `kv_len` rows
    of EVERY circulating block are real and the tail is padding. Padded
    keys are masked inside the kernels (p = 0 exactly → their traveling
    dk/dv rows stay zero); padded query rows are discarded by the caller's
    slice, and the causal global-position math stays consistent because
    the real-index → padded-position map is monotone."""

    def _perm(n):
        return [(i, (i + 1) % n) for i in range(n)]

    def _forward(q3, k3, v3):
        n = axis_size(axis_name)
        my = lax.axis_index(axis_name)
        bh, t, d = q3.shape
        t_real = kv_len if kv_len is not None else t
        acc = jnp.zeros((bh, t, d), jnp.float32)
        m = jnp.full((bh, t, 1), -jnp.inf, jnp.float32)
        l = jnp.zeros((bh, t, 1), jnp.float32)
        k_blk, v_blk = k3, v3
        q_off = my * t
        for step in range(n):
            k_off = ((my - step) % n) * t

            def _update(acc, m, l, k_blk=k_blk, v_blk=v_blk, k_off=k_off):
                return flash_block_update(
                    q3, k_blk, v_blk, acc, m, l, q_off=q_off, k_off=k_off,
                    causal=causal, kv_len=kv_len, interpret=interpret)

            if causal and n > 1:
                # A visiting block whose every key is in this device's
                # future contributes EXACTLY the identity (s = -inf
                # everywhere: corr = 1, p = 0 — safe because step 0 is the
                # self block, so the state is never virgin here). Skip the
                # whole kernel call under lax.cond: the collective schedule
                # below stays uniform across devices, only the local DMAs +
                # MXU work for dead blocks disappear — on average half the
                # causal ring (device my skips the n−1−my future owners).
                acc, m, l = lax.cond(
                    # first (real) key past the last REAL query — padded
                    # query rows are discarded, so they never widen the
                    # live set
                    k_off > q_off + t_real - 1,
                    lambda a, mm, ll: (a, mm, ll), _update,
                    acc, m, l)
            else:
                acc, m, l = _update(acc, m, l)
            if step < n - 1:
                k_blk = lax.ppermute(k_blk, axis_name, _perm(n))
                v_blk = lax.ppermute(v_blk, axis_name, _perm(n))
        out3 = (acc / l).astype(q3.dtype)
        lse = m + jnp.log(l)
        return out3, lse

    @jax.custom_vjp
    def op(q3, k3, v3):
        out3, _ = _forward(q3, k3, v3)
        return out3

    def op_fwd(q3, k3, v3):
        out3, lse = _forward(q3, k3, v3)
        return out3, (q3, k3, v3, out3, lse)

    def op_bwd(res, g3):
        q3, k3, v3, out3, lse = res
        n = axis_size(axis_name)
        my = lax.axis_index(axis_name)
        bh, t, d = q3.shape
        t_real = kv_len if kv_len is not None else t
        do3 = g3.astype(q3.dtype)
        delta = jnp.sum(do3.astype(jnp.float32) * out3.astype(jnp.float32),
                        axis=-1, keepdims=True)
        dq = jnp.zeros((bh, t, d), jnp.float32)
        dk_blk = jnp.zeros((bh, t, d), jnp.float32)
        dv_blk = jnp.zeros((bh, t, d), jnp.float32)
        k_blk, v_blk = k3, v3
        q_off = my * t
        for step in range(n):
            k_off = ((my - step) % n) * t

            def _grads(dq, dk_blk, dv_blk, k_blk=k_blk, v_blk=v_blk,
                       k_off=k_off):
                return flash_block_grads(
                    q3, k_blk, v_blk, do3, lse, delta, dq, dk_blk, dv_blk,
                    q_off=q_off, k_off=k_off, causal=causal, kv_len=kv_len,
                    interpret=interpret)

            if causal and n > 1:
                # fully-future visiting block: p = exp(-inf − lse) = 0 —
                # zero contribution to dq AND to the traveling dk/dv
                # accumulators; skip the kernels (same uniform-schedule
                # argument as the forward)
                dq, dk_blk, dv_blk = lax.cond(
                    # same real-rows predicate as the forward skip
                    k_off > q_off + t_real - 1,
                    lambda a, b, c: (a, b, c), _grads,
                    dq, dk_blk, dv_blk)
            else:
                dq, dk_blk, dv_blk = _grads(dq, dk_blk, dv_blk)
            if step < n - 1:
                k_blk = lax.ppermute(k_blk, axis_name, _perm(n))
                v_blk = lax.ppermute(v_blk, axis_name, _perm(n))
                dk_blk = lax.ppermute(dk_blk, axis_name, _perm(n))
                dv_blk = lax.ppermute(dv_blk, axis_name, _perm(n))
        # block o last visited device (o-1) mod n — one hop brings its
        # accumulated gradients home
        dk3 = lax.ppermute(dk_blk, axis_name, _perm(n))
        dv3 = lax.ppermute(dv_blk, axis_name, _perm(n))
        return (dq.astype(q3.dtype), dk3.astype(k3.dtype),
                dv3.astype(v3.dtype))

    op.defvjp(op_fwd, op_bwd)

    def local(q, k, v):
        b, t, h, d = q.shape
        if kv_len is not None:
            # pad the local shard to the planned block multiple; the pad
            # tail is masked as keys (kv_len) and sliced off as queries
            pad = ((0, 0), (0, pad_to_block(t)[0] - t), (0, 0), (0, 0))
            q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
        out3 = op(_bh_layout(q), _bh_layout(k), _bh_layout(v))
        out = _bthd_layout(out3, b, h)
        return out[:, :t] if kv_len is not None else out

    return local


@functools.lru_cache(maxsize=8)
def _ring_flash_fn(mesh: Mesh, axis_name: str, causal: bool, interpret: bool,
                   kv_len: int | None):
    seq_spec = P(None, axis_name)
    return jax.jit(shard_map(
        _local_fn(axis_name, causal, interpret, kv_len),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec),
        out_specs=seq_spec,
        check_vma=False,
    ))


def ring_flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         mesh: Mesh, axis_name: str = "data",
                         causal: bool = False) -> jnp.ndarray:
    """GLOBAL (B, T, H, D) inputs sharded on T over `axis_name`; exact
    attention, differentiable, O(T_loc · D) residual memory per device.
    T must divide evenly by the axis size (pad upstream — `ring_attention`'s
    contract); within a device the kernels auto-pick the largest ≤128 block
    that divides T_loc (ops/flash_attention.pick_block), and when T_loc's
    own divisors are a perf cliff (prime-ish shards like 394/2 → 197) each
    shard is padded to a 128-multiple with the tail masked — exact incl.
    grads, never a block-1 grid (pad_to_block; VERDICT r4 weak #4)."""
    if q.shape[1] % mesh.shape[axis_name] != 0:
        raise ValueError(
            f"sequence length {q.shape[1]} not divisible by mesh axis "
            f"{axis_name} size {mesh.shape[axis_name]}")
    t_loc = q.shape[1] // mesh.shape[axis_name]
    kv_len = t_loc if pad_to_block(t_loc)[0] != t_loc else None
    return _ring_flash_fn(mesh, axis_name, causal, _fa.INTERPRET,
                          kv_len)(q, k, v)
