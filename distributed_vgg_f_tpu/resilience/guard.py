"""Host-side non-finite step monitor — the production replacement for the
debug-only `jax_debug_nans` flag.

The jitted train step (train/step.py, `skip_nonfinite=True`) already decides
ON DEVICE whether the step was finite — `isfinite(loss) & isfinite(grad_norm)`
over the cross-replica-reduced values, so every replica takes the identical
keep/skip select — and reports the decision as the `bad_step` metric. This
class is the host half: it counts consecutive skips and aborts with a
diagnostic once the run is clearly not training anymore.

Reading `bad_step` the naive way (a `device_get` right after dispatch) would
block the host on every step and collapse the async-dispatch pipeline that
hides feed latency. Instead the guard uses the same lagged-poll idiom as
`parallel/preempt.py PreemptConsensus`: each step's flag is queued and read
LAG steps later, when the device has long since finished it — the poll costs
a no-op sync. The price is that the abort fires up to LAG steps after the
threshold is crossed; the skipped steps in between changed nothing (the
device-side select already dropped their updates), so the lag is free.
"""

from __future__ import annotations

import collections
from typing import Optional

import jax

from distributed_vgg_f_tpu.resilience.errors import NonFiniteStepError


class NonFiniteGuard:
    """Counts device-reported bad steps; raises after `max_consecutive`.

    Usage (one instance per fit loop):

        guard = NonFiniteGuard(max_consecutive=10, logger=logger)
        for step in ...:
            state, metrics = train_step(...)
            guard.observe(step + 1, metrics["bad_step"])   # async, lagged
        guard.drain()                                      # flush the tail
    """

    LAG = 2  # steps between dispatch and poll — poll target is always done

    def __init__(self, max_consecutive: int, logger=None):
        if max_consecutive < 1:
            raise ValueError(
                f"max_consecutive must be >= 1, got {max_consecutive}")
        self.max_consecutive = max_consecutive
        self.consecutive = 0
        self.total = 0
        self._last_bad_step: Optional[int] = None
        self._logger = logger
        self._pending: collections.deque = collections.deque()

    def observe(self, step: int, bad_flag) -> None:
        """Queue this step's device `bad_step` scalar; resolve the one from
        LAG steps ago. Raises NonFiniteStepError once `max_consecutive`
        consecutive steps were skipped."""
        self._pending.append((step, bad_flag))
        if len(self._pending) > self.LAG:
            self._check(*self._pending.popleft())

    def drain(self) -> None:
        """Resolve every still-queued flag (call after the loop ends, so a
        bad tail shorter than LAG is not silently dropped)."""
        while self._pending:
            self._check(*self._pending.popleft())

    def _check(self, step: int, bad_flag) -> None:
        bad = float(jax.device_get(bad_flag)) > 0.0
        if not bad:
            self.consecutive = 0
            return
        self.consecutive += 1
        self.total += 1
        self._last_bad_step = step
        # Registry feed (telemetry/): the skip count is the guard_stalled
        # signal in the stall-attribution verdict — a window that skipped
        # every update spent wall time without training.
        from distributed_vgg_f_tpu import telemetry
        telemetry.inc("resilience/nonfinite_skips")
        if self._logger is not None and jax.process_index() == 0:
            self._logger.log("nonfinite_step_skipped", {
                "step": step, "consecutive": self.consecutive,
                "total": self.total})
        if self.consecutive >= self.max_consecutive:
            telemetry.inc("resilience/nonfinite_aborts")
            # name the crash class for the flight recorder BEFORE raising:
            # the trainer's black-box dump reads the freshest note instead
            # of re-deriving the diagnosis from exception types
            from distributed_vgg_f_tpu.telemetry import flight
            flight.note_crash(
                "nonfinite_abort",
                f"{self.consecutive} consecutive non-finite steps through "
                f"step {step} (threshold {self.max_consecutive})")
            raise NonFiniteStepError(
                f"{self.consecutive} consecutive training steps (through "
                f"step {step}) produced a non-finite loss or gradient norm; "
                f"their optimizer updates were skipped (parameters are "
                f"unchanged since step {step - self.consecutive}), but the "
                f"run is not training — aborting instead of burning fleet "
                f"time. Common causes: corrupt/NaN input batches (check "
                f"data_decode_errors in the metrics log), an out-of-range "
                f"label space, or a diverging learning rate (try "
                f"optim.grad_clip_norm or a lower optim.base_lr). "
                f"{self.total} step(s) were skipped in total this run; the "
                f"abort threshold is train.max_nonfinite_steps="
                f"{self.max_consecutive}.")
