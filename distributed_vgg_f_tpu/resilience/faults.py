"""Config-driven fault injection — the chaos half of the resilience layer.

The guards in this package (non-finite step skip, data watchdog, checkpoint
integrity fallback, preemption consensus) exist for faults that real fleets
throw rarely and CI never does. This module makes those faults reproducible
on demand so the guard paths are exercised in tests (tests/test_resilience.py)
and in staging runs, not discovered during the next real outage.

A `FaultPlan` is parsed from the `train.fault_injection` config string (CLI:
`--set train.fault_injection="nan@3,stall@5:20"`) — empty string means no
injection, the production default. Grammar: comma-separated tokens, steps
are 1-based COMPLETED-step numbers (step N faults the batch consumed by the
N-th training step):

    nan@N          replace step N's batch images with NaN
    nan@N+         ... every batch from step N on (drives the abort path)
    nan@N-M        ... steps N through M inclusive
    stall@N:SECS   loader sleeps SECS before yielding step N's batch
                   (drives the prefetch watchdog -> DataStallError)
    crash@N        loader raises InjectedFault instead of yielding step N
    preempt@N      raise the trainer's preemption flag after step N
                   completes (drives the SIGTERM path incl. the multi-host
                   PreemptConsensus collective, without a real signal)
    preempt@rankR[+R2...]:N
                   rank-targeted preemption (r19 elastic chaos): after
                   step N completes, mark data-axis ranks R, R2, ... as
                   preempted — the trainer raises the consensus flag AND
                   records the flagged ranks, so an elastic-enabled run
                   resizes onto the survivors (parallel/elastic.py) while
                   a disabled run takes the plain preempt@N stop path.
                   Mutually exclusive with preempt@N (one preempt
                   injector per plan).
    worker@N       kill one LIVE disaggregated-ingest decode worker before
                   yielding step N's batch (r16: the service client
                   registers the kill hook and sends the production
                   shutdown op — a real mid-epoch worker death, driving
                   the failover/reassignment path; a no-service run logs
                   a warning and injects nothing)
    sigkill@N      SIGKILL THIS process before yielding step N's batch —
                   a real un-catchable mid-epoch death (no atexit, no
                   flushes), the restart-from-checkpoint + position-exact
                   iterator-state resume drill (r18; the chaos harness
                   reruns the same command without the token and pins
                   loss-trajectory equality vs an uninterrupted run)

Checkpoint-write truncation is a post-hoc injector (`truncate_checkpoint`):
it damages an already-committed step the way an interrupted upload or a
partial rsync would, which is the case the integrity manifests exist for —
an in-band injector could only corrupt data Orbax has not yet committed,
which its staging atomicity already discards.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time
from typing import Iterator, Optional

import numpy as np

from distributed_vgg_f_tpu.resilience.errors import ResilienceError


class InjectedFault(ResilienceError):
    """Raised by the crash injector — a stand-in for a loader worker dying
    mid-run (the prefetch layer relays it to the consumer)."""


_TOKEN = re.compile(
    r"^(?P<kind>nan|stall|crash|preempt|worker|sigkill)@(?P<step>\d+)"
    r"(?P<tail>\+|-\d+|:\d+(\.\d+)?)?$")

# rank-targeted preemption (r19): preempt@rank0+2:5 = ranks {0, 2} are
# preempted after step 5 completes. Tried before _TOKEN — the generic
# regex cannot match the "rank" spelling, but a dedicated pattern keeps
# the error message for near-misses (preempt@rank:5, no rank list) exact.
_RANK_TOKEN = re.compile(
    r"^preempt@rank(?P<ranks>\d+(\+\d+)*):(?P<step>\d+)$")


# -- worker-kill hook (r16 disaggregated ingest) -----------------------------
# The injector must not import the data layer; the service client
# (data/service_client.py) registers its chaos hook here at construction
# and clears it on close. The hook asks one live decode worker to shut
# down through the production protocol and returns its endpoint (or None
# when nothing was alive to kill).
_worker_kill_hook = None


def set_worker_kill_hook(fn) -> None:
    global _worker_kill_hook
    _worker_kill_hook = fn


def clear_worker_kill_hook(fn) -> None:
    """Clear only when `fn` is still the registered hook — a closing
    client must not sever a successor's registration."""
    global _worker_kill_hook
    if _worker_kill_hook is fn:
        _worker_kill_hook = None


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Immutable injection schedule; build with `FaultPlan.parse`."""

    nan_start: Optional[int] = None
    nan_end: Optional[int] = None        # inclusive; None = open-ended
    stall_step: Optional[int] = None
    stall_seconds: float = 0.0
    crash_step: Optional[int] = None
    preempt_step: Optional[int] = None
    # rank-targeted preemption (preempt@rankR[+R2...]:N): the data-axis
    # ranks flagged when preempt_step fires; () = untargeted preempt@N.
    preempt_ranks: tuple = ()
    worker_kill_step: Optional[int] = None
    sigkill_step: Optional[int] = None

    @classmethod
    def parse(cls, spec: str) -> Optional["FaultPlan"]:
        """Parse the config grammar above; "" -> None (no injection). A
        malformed spec fails loudly — a typo'd chaos run silently becoming a
        clean run defeats the point of the harness."""
        spec = (spec or "").strip()
        if not spec:
            return None
        fields: dict = {}
        seen_kinds: set = set()
        for token in (t.strip() for t in spec.split(",") if t.strip()):
            rm = _RANK_TOKEN.match(token)
            if rm is not None:
                if "preempt" in seen_kinds:
                    raise ValueError(
                        f"duplicate 'preempt' token {token!r}: one "
                        f"injector of each kind per plan")
                seen_kinds.add("preempt")
                step = int(rm["step"])
                if step < 1:
                    raise ValueError(
                        f"fault step must be >= 1 in {token!r}")
                ranks = tuple(int(r) for r in rm["ranks"].split("+"))
                if len(set(ranks)) != len(ranks):
                    raise ValueError(
                        f"duplicate rank in {token!r}")
                fields["preempt_step"] = step
                fields["preempt_ranks"] = tuple(sorted(ranks))
                continue
            m = _TOKEN.match(token)
            if m is None:
                raise ValueError(
                    f"bad fault token {token!r}; expected nan@N[+|-M], "
                    f"stall@N:SECONDS, crash@N, preempt@N, or "
                    f"preempt@rankR[+R2...]:N")
            kind, step = m["kind"], int(m["step"])
            tail = m["tail"] or ""
            if step < 1:
                raise ValueError(f"fault step must be >= 1 in {token!r}")
            if kind in seen_kinds:
                # last-token-wins would silently run a DIFFERENT schedule
                # than the spec reads — the silent-clean-run failure mode
                # this parser exists to prevent (code-review)
                raise ValueError(
                    f"duplicate {kind!r} token {token!r}: one injector of "
                    f"each kind per plan (use nan@N-M for a range)")
            seen_kinds.add(kind)
            if kind == "nan":
                if tail and tail != "+" and not tail.startswith("-"):
                    raise ValueError(
                        f"nan takes @N, @N+ or @N-M, got {token!r}")
                fields["nan_start"] = step
                fields["nan_end"] = (None if tail == "+"
                                     else int(tail[1:]) if tail
                                     else step)
                if fields["nan_end"] is not None \
                        and fields["nan_end"] < step:
                    raise ValueError(f"empty nan range in {token!r}")
            elif kind == "stall":
                if not tail.startswith(":"):
                    raise ValueError(
                        f"stall needs a duration: stall@N:SECONDS, "
                        f"got {token!r}")
                fields["stall_step"] = step
                fields["stall_seconds"] = float(tail[1:])
            elif kind == "crash":
                fields["crash_step"] = step
            elif kind == "worker":
                fields["worker_kill_step"] = step
            elif kind == "sigkill":
                fields["sigkill_step"] = step
            else:
                fields["preempt_step"] = step
            if tail and kind in ("crash", "preempt", "worker", "sigkill"):
                raise ValueError(f"{kind} takes no modifier, got {token!r}")
        return cls(**fields)

    # ------------------------------------------------------------- predicates
    @property
    def has_data_faults(self) -> bool:
        return (self.nan_start is not None or self.stall_step is not None
                or self.crash_step is not None
                or self.worker_kill_step is not None
                or self.sigkill_step is not None)

    def _nan_at(self, step: int) -> bool:
        return (self.nan_start is not None and step >= self.nan_start
                and (self.nan_end is None or step <= self.nan_end))

    def preempt_now(self, completed_step: int) -> bool:
        """True when the preemption flag should be raised after
        `completed_step` finished — the trainer feeds this into the same
        path a real SIGTERM takes (incl. PreemptConsensus multi-host)."""
        return self.preempt_step is not None \
            and completed_step >= self.preempt_step

    # -------------------------------------------------------------- injectors
    def wrap_iterator(self, source: Iterator, start_step: int = 0) -> Iterator:
        """Wrap a host-batch iterator with the data-fault injectors. The
        batch yielded for training step N (1-based) is the (N - start_step)-th
        draw — `start_step` keeps injection steps aligned after a resume."""

        def gen():
            # Injections announce themselves in the telemetry registry
            # (`fault/` namespace) so the chaos suite can assert that every
            # fired injector is VISIBLE in the same counter stream the
            # guards report through — a chaos run whose faults are
            # invisible in telemetry would be testing blind.
            from distributed_vgg_f_tpu import telemetry
            step = start_step
            for batch in source:
                step += 1
                if self.crash_step is not None and step == self.crash_step:
                    telemetry.inc("fault/crash")
                    from distributed_vgg_f_tpu.telemetry import flight
                    flight.note_crash(
                        "injected_crash",
                        f"fault_injection crash@{self.crash_step} at step "
                        f"{step}")
                    raise InjectedFault(
                        f"injected loader crash at step {step} "
                        f"(fault_injection crash@{self.crash_step})")
                if self.sigkill_step is not None \
                        and step == self.sigkill_step:
                    # a REAL un-catchable death: count first (best-effort —
                    # in-memory counters die with us; the parent harness
                    # observes rc == -SIGKILL), then kill this process
                    # before step N's batch ever reaches the trainer, so
                    # the last durable checkpoint is strictly mid-epoch
                    # behind the cursor
                    telemetry.inc("fault/sigkill")
                    import signal
                    os.kill(os.getpid(), signal.SIGKILL)
                if self.worker_kill_step is not None \
                        and step == self.worker_kill_step:
                    hook = _worker_kill_hook
                    if hook is None:
                        import logging
                        logging.getLogger(__name__).warning(
                            "fault_injection worker@%d: no disaggregated-"
                            "ingest client registered a kill hook "
                            "(data.service off?) — nothing injected",
                            self.worker_kill_step)
                    else:
                        killed = hook()
                        if killed is not None:
                            telemetry.inc("fault/worker_kill")
                if self.stall_step is not None and step == self.stall_step:
                    telemetry.inc("fault/stall")
                    time.sleep(self.stall_seconds)
                if self._nan_at(step):
                    telemetry.inc("fault/nan")
                    batch = dict(batch)
                    batch["image"] = np.full_like(
                        np.asarray(batch["image"]), np.nan)
                yield batch

        return gen()


def truncate_checkpoint(directory: str, step: Optional[int] = None,
                        keep_fraction: float = 0.5) -> str:
    """Damage a committed checkpoint the way an interrupted upload would:
    truncate the LARGEST file under the step dir (default: the newest step)
    to `keep_fraction` of its bytes. Returns the truncated file's path.
    Test/staging helper — pair with the manager's manifest verification to
    prove the fallback restore path end-to-end."""
    from distributed_vgg_f_tpu.resilience.integrity import step_dir
    if step is None:
        steps = [int(name) for name in os.listdir(directory)
                 if name.isdigit()]
        if not steps:
            raise FileNotFoundError(f"no step dirs under {directory}")
        step = max(steps)
    base = step_dir(directory, step)
    files = [os.path.join(dp, f)
             for dp, _, fs in os.walk(base) for f in fs]
    if not files:
        raise FileNotFoundError(f"no files under {base}")
    target = max(files, key=os.path.getsize)
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(max(0, int(size * keep_fraction)))
    return target
