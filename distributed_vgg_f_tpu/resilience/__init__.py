"""Resilience layer: survive the dirty faults long distributed runs actually
hit — non-finite steps, stalled input pipelines, damaged checkpoints — and a
fault-injection harness that keeps every guard path exercised in CI.

The clean failure modes were already first-class (preemption consensus in
parallel/preempt.py, cross-topology restore in checkpoint/retopology.py);
this package adds the guards for faults that would otherwise hang the run or
silently train on garbage:

- `guard.NonFiniteGuard` + train/step.py `skip_nonfinite`: bad steps are
  skipped on device (params bit-identical), K consecutive skips abort with
  `NonFiniteStepError` and a diagnostic.
- data/prefetch.py watchdog: a stalled or dead loader surfaces as
  `DataStallError` within a bounded backoff window instead of hanging.
- `integrity` + checkpoint/manager.py: saves write per-step checksum
  manifests and retry transient I/O errors; restores verify and fall back
  to the newest intact step, raising `CheckpointIntegrityError` only when
  nothing intact remains.
- `faults.FaultPlan`: config-driven injectors (`train.fault_injection`)
  proving each path end-to-end — tests/test_resilience.py is the chaos
  suite.
"""

from distributed_vgg_f_tpu.resilience.errors import (  # noqa: F401
    CheckpointIntegrityError,
    DataStallError,
    NonFiniteStepError,
    ResilienceError,
)
from distributed_vgg_f_tpu.resilience.faults import (  # noqa: F401
    FaultPlan,
    InjectedFault,
    truncate_checkpoint,
)
from distributed_vgg_f_tpu.resilience.guard import NonFiniteGuard  # noqa: F401
