"""Checkpoint integrity manifests: per-step checksums, written after a save
is durable and verified before a restore touches the data.

Orbax's own atomicity (stage to a tmp dir, rename to commit) protects
against crashes DURING a save — a partially-written step never appears under
its final name. What it does not protect against is post-commit damage: a
truncated object-store upload, filesystem corruption, a partial rsync of the
checkpoint dir, bit rot on a long-lived volume. The manifest layer covers
that gap: after a step is durable, `write_step_manifest` records every
file's size and SHA-256 under `<root>/integrity/<step>.json`; before a
restore, `verify_step_manifest` re-hashes and compares, so a damaged step is
detected up front (and `checkpoint/manager.py` falls back to the newest
intact one) instead of crashing mid-deserialization or silently loading
partial state.

A step WITHOUT a manifest verifies as `None` (unknown): pre-manifest
checkpoints and the crash window between a cadence save and its manifest
flush stay restorable — Orbax's commit atomicity already vouches for them.

Layout note: manifests live in `<root>/integrity/`, a non-numeric sibling of
the step dirs (like the trainer's `data_state/`), which Orbax's step scan
ignores. Multi-host: only process 0 writes (same shared filesystem contract
as Orbax itself); every host verifies and reaches the same verdict.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

MANIFEST_DIRNAME = "integrity"


def _manifest_dir(root: str) -> str:
    return os.path.join(root, MANIFEST_DIRNAME)


def manifest_path(root: str, step: int) -> str:
    return os.path.join(_manifest_dir(root), f"{int(step)}.json")


def step_dir(root: str, step: int) -> str:
    """The Orbax step directory (default name format: the bare number)."""
    return os.path.join(root, str(int(step)))


def _iter_files(base: str):
    for dirpath, _, filenames in os.walk(base):
        for name in sorted(filenames):
            full = os.path.join(dirpath, name)
            yield os.path.relpath(full, base), full


def step_size_bytes(root: str, step: int) -> int:
    """Total on-disk bytes of a step — a cheap stat walk, used to decide
    whether hashing it inline on the training thread is acceptable
    (checkpoint/manager.py INLINE_MANIFEST_MAX_BYTES)."""
    return sum(os.path.getsize(full)
               for _, full in _iter_files(step_dir(root, step)))


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_step_manifest(root: str, step: int) -> str:
    """Hash every file under the (already durable) step dir and write the
    manifest atomically (tmp + rename — a crash mid-write must not leave a
    half manifest that later fails verification of a GOOD step)."""
    base = step_dir(root, step)
    files = {rel: {"size": os.path.getsize(full), "sha256": _sha256(full)}
             for rel, full in _iter_files(base)}
    path = manifest_path(root, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"step": int(step), "files": files}, f, indent=1)
    os.replace(tmp, path)
    return path


def verify_step_manifest(root: str, step: int) -> tuple[Optional[bool], str]:
    """(verdict, detail): True = every manifest entry matches on size and
    hash; False = damage found (detail names the first mismatch); None = no
    manifest exists, nothing to verify against (legacy / pre-flush step)."""
    path = manifest_path(root, step)
    if not os.path.exists(path):
        return None, "no manifest"
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"unreadable manifest: {e}"
    base = step_dir(root, step)
    for rel, want in manifest.get("files", {}).items():
        full = os.path.join(base, rel)
        if not os.path.exists(full):
            return False, f"missing file {rel}"
        size = os.path.getsize(full)
        if size != want["size"]:
            return False, (f"size mismatch {rel}: manifest {want['size']} "
                           f"bytes, on disk {size}")
        if _sha256(full) != want["sha256"]:
            return False, f"checksum mismatch {rel}"
    return True, "ok"


def remove_step_manifest(root: str, step: int) -> None:
    try:
        os.remove(manifest_path(root, step))
    except FileNotFoundError:
        pass


def list_manifest_steps(root: str) -> list[int]:
    """Steps that currently have a manifest on disk — used by the manager to
    prune manifests orphaned by Orbax's retention GC (which deletes step
    dirs without notifying this layer)."""
    try:
        names = os.listdir(_manifest_dir(root))
    except FileNotFoundError:
        return []
    return sorted(int(n[:-5]) for n in names
                  if n.endswith(".json") and n[:-5].isdigit())
