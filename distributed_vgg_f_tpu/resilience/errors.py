"""Typed failure taxonomy for the resilience layer.

Every guard surfaces its failure as one of these instead of a hang, a bare
RuntimeError, or silent garbage training — callers (and the chaos suite,
tests/test_resilience.py) can catch exactly the failure mode they handle.
"""

from __future__ import annotations


class ResilienceError(RuntimeError):
    """Base class for every failure the resilience layer diagnoses."""


class DataStallError(ResilienceError):
    """The input pipeline stopped producing batches: the per-batch watchdog
    timed out through all its backoff retries, or the prefetch worker thread
    died without delivering a batch or an error (data/prefetch.py)."""


class NonFiniteStepError(ResilienceError):
    """Too many CONSECUTIVE training steps produced a non-finite loss or
    gradient norm. Individual bad steps are skipped (the optimizer update is
    dropped, parameters stay bit-identical); a run that only produces bad
    steps is diverged or fed garbage, and training on it is wasted fleet
    time — abort loudly (resilience/guard.py)."""


class CheckpointIntegrityError(ResilienceError):
    """A checkpoint failed its manifest verification and no intact fallback
    exists (or an explicitly requested step is corrupt). Restoring it would
    crash deep inside deserialization — or worse, silently load partial
    state (checkpoint/manager.py)."""


class GeometryReceiptError(ResilienceError, ValueError):
    """The checkpoint's opt-layout receipt names a geometry that does not
    reproduce against the live params tree: WRONG LAYOUT (saved for a
    different model, shard count, or bucket size), not corrupt bytes —
    integrity manifests already verified the bytes. Elastic restore
    (parallel/elastic.py, checkpoint/retopology.py) branches on this vs
    `CheckpointIntegrityError` in the flight recorder: wrong-layout means
    re-derive the conversion geometry; corrupt means fall back a step.
    Subclasses ValueError so pre-r19 callers that caught the untyped
    receipt failure keep working."""


class ElasticDegraded(ResilienceError):
    """A live elastic resize (parallel/elastic.py) could not proceed —
    too few survivors, an indivisible global batch under keep_global, or a
    missing resumable-ingest cursor. NOT a crash class: the trainer
    degrades to the r18 restart-from-checkpoint path, recording the reason
    as the `elastic_degraded_restart` flight class so the black box says
    WHY the fleet restarted instead of resizing."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(detail or reason)
        #: machine-readable cause, e.g. "too_few_survivors"
        self.reason = reason
