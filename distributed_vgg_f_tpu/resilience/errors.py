"""Typed failure taxonomy for the resilience layer.

Every guard surfaces its failure as one of these instead of a hang, a bare
RuntimeError, or silent garbage training — callers (and the chaos suite,
tests/test_resilience.py) can catch exactly the failure mode they handle.
"""

from __future__ import annotations


class ResilienceError(RuntimeError):
    """Base class for every failure the resilience layer diagnoses."""


class DataStallError(ResilienceError):
    """The input pipeline stopped producing batches: the per-batch watchdog
    timed out through all its backoff retries, or the prefetch worker thread
    died without delivering a batch or an error (data/prefetch.py)."""


class NonFiniteStepError(ResilienceError):
    """Too many CONSECUTIVE training steps produced a non-finite loss or
    gradient norm. Individual bad steps are skipped (the optimizer update is
    dropped, parameters stay bit-identical); a run that only produces bad
    steps is diverged or fed garbage, and training on it is wasted fleet
    time — abort loudly (resilience/guard.py)."""


class CheckpointIntegrityError(ResilienceError):
    """A checkpoint failed its manifest verification and no intact fallback
    exists (or an explicitly requested step is corrupt). Restoring it would
    crash deep inside deserialization — or worse, silently load partial
    state (checkpoint/manager.py)."""
