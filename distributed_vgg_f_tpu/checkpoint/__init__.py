from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager  # noqa: F401
