"""Checkpoint / resume (SURVEY.md §3.5, §5).

Reference: `tf.train.Saver`-style periodic save, restore-on-restart. Here:
Orbax — async, multi-host aware, sharded-array native. Saved unit is the full
`TrainState` (step, params, batch_stats, opt_state) plus the host data-iterator
position, so a restart resumes mid-epoch and the step-LR schedule position is
reproduced exactly (the schedule reads the restored step counter inside the
jitted step).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Mapping, Optional

import jax
import orbax.checkpoint as ocp

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.resilience.errors import CheckpointIntegrityError
from distributed_vgg_f_tpu.resilience.integrity import (
    list_manifest_steps,
    remove_step_manifest,
    step_size_bytes,
    verify_step_manifest,
    write_step_manifest,
)

#: The save()-path (non-blocking) manifest flush hashes a committed step
#: inline only when it is at most this large — full-file SHA-256 of a
#: multi-GB state on the TRAINING thread would stall the step loop for
#: seconds at every checkpoint cadence (code-review). Larger steps stay
#: pending and are manifested at the next wait()/restore-time blocking
#: flush instead; until then they verify as unknown-but-restorable, which
#: Orbax's commit atomicity already vouches for.
INLINE_MANIFEST_MAX_BYTES = 256 * 1024 * 1024

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: a runtime import would cycle through
    # train/__init__ -> trainer -> this module when the package is entered
    # via `distributed_vgg_f_tpu.checkpoint` first
    from distributed_vgg_f_tpu.train.state import TrainState


class CheckpointManager:
    """Thin wrapper over `orbax.checkpoint.CheckpointManager`.

    `save(state, extra=...)` is async (returns immediately, serializes in a
    background thread); `restore(template)` blocks. `extra` carries small
    JSON-able host state (e.g. data-iterator position).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1,
                 best_metric: str | None = None,
                 save_retries: int = 2):
        """`best_metric`: retain steps by this metric (max) instead of
        recency — Orbax's native best-checkpoint GC, which keeps the
        best-SCORED step even if a stale step with a higher step number
        survives a crash (pass the metric via `save(..., metrics=...)`;
        `best_step()` then selects by score, self-healing).

        `save_retries`: transient-I/O retry budget for the save dispatch
        (exponential backoff) — a momentary filesystem blip must not kill a
        long run when the NEXT attempt would succeed.

        Integrity (resilience layer): every durable step gets a checksum
        manifest (`<dir>/integrity/<step>.json`, resilience/integrity.py);
        `best_step()`/default restores verify it and transparently fall back
        to the newest INTACT step when the preferred one is truncated or
        corrupt — the skipped steps are recorded on
        `last_integrity_fallback` for the caller to log."""
        self._save_interval = max(1, save_interval_steps)
        # steps this manager instance has durably saved: a collision with one
        # of these is a re-save of IDENTICAL state (a training session holds
        # one state per step) and must not delete-and-rewrite it
        self._saved_steps: set[int] = set()
        self._dir = os.path.abspath(directory)
        self._best_metric = best_metric
        self._save_retries = max(0, save_retries)
        # steps saved but not yet manifested (saves are async — the manifest
        # can only hash a DURABLE step, so it is flushed behind a wait)
        self._manifest_pending: set[int] = set()
        # verification verdicts are cached per content write — this manager
        # is the only writer, so a verified step stays verified
        self._verified: dict[int, bool] = {}
        #: {"chosen": step, "skipped": [(step, detail), ...]} after a
        #: best_step() resolution had to skip damaged steps; None otherwise
        self.last_integrity_fallback: Optional[dict] = None
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            item_names=("state", "extra"),
            # explicit handlers (not just names): item_metadata() must work on
            # a fresh manager that has never saved — the cross-topology restore
            # path reads the SAVED opt-state shapes before building a template
            item_handlers={"state": ocp.StandardCheckpointHandler(),
                           "extra": ocp.JsonCheckpointHandler()},
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
                best_fn=(None if best_metric is None
                         else lambda m: float(m[best_metric])),
                best_mode="max",
            ),
        )

    # ------------------------------------------------------------------ save
    def save(self, state: TrainState, extra: Optional[Mapping[str, Any]] = None,
             *, force: bool = False,
             metrics: Optional[Mapping[str, Any]] = None,
             replace_on_collision: bool = False) -> bool:
        """`replace_on_collision`: Orbax never overwrites a step; a run
        branched from an earlier checkpoint (train.restore_from_best)
        re-reaches step numbers that already exist on disk holding STALE
        pre-branch state. With this flag such a collision replaces the stale
        step, synchronously (durable before returning). Two strategies:

        - plain (recency-retained) manager: delete the stale step, re-save.
          A crash inside that window loses only the stale step, never the
          rest of the chain.
        - best-metric manager: save the replacement at an UNUSED index —
          Orbax's retention GC removes the worse-scored old entry only after
          the new save is durable (checkpoint_manager._finalize), so at
          every instant at least one best checkpoint exists. `best_step()`
          selects by recorded score, not index.

        A collision with a step THIS manager instance already saved is a
        re-save of identical state (one state per step per session) — e.g.
        the end-of-run forced save landing on the step the cadence save just
        persisted — and returns True without touching the durable copy.

        Transient I/O errors (OSError family) during the save dispatch are
        retried `save_retries` times with exponential backoff before
        propagating — a blip must not kill the run when the retry would
        land."""
        step = int(jax.device_get(state.step))
        # manifest previously-committed steps when the async writer is idle
        # (non-blocking: a cadence save must never stall the train loop
        # behind the in-flight save — Orbax will serialize on it anyway if
        # this call actually dispatches)
        self._flush_manifests(block=False)
        args = {"state": ocp.args.StandardSave(state),
                "extra": ocp.args.JsonSave(dict(extra or {}))}

        def _save_at(idx: int, force_flag: bool) -> bool:
            # "checkpoint" span category: the dispatch is normally async and
            # cheap, but collision replacement / forced saves block — which
            # is exactly what the stall attributor's checkpoint_bound
            # verdict needs to see (telemetry/stall.py).
            with telemetry.span("checkpoint_save_dispatch", "checkpoint"):
                saved = self._retry_io(lambda: self._mngr.save(
                    idx, args=ocp.args.Composite(**args), force=force_flag,
                    metrics=dict(metrics) if metrics else None))
            if saved:
                telemetry.inc("checkpoint/saves")
                self._manifest_pending.add(idx)
            return saved

        def _save_replacing() -> bool:
            if step in self._saved_steps:
                return True  # already durable, identical by construction
            if self._best_metric is not None:
                staged = 1 + max(self._mngr.all_steps(), default=step)
                saved = _save_at(staged, True)
            else:
                if step in self._mngr.all_steps():
                    self.delete(step)
                saved = _save_at(step, True)
            if saved:
                self._mngr.wait_until_finished()
                self._saved_steps.add(step)
            return saved

        try:
            saved = _save_at(step, force)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            return _save_replacing() if replace_on_collision else False
        if saved:
            self._saved_steps.add(step)
            return True
        if force or not replace_on_collision:
            return saved
        # Non-forced save returned False. Orbax's should_save rejects
        # step <= latest_step BEFORE its existence check, so inside a
        # branched run's stale-overlap region a cadence save is silently
        # suppressed rather than raising StepAlreadyExistsError. Detect the
        # overlap and replace; a genuine interval skip stays skipped.
        latest = self._mngr.latest_step()
        if latest is not None and latest >= step \
                and step % self._save_interval == 0:
            return _save_replacing()
        return False

    def _retry_io(self, fn):
        """Run `fn`, retrying the OSError family with exponential backoff
        (`save_retries` attempts). Orbax control-flow exceptions
        (StepAlreadyExistsError) are not I/O faults and pass straight
        through to the collision handling above."""
        delay = 0.1
        for attempt in range(self._save_retries + 1):
            try:
                return fn()
            except OSError:
                if attempt == self._save_retries:
                    telemetry.inc("checkpoint/save_failures")
                    raise
                telemetry.inc("checkpoint/save_retries")
                time.sleep(delay)
                delay *= 2

    # ------------------------------------------------------------- integrity
    def _flush_manifests(self, block: bool = True) -> None:
        """Write checksum manifests for steps whose async save finished. A
        manifest can only hash DURABLE files, so a flush needs the async
        writer idle: `block=True` (restore/wait paths — correctness over
        latency) waits for it; `block=False` (the per-step save path) skips
        the flush while a save is still in flight rather than stall the
        train loop behind it. Process 0 writes; other hosts only drop their
        pending marks (shared filesystem, the contract Orbax itself
        relies on)."""
        in_progress = getattr(self._mngr, "is_saving_in_progress", None)
        busy = in_progress is not None and in_progress()
        if self._manifest_pending and not (busy and not block):
            self._mngr.wait_until_finished()
            on_disk = set(self._mngr.all_steps())
            deferred: set[int] = set()
            for idx in sorted(self._manifest_pending):
                if idx in on_disk and jax.process_index() == 0:
                    if not block and \
                            step_size_bytes(self._dir, idx) > \
                            INLINE_MANIFEST_MAX_BYTES:
                        # too big to hash on the training thread — defer to
                        # the next blocking flush (wait()/restore)
                        deferred.add(idx)
                        continue
                    write_step_manifest(self._dir, idx)
                self._verified.pop(idx, None)
            self._manifest_pending = deferred
        # Prune manifests orphaned by Orbax's retention GC, which deletes
        # step dirs without passing through delete(): a stale manifest left
        # for a GC'd step NUMBER would falsely flag a later re-save of that
        # number (branched runs re-reach old step numbers) as corrupt and
        # brick its restore (code-review). Cheap: one listdir + all_steps.
        if jax.process_index() == 0:
            alive = set(self._mngr.all_steps()) | self._manifest_pending
            for step in list_manifest_steps(self._dir):
                if step not in alive:
                    remove_step_manifest(self._dir, step)
                    self._verified.pop(step, None)

    def verify_step(self, step: int) -> bool:
        """True when the step's files match its checksum manifest (or no
        manifest exists to check against — legacy steps and the crash window
        before a manifest flush stay restorable on the strength of Orbax's
        commit atomicity). Verdicts are cached; this manager is the only
        writer."""
        if step not in self._verified:
            verdict, detail = verify_step_manifest(self._dir, step)
            self._verified[step] = verdict is not False
            if verdict is False:
                self._last_verify_detail = (step, detail)
        return self._verified[step]

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def best_step(self) -> Optional[int]:
        """The step a default restore should use: the best-scored step (when
        `best_metric` is configured), else the latest — SKIPPING any step
        that fails integrity verification, falling back through the
        remaining steps newest-first. None when no intact step remains
        (callers treat that as restore-impossible and must not silently
        reinitialize — see restore()). Skipped steps are recorded on
        `last_integrity_fallback`."""
        self._flush_manifests()
        order: list[int] = []
        if self._best_metric is not None:
            preferred = self._mngr.best_step()
            if preferred is not None:
                order.append(preferred)
        order.extend(s for s in sorted(self._mngr.all_steps(), reverse=True)
                     if s not in order)
        skipped = []
        self.last_integrity_fallback = None
        for step in order:
            if self.verify_step(step):
                if skipped:
                    self.last_integrity_fallback = {
                        "chosen": step, "skipped": skipped}
                    telemetry.inc("checkpoint/integrity_fallbacks")
                return step
            skipped.append((step, getattr(self, "_last_verify_detail",
                                          (step, "corrupt"))[1]))
        if skipped:
            self.last_integrity_fallback = {"chosen": None,
                                            "skipped": skipped}
        return None

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> tuple:
        """Restore (state, extra) at `step` (default: the newest INTACT
        best/latest step — a truncated or corrupt latest falls back
        transparently, see best_step()). `template` is a concrete TrainState
        whose structure/shardings the restored arrays adopt — pass the
        freshly-initialized state so multi-host restores land replicated on
        the mesh. An EXPLICITLY requested step that fails verification
        raises CheckpointIntegrityError (the caller asked for that exact
        state; substituting another would be silent time travel), as does a
        default restore with checkpoints on disk but none intact."""
        if step is not None and not self.verify_step(step):
            raise CheckpointIntegrityError(
                f"checkpoint step {step} under {self._dir} failed integrity "
                f"verification ({getattr(self, '_last_verify_detail', '?')})"
                f" — the files are truncated or corrupt")
        step = step if step is not None else self.best_step()
        if step is None:
            if self._mngr.all_steps():
                raise CheckpointIntegrityError(
                    f"every checkpoint under {self._dir} failed integrity "
                    f"verification "
                    f"({(self.last_integrity_fallback or {}).get('skipped')})"
                    f" — refusing to restore corrupt state; restore from a "
                    f"replica/backup or clear the directory to restart from "
                    f"scratch")
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        # one measurement feeds both the span and the counter, so the two
        # views of the interval can never disagree (native_loader idiom)
        t0 = time.monotonic_ns()
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                extra=ocp.args.JsonRestore(),
            ),
        )
        dt = time.monotonic_ns() - t0
        telemetry.record("checkpoint_restore", "checkpoint", t0, dt)
        telemetry.inc("checkpoint/restores")
        telemetry.inc("checkpoint/restore_ns", dt)
        extra = restored.get("extra") or {}
        return restored["state"], extra

    def delete(self, step: int) -> None:
        """Remove a saved step (e.g. to replace a best-slot entry whose step
        number collides after a resume — Orbax never overwrites a step)."""
        self._mngr.wait_until_finished()
        self._mngr.delete(step)
        if jax.process_index() == 0:
            remove_step_manifest(self._dir, step)
        self._manifest_pending.discard(step)
        self._verified.pop(step, None)

    def state_metadata(self, step: Optional[int] = None):
        """Structure-only view of the saved state item at `step` (default:
        best/latest): a nested dict/list tree whose leaves carry `.shape` and
        `.dtype` but no array data. Used to detect the saved opt-state layout
        for cross-topology restore (checkpoint/retopology.py)."""
        step = step if step is not None else self.best_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        meta = self._mngr.item_metadata(step)["state"]
        # Orbax ≥ 0.11 wraps the structure in a metadata object carrying
        # `.tree`; older releases return the nested dict directly
        return meta.tree if hasattr(meta, "tree") else meta

    def latest_extra(self) -> Optional[Mapping[str, Any]]:
        """The `extra` JSON of the latest (best-metric-selected, when
        configured) checkpoint without restoring the (large) state — e.g.
        the best-eval score a resumed run must not regress. None when no
        checkpoint exists."""
        step = self.best_step()
        if step is None:
            return None
        return self.extra_at(step)

    def extra_at(self, step: int) -> Mapping[str, Any]:
        """The `extra` JSON of one specific step (no state restore) —
        checkpoint/retopology.py reads the ZeRO-2 bucket-geometry receipt
        here BEFORE deciding how to interpret the saved flat opt state."""
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
        return restored.get("extra") or {}

    def iterator_state_at(self, step: int) -> Optional[Mapping[str, Any]]:
        """The r18 iterator-state blob of one step's `extra` (no state
        restore), or None — receipt-absent means a pre-r18 checkpoint and
        the restore dispatch takes the epoch-boundary replay path. The
        trainer reads the blob off the restore it already performs; this
        accessor serves tools/tests/bench that inspect checkpoints
        without restoring arrays (benchmarks/resume_bench.py)."""
        blob = self.extra_at(step).get("iterator_state")
        return blob if isinstance(blob, Mapping) else None

    def wait(self) -> None:
        """Block until pending async saves are durable (and manifested)."""
        t0 = time.monotonic_ns()
        self._mngr.wait_until_finished()
        self._flush_manifests()
        dt = time.monotonic_ns() - t0
        telemetry.record("checkpoint_wait", "checkpoint", t0, dt)
        telemetry.inc("checkpoint/wait_ns", dt)

    def close(self) -> None:
        self.wait()
        self._mngr.close()

    def all_steps(self):
        return sorted(self._mngr.all_steps())
