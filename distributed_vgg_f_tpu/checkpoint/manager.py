"""Checkpoint / resume (SURVEY.md §3.5, §5).

Reference: `tf.train.Saver`-style periodic save, restore-on-restart. Here:
Orbax — async, multi-host aware, sharded-array native. Saved unit is the full
`TrainState` (step, params, batch_stats, opt_state) plus the host data-iterator
position, so a restart resumes mid-epoch and the step-LR schedule position is
reproduced exactly (the schedule reads the restored step counter inside the
jitted step).
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping, Optional

import jax
import orbax.checkpoint as ocp

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: a runtime import would cycle through
    # train/__init__ -> trainer -> this module when the package is entered
    # via `distributed_vgg_f_tpu.checkpoint` first
    from distributed_vgg_f_tpu.train.state import TrainState


class CheckpointManager:
    """Thin wrapper over `orbax.checkpoint.CheckpointManager`.

    `save(state, extra=...)` is async (returns immediately, serializes in a
    background thread); `restore(template)` blocks. `extra` carries small
    JSON-able host state (e.g. data-iterator position).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1,
                 best_metric: str | None = None):
        """`best_metric`: retain steps by this metric (max) instead of
        recency — Orbax's native best-checkpoint GC, which keeps the
        best-SCORED step even if a stale step with a higher step number
        survives a crash (pass the metric via `save(..., metrics=...)`;
        `best_step()` then selects by score, self-healing)."""
        self._dir = os.path.abspath(directory)
        self._best_metric = best_metric
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            item_names=("state", "extra"),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
                best_fn=(None if best_metric is None
                         else lambda m: float(m[best_metric])),
                best_mode="max",
            ),
        )

    # ------------------------------------------------------------------ save
    def save(self, state: TrainState, extra: Optional[Mapping[str, Any]] = None,
             *, force: bool = False,
             metrics: Optional[Mapping[str, Any]] = None) -> bool:
        step = int(jax.device_get(state.step))
        args = {"state": ocp.args.StandardSave(state),
                "extra": ocp.args.JsonSave(dict(extra or {}))}
        try:
            return self._mngr.save(step, args=ocp.args.Composite(**args),
                                   force=force,
                                   metrics=dict(metrics) if metrics else None)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            return False

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def best_step(self) -> Optional[int]:
        """The step retained as best (by `best_metric`); falls back to the
        latest step when no metric is configured or none was recorded."""
        if self._best_metric is not None:
            step = self._mngr.best_step()
            if step is not None:
                return step
        return self._mngr.latest_step()

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> tuple:
        """Restore (state, extra) at `step` (default latest). `template` is a
        concrete TrainState whose structure/shardings the restored arrays
        adopt — pass the freshly-initialized state so multi-host restores
        land replicated on the mesh."""
        step = step if step is not None else self.best_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                extra=ocp.args.JsonRestore(),
            ),
        )
        extra = restored.get("extra") or {}
        return restored["state"], extra

    def delete(self, step: int) -> None:
        """Remove a saved step (e.g. to replace a best-slot entry whose step
        number collides after a resume — Orbax never overwrites a step)."""
        self._mngr.wait_until_finished()
        self._mngr.delete(step)

    def latest_extra(self) -> Optional[Mapping[str, Any]]:
        """The `extra` JSON of the latest (best-metric-selected, when
        configured) checkpoint without restoring the (large) state — e.g.
        the best-eval score a resumed run must not regress. None when no
        checkpoint exists."""
        step = self.best_step()
        if step is None:
            return None
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
        return restored.get("extra") or {}

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def all_steps(self):
        return sorted(self._mngr.all_steps())
