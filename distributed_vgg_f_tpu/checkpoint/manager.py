"""Checkpoint / resume (SURVEY.md §3.5, §5).

Reference: `tf.train.Saver`-style periodic save, restore-on-restart. Here:
Orbax — async, multi-host aware, sharded-array native. Saved unit is the full
`TrainState` (step, params, batch_stats, opt_state) plus the host data-iterator
position, so a restart resumes mid-epoch and the step-LR schedule position is
reproduced exactly (the schedule reads the restored step counter inside the
jitted step).
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping, Optional

import jax
import orbax.checkpoint as ocp

from typing import TYPE_CHECKING

if TYPE_CHECKING:  # annotation-only: a runtime import would cycle through
    # train/__init__ -> trainer -> this module when the package is entered
    # via `distributed_vgg_f_tpu.checkpoint` first
    from distributed_vgg_f_tpu.train.state import TrainState


class CheckpointManager:
    """Thin wrapper over `orbax.checkpoint.CheckpointManager`.

    `save(state, extra=...)` is async (returns immediately, serializes in a
    background thread); `restore(template)` blocks. `extra` carries small
    JSON-able host state (e.g. data-iterator position).
    """

    def __init__(self, directory: str, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1,
                 best_metric: str | None = None):
        """`best_metric`: retain steps by this metric (max) instead of
        recency — Orbax's native best-checkpoint GC, which keeps the
        best-SCORED step even if a stale step with a higher step number
        survives a crash (pass the metric via `save(..., metrics=...)`;
        `best_step()` then selects by score, self-healing)."""
        self._save_interval = max(1, save_interval_steps)
        # steps this manager instance has durably saved: a collision with one
        # of these is a re-save of IDENTICAL state (a training session holds
        # one state per step) and must not delete-and-rewrite it
        self._saved_steps: set[int] = set()
        self._dir = os.path.abspath(directory)
        self._best_metric = best_metric
        os.makedirs(self._dir, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            item_names=("state", "extra"),
            # explicit handlers (not just names): item_metadata() must work on
            # a fresh manager that has never saved — the cross-topology restore
            # path reads the SAVED opt-state shapes before building a template
            item_handlers={"state": ocp.StandardCheckpointHandler(),
                           "extra": ocp.JsonCheckpointHandler()},
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                enable_async_checkpointing=True,
                best_fn=(None if best_metric is None
                         else lambda m: float(m[best_metric])),
                best_mode="max",
            ),
        )

    # ------------------------------------------------------------------ save
    def save(self, state: TrainState, extra: Optional[Mapping[str, Any]] = None,
             *, force: bool = False,
             metrics: Optional[Mapping[str, Any]] = None,
             replace_on_collision: bool = False) -> bool:
        """`replace_on_collision`: Orbax never overwrites a step; a run
        branched from an earlier checkpoint (train.restore_from_best)
        re-reaches step numbers that already exist on disk holding STALE
        pre-branch state. With this flag such a collision replaces the stale
        step, synchronously (durable before returning). Two strategies:

        - plain (recency-retained) manager: delete the stale step, re-save.
          A crash inside that window loses only the stale step, never the
          rest of the chain.
        - best-metric manager: save the replacement at an UNUSED index —
          Orbax's retention GC removes the worse-scored old entry only after
          the new save is durable (checkpoint_manager._finalize), so at
          every instant at least one best checkpoint exists. `best_step()`
          selects by recorded score, not index.

        A collision with a step THIS manager instance already saved is a
        re-save of identical state (one state per step per session) — e.g.
        the end-of-run forced save landing on the step the cadence save just
        persisted — and returns True without touching the durable copy."""
        step = int(jax.device_get(state.step))
        args = {"state": ocp.args.StandardSave(state),
                "extra": ocp.args.JsonSave(dict(extra or {}))}

        def _save_at(idx: int, force_flag: bool) -> bool:
            return self._mngr.save(idx, args=ocp.args.Composite(**args),
                                   force=force_flag,
                                   metrics=dict(metrics) if metrics else None)

        def _save_replacing() -> bool:
            if step in self._saved_steps:
                return True  # already durable, identical by construction
            if self._best_metric is not None:
                staged = 1 + max(self._mngr.all_steps(), default=step)
                saved = _save_at(staged, True)
            else:
                if step in self._mngr.all_steps():
                    self.delete(step)
                saved = _save_at(step, True)
            if saved:
                self._mngr.wait_until_finished()
                self._saved_steps.add(step)
            return saved

        try:
            saved = _save_at(step, force)
        except ocp.checkpoint_manager.StepAlreadyExistsError:
            return _save_replacing() if replace_on_collision else False
        if saved:
            self._saved_steps.add(step)
            return True
        if force or not replace_on_collision:
            return saved
        # Non-forced save returned False. Orbax's should_save rejects
        # step <= latest_step BEFORE its existence check, so inside a
        # branched run's stale-overlap region a cadence save is silently
        # suppressed rather than raising StepAlreadyExistsError. Detect the
        # overlap and replace; a genuine interval skip stays skipped.
        latest = self._mngr.latest_step()
        if latest is not None and latest >= step \
                and step % self._save_interval == 0:
            return _save_replacing()
        return False

    # --------------------------------------------------------------- restore
    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def best_step(self) -> Optional[int]:
        """The step retained as best (by `best_metric`); falls back to the
        latest step when no metric is configured or none was recorded."""
        if self._best_metric is not None:
            step = self._mngr.best_step()
            if step is not None:
                return step
        return self._mngr.latest_step()

    def restore(self, template: TrainState,
                step: Optional[int] = None) -> tuple:
        """Restore (state, extra) at `step` (default latest). `template` is a
        concrete TrainState whose structure/shardings the restored arrays
        adopt — pass the freshly-initialized state so multi-host restores
        land replicated on the mesh."""
        step = step if step is not None else self.best_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(template),
                extra=ocp.args.JsonRestore(),
            ),
        )
        extra = restored.get("extra") or {}
        return restored["state"], extra

    def delete(self, step: int) -> None:
        """Remove a saved step (e.g. to replace a best-slot entry whose step
        number collides after a resume — Orbax never overwrites a step)."""
        self._mngr.wait_until_finished()
        self._mngr.delete(step)

    def state_metadata(self, step: Optional[int] = None):
        """Structure-only view of the saved state item at `step` (default:
        best/latest): a nested dict/list tree whose leaves carry `.shape` and
        `.dtype` but no array data. Used to detect the saved opt-state layout
        for cross-topology restore (checkpoint/retopology.py)."""
        step = step if step is not None else self.best_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        return self._mngr.item_metadata(step)["state"].tree

    def latest_extra(self) -> Optional[Mapping[str, Any]]:
        """The `extra` JSON of the latest (best-metric-selected, when
        configured) checkpoint without restoring the (large) state — e.g.
        the best-eval score a resumed run must not regress. None when no
        checkpoint exists."""
        step = self.best_step()
        if step is None:
            return None
        restored = self._mngr.restore(
            step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
        return restored.get("extra") or {}

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mngr.wait_until_finished()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()

    def all_steps(self):
        return sorted(self._mngr.all_steps())
