"""Cross-topology checkpoint restore (BASELINE north_star: train on v4-8,
grow to v4-128 — and migrate replicated DP ↔ ZeRO-1 — without retraining).

A checkpoint's optimizer-state layout is a function of HOW it was trained:
replicated DP saves a params-tree optax state; ZeRO-1 saves one flat vector
padded to a multiple of the shard count (parallel/zero.py), so its shapes
change with the mesh size. Restoring onto a different topology must therefore
ADAPT the state, not just reshard it.

Strategy:
1. Detect the saved layout from checkpoint metadata (shapes only, no array
   reads — checkpoint/manager.py `state_metadata`).
2. Fast path: saved shapes == template shapes → plain Orbax restore (Orbax
   reshards to the template's shardings natively; this covers N→M meshes
   whose padded sizes happen to coincide, and all replicated-DP resizes).
3. Otherwise restore at the SAVED shapes (opt state replicated), then convert
   with `parallel.zero.convert_opt_state` inside one jitted computation whose
   `out_shardings` are the target layout — XLA places the result directly
   into the target topology, on one host or many.

Params/step/batch_stats are topology-independent (always replicated over the
data axis) and restore bit-identically on any mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax

from distributed_vgg_f_tpu.parallel.zero import (
    convert_opt_state,
    flat_param_count,
    opt_state_layout,
)


def restore_any_topology(manager, template, tx, *,
                         opt_shardings: Any,
                         target_padded: Optional[int],
                         step: Optional[int] = None,
                         target_bucket_layout: Any = None) -> tuple:
    """Restore `manager`'s checkpoint into `template`'s topology and layout.

    - `template`: concrete TrainState initialized for the CURRENT run (its
      shardings define the target topology).
    - `opt_shardings`: sharding (tree or single) for the target opt state —
      the trainer's `_state_sharding().opt_state` under ZeRO-1, its
      replicated sharding otherwise.
    - `target_padded`: ZeRO-1 padded flat length for the current shard count
      (the bucket layout's `total_padded` under the bucketed exchange), or
      None for the replicated layout.
    - `target_bucket_layout` (r14): the current run's
      parallel/buckets.GradBucketLayout when the bucketed ZeRO exchange is
      on — the saved vector is then PERMUTED into the bucket-major frame,
      not just re-padded. The saved side's geometry comes from the
      `opt_layout` receipt the trainer writes into every checkpoint's
      `extra`; absent receipt = the canonical ZeRO-1 layout (true for
      every pre-r14 checkpoint).

    Returns `(state, extra)` like `manager.restore`.
    """
    step = step if step is not None else manager.best_step()
    saved_opt_meta = manager.state_metadata(step)["opt_state"]
    saved_shapes = [tuple(l.shape) for l in jax.tree.leaves(saved_opt_meta)]
    tmpl_shapes = [tuple(l.shape) for l in jax.tree.leaves(template.opt_state)]
    params_struct = jax.eval_shape(lambda p: p, template.params)
    total = flat_param_count(params_struct)
    layout, padded_src = opt_state_layout(saved_opt_meta, total)
    # The saved FLAT layout's geometry receipt: same-shape vectors can
    # still be differently PERMUTED (canonical vs bucket-major, or two
    # bucket sizes whose totals coincide) — shapes alone cannot
    # disambiguate, the receipt can.
    src_bucket_layout = None
    saved_layout_receipt = None
    if layout == "flat":
        saved_layout_receipt = (manager.extra_at(step) or {}).get(
            "opt_layout")
        if saved_layout_receipt is not None:
            from distributed_vgg_f_tpu.parallel.buckets import (
                layout_from_receipt)
            from distributed_vgg_f_tpu.resilience.errors import (
                GeometryReceiptError)
            try:
                src_bucket_layout = layout_from_receipt(
                    params_struct, saved_layout_receipt)
            except ValueError as e:
                # r19: a receipt that names a non-reproducing geometry is
                # WRONG LAYOUT, not corrupt bytes — the typed class lets
                # elastic restore tell the flight recorder which one it
                # was (corrupt bytes raise CheckpointIntegrityError long
                # before this point, in the manager's manifest check)
                raise GeometryReceiptError(
                    f"opt-layout receipt at step {step} does not describe "
                    f"this run's geometry: {e}") from e
    target_layout_receipt = (target_bucket_layout.describe()
                             if target_bucket_layout is not None else None)
    if saved_shapes == tmpl_shapes \
            and saved_layout_receipt == target_layout_receipt:
        return manager.restore(template, step)

    # -- layout mismatch: rebuild the SAVED opt-state structure abstractly
    if layout == "flat":
        src_struct = jax.eval_shape(
            tx.init, jax.ShapeDtypeStruct((padded_src,), jax.numpy.float32))
    else:
        src_struct = jax.eval_shape(tx.init, params_struct)
    src_shapes = [tuple(l.shape) for l in jax.tree.leaves(src_struct)]
    if src_shapes != saved_shapes:
        raise ValueError(
            f"checkpoint opt-state shapes {saved_shapes} match neither the "
            f"current topology {tmpl_shapes} nor a reconstruction of the "
            f"saved layout {src_shapes} — was it written by a different "
            f"optimizer chain?")

    # restore at the saved shapes, replicated over the current mesh
    replicated = template.step.sharding
    saved_template = template.replace(opt_state=jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=replicated),
        src_struct))
    restored, extra = manager.restore(saved_template, step)

    # convert the layout inside jit: out_shardings place the result straight
    # into the target topology
    convert = jax.jit(
        functools.partial(convert_opt_state, tx=tx,
                          params_struct=params_struct,
                          target_padded=target_padded,
                          src_bucket_layout=src_bucket_layout,
                          target_bucket_layout=target_bucket_layout),
        out_shardings=opt_shardings)
    new_opt = convert(restored.opt_state)
    return restored.replace(opt_state=new_opt), extra
