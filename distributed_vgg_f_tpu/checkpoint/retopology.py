"""Cross-topology checkpoint restore (BASELINE north_star: train on v4-8,
grow to v4-128 — and migrate replicated DP ↔ ZeRO-1 — without retraining).

A checkpoint's optimizer-state layout is a function of HOW it was trained:
replicated DP saves a params-tree optax state; ZeRO-1 saves one flat vector
padded to a multiple of the shard count (parallel/zero.py), so its shapes
change with the mesh size. Restoring onto a different topology must therefore
ADAPT the state, not just reshard it.

Strategy:
1. Detect the saved layout from checkpoint metadata (shapes only, no array
   reads — checkpoint/manager.py `state_metadata`).
2. Fast path: saved shapes == template shapes → plain Orbax restore (Orbax
   reshards to the template's shardings natively; this covers N→M meshes
   whose padded sizes happen to coincide, and all replicated-DP resizes).
3. Otherwise restore at the SAVED shapes (opt state replicated), then convert
   with `parallel.zero.convert_opt_state` inside one jitted computation whose
   `out_shardings` are the target layout — XLA places the result directly
   into the target topology, on one host or many.

Step/batch_stats are topology-independent (always replicated over the data
axis) and restore bit-identically on any mesh. Params (and EMA params) were
too — until ZeRO-3 (r21, mesh.shard_params), which persists them as the SAME
padded flat vector the opt state uses. They now flow through the identical
detect → receipt-check → restore-replicated → jitted-convert machinery
(`parallel.zero.convert_params`), keyed by the `param_layout` receipt in the
checkpoint's `extra` (kind: canonical_flat | bucketed_flat; absent receipt on
a flat vector = canonical — and on a tree = the pre-r21 layout). Any
direction works: zero2 ↔ zero3, N ↔ M shards, bucketed ↔ canonical — or
refuses with a typed GeometryReceiptError, never a shape error.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax

from distributed_vgg_f_tpu.parallel.zero import (
    convert_opt_state,
    convert_params,
    flat_param_count,
    opt_state_layout,
    params_layout,
)


def restore_any_topology(manager, template, tx, *,
                         opt_shardings: Any,
                         target_padded: Optional[int],
                         step: Optional[int] = None,
                         target_bucket_layout: Any = None,
                         params_tree_struct: Any = None,
                         params_shardings: Any = None) -> tuple:
    """Restore `manager`'s checkpoint into `template`'s topology and layout.

    - `template`: concrete TrainState initialized for the CURRENT run (its
      shardings define the target topology).
    - `opt_shardings`: sharding (tree or single) for the target opt state —
      the trainer's `_state_sharding().opt_state` under ZeRO-1, its
      replicated sharding otherwise.
    - `target_padded`: ZeRO-1 padded flat length for the current shard count
      (the bucket layout's `total_padded` under the bucketed exchange), or
      None for the replicated layout.
    - `target_bucket_layout` (r14): the current run's
      parallel/buckets.GradBucketLayout when the bucketed ZeRO exchange is
      on — the saved vector is then PERMUTED into the bucket-major frame,
      not just re-padded. The saved side's geometry comes from the
      `opt_layout` receipt the trainer writes into every checkpoint's
      `extra`; absent receipt = the canonical ZeRO-1 layout (true for
      every pre-r14 checkpoint).
    - `params_tree_struct` (r21, ZeRO-3): the params TREE geometry. Required
      when `template.params` is the ZeRO-3 flat shard vector (the tree is
      no longer recoverable from the template); under it, saved params/EMA
      in ANY layout — replicated tree, canonical flat, bucket-major flat,
      any shard count — are converted to the template's layout exactly like
      the opt state (same receipts, same typed refusals). None keeps the
      pre-r21 behavior: params restore as the tree they are.
    - `params_shardings` (r21): target sharding for params (and EMA) when
      they need layout conversion — the trainer's
      `_state_sharding().params` under ZeRO-3. None = replicated.

    Returns `(state, extra)` like `manager.restore`.
    """
    step = step if step is not None else manager.best_step()
    saved_meta = manager.state_metadata(step)
    saved_opt_meta = saved_meta["opt_state"]
    saved_shapes = [tuple(l.shape) for l in jax.tree.leaves(saved_opt_meta)]
    tmpl_shapes = [tuple(l.shape) for l in jax.tree.leaves(template.opt_state)]
    params_struct = (params_tree_struct if params_tree_struct is not None
                     else jax.eval_shape(lambda p: p, template.params))
    total = flat_param_count(params_struct)
    layout, padded_src = opt_state_layout(saved_opt_meta, total)
    # The saved FLAT layout's geometry receipt: same-shape vectors can
    # still be differently PERMUTED (canonical vs bucket-major, or two
    # bucket sizes whose totals coincide) — shapes alone cannot
    # disambiguate, the receipt can.
    src_bucket_layout = None
    saved_layout_receipt = None
    if layout == "flat":
        saved_layout_receipt = (manager.extra_at(step) or {}).get(
            "opt_layout")
        if saved_layout_receipt is not None:
            from distributed_vgg_f_tpu.parallel.buckets import (
                layout_from_receipt)
            from distributed_vgg_f_tpu.resilience.errors import (
                GeometryReceiptError)
            try:
                src_bucket_layout = layout_from_receipt(
                    params_struct, saved_layout_receipt)
            except ValueError as e:
                # r19: a receipt that names a non-reproducing geometry is
                # WRONG LAYOUT, not corrupt bytes — the typed class lets
                # elastic restore tell the flight recorder which one it
                # was (corrupt bytes raise CheckpointIntegrityError long
                # before this point, in the manager's manifest check)
                raise GeometryReceiptError(
                    f"opt-layout receipt at step {step} does not describe "
                    f"this run's geometry: {e}") from e
    target_layout_receipt = (target_bucket_layout.describe()
                             if target_bucket_layout is not None else None)

    # -- params side (r21): detect the SAVED params layout (replicated tree
    # vs ZeRO-3 flat) and the template's, plus the `param_layout` receipt
    # that disambiguates canonical vs bucket-major flat (same shapes,
    # different permutation — exactly the opt-state ambiguity).
    from distributed_vgg_f_tpu.resilience.errors import GeometryReceiptError
    saved_p_meta = saved_meta["params"]
    saved_p_shapes = [tuple(l.shape) for l in jax.tree.leaves(saved_p_meta)]
    tmpl_p_shapes = [tuple(l.shape)
                     for l in jax.tree.leaves(template.params)]
    s_p_layout, s_p_padded = params_layout(saved_p_meta, total)
    t_p_layout, t_p_padded = (params_layout(template.params, total)
                              if params_tree_struct is not None
                              else ("tree", None))
    saved_param_receipt = None
    src_param_bucket = None
    if s_p_layout == "flat":
        saved_param_receipt = (manager.extra_at(step) or {}).get(
            "param_layout")
        kind = (saved_param_receipt or {}).get("kind", "canonical_flat")
        if saved_param_receipt is not None \
                and saved_param_receipt.get("total_padded") != s_p_padded:
            raise GeometryReceiptError(
                f"param-layout receipt at step {step} claims total_padded="
                f"{saved_param_receipt.get('total_padded')} but the saved "
                f"flat params vector has length {s_p_padded}")
        if kind == "bucketed_flat":
            # a bucketed flat params vector always rides with the bucketed
            # opt vector — ONE layout, described once by the opt receipt
            if src_bucket_layout is None:
                raise GeometryReceiptError(
                    f"param-layout receipt at step {step} says "
                    f"'bucketed_flat' but no opt-layout receipt describes "
                    f"the bucket geometry — cannot invert the permutation")
            src_param_bucket = src_bucket_layout
    elif (manager.extra_at(step) or {}).get("param_layout") is not None:
        raise GeometryReceiptError(
            f"param-layout receipt present at step {step} but the saved "
            f"params are a tree, not a flat vector — receipt and payload "
            f"disagree")
    # comparison keys: (kind, padded) per side, where an ABSENT receipt on
    # a flat vector means the canonical layout (pre-receipt writers) — so
    # absence and an explicit canonical receipt of the same length compare
    # equal. Bucketed-flat interleaving additionally depends on the bucket
    # geometry, which the opt receipts carry.
    saved_p_key = target_p_key = None
    if s_p_layout == "flat":
        saved_p_key = ((saved_param_receipt or {}).get(
            "kind", "canonical_flat"), s_p_padded)
    if t_p_layout == "flat":
        target_p_key = (("bucketed_flat" if target_bucket_layout is not None
                         else "canonical_flat"), t_p_padded)
    params_match = (saved_p_shapes == tmpl_p_shapes
                    and saved_p_key == target_p_key
                    and (saved_layout_receipt == target_layout_receipt
                         or (saved_p_key or ("",))[0] != "bucketed_flat"))

    if saved_shapes == tmpl_shapes \
            and saved_layout_receipt == target_layout_receipt \
            and params_match:
        return manager.restore(template, step)

    # -- layout mismatch: rebuild the SAVED opt-state structure abstractly
    if layout == "flat":
        src_struct = jax.eval_shape(
            tx.init, jax.ShapeDtypeStruct((padded_src,), jax.numpy.float32))
    else:
        src_struct = jax.eval_shape(tx.init, params_struct)
    src_shapes = [tuple(l.shape) for l in jax.tree.leaves(src_struct)]
    if src_shapes != saved_shapes:
        raise ValueError(
            f"checkpoint opt-state shapes {saved_shapes} match neither the "
            f"current topology {tmpl_shapes} nor a reconstruction of the "
            f"saved layout {src_shapes} — was it written by a different "
            f"optimizer chain?")

    # restore at the saved shapes, replicated over the current mesh
    replicated = template.step.sharding
    saved_template = template.replace(opt_state=jax.tree.map(
        lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=replicated),
        src_struct))
    if not params_match:
        # rebuild the SAVED params structure abstractly, replicated — the
        # flat vector (any shard count) or the plain tree
        if s_p_layout == "flat":
            src_p_struct = jax.ShapeDtypeStruct(
                (s_p_padded,), jax.numpy.float32, sharding=replicated)
        else:
            src_p_struct = jax.tree.map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype,
                                               sharding=replicated),
                params_struct)
        src_p_shapes = [tuple(l.shape)
                        for l in jax.tree.leaves(src_p_struct)]
        if src_p_shapes != saved_p_shapes:
            raise GeometryReceiptError(
                f"checkpoint params shapes {saved_p_shapes} match neither "
                f"the current topology {tmpl_p_shapes} nor a reconstruction "
                f"of the saved layout {src_p_shapes} — was it written for a "
                f"different model?")
        saved_template = saved_template.replace(
            params=src_p_struct,
            ema_params=(src_p_struct if template.ema_params is not None
                        else template.ema_params))
    restored, extra = manager.restore(saved_template, step)

    # convert the layout inside jit: out_shardings place the result straight
    # into the target topology
    convert = jax.jit(
        functools.partial(convert_opt_state, tx=tx,
                          params_struct=params_struct,
                          target_padded=target_padded,
                          src_bucket_layout=src_bucket_layout,
                          target_bucket_layout=target_bucket_layout),
        out_shardings=opt_shardings)
    new_opt = convert(restored.opt_state)
    out = restored.replace(opt_state=new_opt)
    if not params_match:
        p_shardings = (params_shardings if params_shardings is not None
                       else replicated)
        conv_p = jax.jit(
            functools.partial(
                convert_params, params_struct=params_struct,
                target_padded=(t_p_padded if t_p_layout == "flat" else None),
                src_bucket_layout=src_param_bucket,
                target_bucket_layout=(target_bucket_layout
                                      if t_p_layout == "flat" else None)),
            out_shardings=p_shardings)
        new_params = conv_p(restored.params)
        new_ema = (conv_p(restored.ema_params)
                   if template.ema_params is not None else restored.ema_params)
        out = out.replace(params=new_params, ema_params=new_ema)
    return out, extra
