"""Admission controller — the r11 ingest autotuner reused over the batch
window (r17).

The serving trade the operator cannot pin by hand is batch window vs queue
depth: a wide window batches efficiently but taxes every light-traffic
request with its full wait; a narrow one keeps light traffic snappy but
flushes tiny buckets under load, and the queue — not the window — becomes
the latency. The same closed-loop answer as ingest (data/autotune.py):
derive a per-window VERDICT from live evidence and steer one knob through
the existing controller discipline — hysteresis (k consecutive verdicts),
cooldown, bounded geometric steps, hard rails, oscillation freeze, and the
full receipt trail (actuation history, flight-recorder ring, `autotune/*`
counters — the controller CLASS is shared, so its bookkeeping namespace
is too; the serving-specific effects land in `serving/*`).

Verdict derivation (the serving analogue of the stall attributor's
`infeed_bound`):

- ``queue_pressure``→ observe as `infeed_bound`: the window is too narrow
  for the arrival rate — sheds happened, or the queue peaked past
  `queue_pressure_fraction` of its bound. The controller widens the
  window (bigger buckets, more throughput per flush) toward its rail.
- anything else    → observe as `compute_bound` (the good verdict): with
  `relax_after_windows` > 0 a controller-raised window steps back down
  toward the configured baseline after a sustained quiet streak — the
  latency tax is only paid while the pressure lasts.

The knob is `DynamicBatcher.window_ms`/`set_window_ms` — the exact
get/apply surface `data/autotune.Knob` binds, rails from
`serving.window_min_ms`/`window_max_ms`, baseline the configured
`serving.max_latency_ms`.
"""

from __future__ import annotations

from typing import Optional

from distributed_vgg_f_tpu import telemetry

#: The verdict label the controller receipts carry for a pressured window
#: (mapped onto the autotuner's UP verdict when observed).
PRESSURE_VERDICT = "queue_pressure"
STEADY_VERDICT = "steady"


class AdmissionController:
    """One batcher's admission-window feedback loop."""

    def __init__(self, serving_cfg, batcher, *, registry=None, flight=None):
        from distributed_vgg_f_tpu.config import AutotuneConfig
        from distributed_vgg_f_tpu.data.autotune import IngestAutotuner, Knob
        self.cfg = serving_cfg
        self.batcher = batcher
        self._reg = registry if registry is not None \
            else telemetry.get_registry()
        self._reg.counter("serving/controller_actuations")
        knob = Knob("batch_window_ms",
                    get=lambda: int(batcher.window_ms),
                    apply=batcher.set_window_ms,
                    min_value=max(1, int(serving_cfg.window_min_ms)),
                    max_value=max(1, int(serving_cfg.window_max_ms)),
                    geometric=True)
        self._tuner = IngestAutotuner(
            AutotuneConfig(
                enabled=True,
                k_windows=serving_cfg.controller_k_windows,
                cooldown_windows=serving_cfg.controller_cooldown_windows,
                relax_after_windows=serving_cfg.controller_relax_after_windows,
            ),
            [knob], registry=self._reg, flight=flight)
        self._last_verdict: Optional[str] = None

    def classify(self, stats: dict) -> str:
        """stats (batcher.window_stats shape) → serving verdict."""
        pressure_depth = self.cfg.queue_pressure_fraction \
            * self.batcher.queue_limit
        if stats.get("shed", 0) > 0 \
                or stats.get("queue_peak", 0) >= pressure_depth:
            return PRESSURE_VERDICT
        return STEADY_VERDICT

    def observe_window(self, stats: dict) -> dict:
        """One controller window: classify, feed the autotuner (pressure
        rides its UP verdict, steady its relax verdict), and return the
        window record for /servingz + the flight ring."""
        verdict = self.classify(stats)
        self._last_verdict = verdict
        mapped = "infeed_bound" if verdict == PRESSURE_VERDICT \
            else "compute_bound"
        record = self._tuner.observe({
            "verdict": mapped,
            "queue_peak": stats.get("queue_peak", 0),
            "shed": stats.get("shed", 0)})
        if record.get("actuations"):
            self._reg.inc("serving/controller_actuations",
                          len(record["actuations"]))
        self._reg.set_gauge("serving/window_ms", self.batcher.window_ms)
        record["serving_verdict"] = verdict
        return record

    @property
    def window_ms(self) -> int:
        return self.batcher.window_ms

    def describe(self) -> dict:
        """Controller receipt for /servingz — the autotuner's full state
        (knob vs rails, settled flag, actuation history) plus the serving
        verdict vocabulary it steers from."""
        out = self._tuner.describe()
        out["verdicts"] = [PRESSURE_VERDICT, STEADY_VERDICT]
        out["last_verdict"] = self._last_verdict
        return out
