"""Production inference service (r17; ROADMAP item 1, the serving half of
the TF-system training/serving split, arXiv 1605.08695).

`train/predict.py` is the batch-offline surface — point it at files, get
JSON lines. This package is the always-on one: a persistent server wrapping
the SAME jitted predict step behind a dynamic batcher, fed u8 image payloads
over plain HTTP (1 B/px off the network — the u8 ingest wire's contract,
finished on device by the dtype-dispatching prologue the train/eval/predict
steps already install).

Four modules, one per concern:

- ``engine.py``    — `PredictEngine`: the per-model compute plane. One
  AOT-lowered executable per batch BUCKET (pad to the nearest bucket, slice
  results back), built from the exact forward `run_predict` uses
  (`train/predict.build_forward` — parity between server and offline
  predict is structural, not re-derived). Routing metadata comes from the
  per-model `IngestDescriptor` table (models/ingest.py), so one server
  fronts the whole zoo.
- ``batcher.py``   — `DynamicBatcher`: bounded admission queue with
  max-latency + max-batch flush and explicit overload behavior — a full
  queue sheds the request with a typed error instead of collapsing into
  unbounded latency.
- ``controller.py``— `AdmissionController`: the r11 autotuner
  (data/autotune.IngestAutotuner — hysteresis, rails, cooldown,
  oscillation guard, receipt history) reused over ONE knob, the admission
  window, steered by per-window queue-depth/latency verdicts.
- ``server.py``    — `PredictServer`: the stdlib HTTP front end, the model
  router, the telemetry wiring (`serving/*` counters, latency-quantile
  gauges, the `/servingz` exporter provider, flight-recorder windows, the
  serving heartbeat that keeps `/healthz` honest for a load balancer).

Kill-switch discipline (r6–r16): `serving.enabled` is false by default and
nothing in the training/predict path imports this package when it is off —
`run_predict` on image files is byte-identical to r16, pinned structurally
in tests/test_serving.py (the package must not even appear in sys.modules
after an offline predict run).
"""

from __future__ import annotations

__all__ = ["PredictEngine", "DynamicBatcher", "OverloadShed",
           "AdmissionController", "PredictServer", "serve_from_trainer"]


def __getattr__(name):
    # lazy re-exports: importing the package name alone (e.g. for the
    # kill-switch sys.modules pin) must not pull jax/numpy
    if name in ("PredictEngine",):
        from distributed_vgg_f_tpu.serving.engine import PredictEngine
        return PredictEngine
    if name in ("DynamicBatcher", "OverloadShed"):
        from distributed_vgg_f_tpu.serving import batcher
        return getattr(batcher, name)
    if name in ("AdmissionController",):
        from distributed_vgg_f_tpu.serving.controller import (
            AdmissionController)
        return AdmissionController
    if name in ("PredictServer", "serve_from_trainer"):
        from distributed_vgg_f_tpu.serving import server
        return getattr(server, name)
    raise AttributeError(name)
