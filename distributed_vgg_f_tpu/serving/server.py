"""Always-on predict server — the u8-wire HTTP front end over the dynamic
batcher (r17; ROADMAP item 1, the serving half of arXiv 1605.08695's
training/serving split).

Request contract (deliberately the thinnest thing that carries the u8
wire over HTTP — the wire IS the payload format, HTTP adds routing only):

    POST /v1/predict/<model>        body: raw uint8 pixels, C-order,
                                    exactly image_size*image_size*3 bytes
                                    (1 B/px off the network; the device-
                                    finish prologue normalizes on device)
    → 200 {"model", "top_k": [{"class", "prob"}...], "bucket",
           "latency_ms"}            prob at FULL precision: the bitwise
                                    parity gate vs offline run_predict
                                    needs exact values, not display
                                    rounding
    → 400 {"error": "bad_request", ...}      wrong size/model
    → 503 {"error": "overloaded", "kind": "shed"|"draining",
           "queue_depth", "queue_limit", "retry_after_ms"}
                                    + Retry-After header — the typed shed
                                    payload; the queue is bounded and the
                                    server NEVER converts overload into
                                    unbounded latency
    → 504 {"error": "timeout"}      batcher answered nothing within
                                    serving.request_timeout_s
    GET  /v1/models                 the routing table (one row per
                                    registered engine, descriptor receipt
                                    included)

Observability is the EXISTING plane, extended, not a parallel one:
`serving/*` counters + latency-quantile gauges land in the process
registry (scraped at /metrics), the housekeeping loop heartbeats the
process exporter so `/healthz` is a real LB health check for the serving
process (the heartbeat means "the serve loop is alive", so an idle server
stays healthy), per-window summaries ride the flight recorder's ring (a
crash dumps the same black box a trainer crash does), and `/servingz`
serves the live admission state through the provider-registration pattern
(`telemetry/exporter.set_serving_source` — telemetry never imports this
package).

One server fronts the whole zoo: `add_engine` registers one
`PredictEngine` per model (each with its own batcher + admission
controller), routed by URL path over the `IngestDescriptor` table's
names.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.serving.batcher import DynamicBatcher, OverloadShed
from distributed_vgg_f_tpu.serving.controller import AdmissionController
from distributed_vgg_f_tpu.serving.engine import PredictEngine


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    #: listen() backlog. The stdlib default (5) refuses connections the
    #: moment an open-loop burst arrives faster than accept() turns —
    #: overload must reach the ADMISSION queue and shed with a typed 503,
    #: not die as TCP connection resets three layers below it.
    request_queue_size = 512

#: Counters/gauges pre-created at server start (the r11 discipline: a
#: visible zero reads as "instrumented, nothing happened" — and the README
#: counter-table drift guard scans these literals).
def _precreate(reg) -> None:
    reg.counter("serving/requests")
    reg.counter("serving/admitted")
    reg.counter("serving/shed")
    reg.counter("serving/errors")
    reg.counter("serving/batches")
    reg.counter("serving/batch_images")
    reg.counter("serving/padded_images")
    reg.counter("serving/controller_actuations")
    reg.set_gauge("serving/queue_depth", 0)
    reg.set_gauge("serving/models", 0)
    reg.set_gauge("serving/shed_rate", 0.0)
    reg.set_gauge("serving/window_ms", 0)
    # quantile gauges pre-created literally (the drift guard scans
    # literals); the housekeeping loop refreshes them per window
    reg.set_gauge("serving/latency_p50_ms", 0.0)
    reg.set_gauge("serving/latency_p95_ms", 0.0)
    reg.set_gauge("serving/latency_p99_ms", 0.0)


class PredictServer:
    """HTTP front end + model router + housekeeping loop."""

    def __init__(self, serving_cfg, *, registry=None, flight=None):
        self.cfg = serving_cfg
        self._reg = registry if registry is not None \
            else telemetry.get_registry()
        if flight is None:
            from distributed_vgg_f_tpu.telemetry.flight import get_flight
            flight = get_flight()
        self._flight = flight
        _precreate(self._reg)
        self._engines: Dict[str, PredictEngine] = {}
        self._batchers: Dict[str, DynamicBatcher] = {}
        self._controllers: Dict[str, AdmissionController] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._house_thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        self._windows = 0
        self._started_mono = time.monotonic()
        # ONE bound-method object for register AND compare-and-clear:
        # `self.servingz_payload` is a fresh object per attribute access,
        # so clearing with a second access would never match `is`
        self._servingz_source = self.servingz_payload

    # --------------------------------------------------------------- routing
    def add_engine(self, engine: PredictEngine) -> None:
        """Register one model's engine — its own batcher and (when
        configured) admission controller; the URL path routes by
        `engine.model_name`."""
        with self._lock:
            if engine.model_name in self._engines:
                raise ValueError(f"model {engine.model_name!r} already "
                                 "registered")
            batcher = DynamicBatcher(
                engine, max_batch=self.cfg.max_batch,
                window_ms=self.cfg.max_latency_ms,
                queue_limit=self.cfg.queue_limit,
                # queue entries older than the request timeout are
                # expired, never run: their handlers already replied 504
                reap_after_s=self.cfg.request_timeout_s,
                registry=self._reg)
            self._engines[engine.model_name] = engine
            self._batchers[engine.model_name] = batcher
            if self.cfg.controller:
                self._controllers[engine.model_name] = AdmissionController(
                    self.cfg, batcher, registry=self._reg,
                    flight=self._flight)
            self._reg.set_gauge("serving/models", len(self._engines))
        if self.cfg.warmup:
            engine.warmup()

    def engine(self, model: str) -> Optional[PredictEngine]:
        with self._lock:
            return self._engines.get(model)

    # ------------------------------------------------------------- lifecycle
    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    @property
    def endpoint(self) -> str:
        return f"{self.cfg.host}:{self.port}"

    def start(self) -> int:
        """Bind + serve + start housekeeping; returns the BOUND port (the
        port-0 contract every server in this repo follows)."""
        if self._server is not None:
            return self.port
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_POST(self):  # noqa: N802
                srv._handle_post(self)

            def do_GET(self):  # noqa: N802
                srv._handle_get(self)

        self._server = _HTTPServer(
            (self.cfg.host, int(self.cfg.port)), Handler)
        self._started_mono = time.monotonic()
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, name="serving-http",
            daemon=True)
        self._serve_thread.start()
        self._house_thread = threading.Thread(
            target=self._housekeeping, name="serving-housekeeping",
            daemon=True)
        self._house_thread.start()
        from distributed_vgg_f_tpu.telemetry import exporter as _exp
        _exp.set_serving_source(self._servingz_source)
        return self.port

    def wait(self) -> None:
        """Block the caller (the CLI serve mode) until close()."""
        self._closed.wait()

    def close(self) -> None:
        """Drain, don't drop: stop admission + the listener, answer every
        in-flight request, then tear the threads down."""
        if self._closed.is_set():
            return
        self._closed.set()
        from distributed_vgg_f_tpu.telemetry import exporter as _exp
        _exp.clear_serving_source(self._servingz_source)
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        with self._lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            b.close()
        for t in (self._serve_thread, self._house_thread):
            if t is not None:
                t.join(timeout=10)

    # ---------------------------------------------------------- housekeeping
    def _housekeeping(self) -> None:
        """The serve loop's pulse: per interval, feed each model's
        controller its window evidence, refresh the latency-quantile
        gauges, append a window to the flight ring, and heartbeat the
        process exporter (the serving heartbeat /healthz reads — ticked
        whether or not traffic arrives, so an idle server is healthy and a
        wedged one goes 503)."""
        interval = max(0.01, float(self.cfg.controller_interval_s))
        while not self._closed.wait(interval):
            self._windows += 1
            # the whole window body is receipts: an exception here must
            # never kill the loop — a dead housekeeping thread silences
            # the heartbeat and an LB would drain a server that is still
            # answering requests
            try:
                self._housekeeping_window(interval)
            except Exception:  # noqa: BLE001 — receipts never kill serving
                self._reg.inc("serving/errors")
            from distributed_vgg_f_tpu.telemetry import exporter as _exp
            exp = _exp.get_exporter()
            if exp is not None:
                exp.heartbeat(self._windows)

    def _housekeeping_window(self, interval: float) -> None:
        lat_all = []
        shed = admitted = 0
        depth_total = 0
        window_max = 0
        verdicts = {}
        with self._lock:
            items = list(self._batchers.items())
            controllers = dict(self._controllers)
        for name, batcher in items:
            stats = batcher.window_stats()
            lat_all.extend(stats["latencies_ms"])
            shed += stats["shed"]
            admitted += stats["admitted"]
            depth_total += stats["queue_depth"]
            window_max = max(window_max, batcher.window_ms)
            ctrl = controllers.get(name)
            if ctrl is not None:
                verdicts[name] = ctrl.observe_window(stats)[
                    "serving_verdict"]
            else:
                verdicts[name] = "steady"
        # process-global gauges AGGREGATE across models (sum of depths,
        # widest live window) — per-model detail lives on /servingz; two
        # batchers writing one gauge would be last-writer-wins garbage
        self._reg.set_gauge("serving/queue_depth", depth_total)
        self._reg.set_gauge("serving/window_ms", window_max)
        total = shed + admitted
        self._reg.set_gauge("serving/shed_rate",
                            round(shed / total, 4) if total else 0.0)
        quantiles = _quantiles(lat_all)
        for key, value in quantiles.items():
            self._reg.set_gauge(f"serving/latency_{key}_ms", value)
        # the worst per-model verdict labels the window in the ring
        verdict = "queue_pressure" if "queue_pressure" in \
            verdicts.values() else "steady"
        self._flight.record_window(
            step=self._windows,
            wall_s=interval,
            stall={"verdict": verdict,
                   "shed": shed, "admitted": admitted,
                   **({"p99_ms": quantiles["p99"]}
                      if quantiles else {})},
            counters={"serving/shed": shed,
                      "serving/admitted": admitted})

    # -------------------------------------------------------------- handling
    def _handle_post(self, req: BaseHTTPRequestHandler) -> None:
        self._reg.inc("serving/requests")
        t0 = time.monotonic()
        try:
            path = req.path.split("?", 1)[0]
            query = req.path.partition("?")[2]
            if not path.startswith("/v1/predict/"):
                _reply(req, 404, {"error": "not found",
                                  "endpoints": ["/v1/predict/<model>",
                                                "/v1/models"]})
                return
            model = path[len("/v1/predict/"):].strip("/")
            engine = self.engine(model)
            if engine is None:
                with self._lock:
                    known = sorted(self._engines)
                _reply(req, 400, {"error": "bad_request",
                                  "detail": f"unknown model {model!r}",
                                  "models": known})
                return
            length = int(req.headers.get("Content-Length") or 0)
            expect = engine.image_size * engine.image_size * 3
            if length != expect:
                _reply(req, 400, {
                    "error": "bad_request",
                    "detail": f"payload must be exactly {expect} bytes of "
                              f"raw uint8 pixels "
                              f"({engine.image_size}x{engine.image_size}"
                              f"x3, the u8 wire), got {length}"})
                return
            body = req.rfile.read(length)
            if len(body) != length:
                # truncated upload: a CLIENT fault (400), not a server
                # error — serving/errors is the counter ops alert on
                _reply(req, 400, {
                    "error": "bad_request",
                    "detail": f"body truncated: declared {length} bytes, "
                              f"received {len(body)}"})
                return
            image = np.frombuffer(body, np.uint8).reshape(
                engine.image_size, engine.image_size, 3)
            with self._lock:
                batcher = self._batchers[model]
            # client-supplied correlation id (optional header): tags this
            # request's span AND the engine-flush span that carries it, so
            # telemetry/stitch.py can draw the request→flush flow arrow
            trace_id = str(req.headers.get("X-DVGGF-Trace-Id") or "") or None
            t0_ns = time.monotonic_ns()
            try:
                pending = batcher.submit(image, trace_id=trace_id)
            except OverloadShed as shed:
                # the header is SECOND-granular (RFC 9110): round the ms
                # hint UP so a compliant client never retries early; the
                # JSON field carries the precise hint
                retry_s = -(-int(self.cfg.shed_retry_after_ms) // 1000) or 1
                _reply(req, 503, {
                    "error": "overloaded", "kind": shed.kind,
                    "model": model,
                    "queue_depth": shed.queue_depth,
                    "queue_limit": shed.queue_limit,
                    "retry_after_ms": int(self.cfg.shed_retry_after_ms),
                }, headers={"Retry-After": str(retry_s)})
                return
            if not pending.event.wait(float(self.cfg.request_timeout_s)):
                self._reg.inc("serving/errors")
                _reply(req, 504, {"error": "timeout", "model": model,
                                  "timeout_s": self.cfg.request_timeout_s})
                return
            if pending.error is not None:
                self._reg.inc("serving/errors")
                if isinstance(pending.error, TimeoutError):
                    # reaped from the queue past the request deadline —
                    # same class as the handler's own wait timeout
                    _reply(req, 504, {"error": "timeout", "model": model,
                                      "detail": str(pending.error)})
                    return
                _reply(req, 500, {"error": "predict_failed",
                                  "detail": repr(pending.error)})
                return
            if trace_id:
                telemetry.record(
                    "serving_request", "serving", t0_ns,
                    time.monotonic_ns() - t0_ns,
                    {"trace_id": trace_id, "flow": "out", "model": model})
            k = _top_k_from_query(query, engine.num_classes)
            from distributed_vgg_f_tpu.train.predict import top_k_records
            _reply(req, 200, {
                "model": model,
                "top_k": top_k_records(pending.probs, k,
                                       full_precision=True),
                "bucket": pending.bucket,
                "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            })
        except (BrokenPipeError, ConnectionError):
            pass  # client hung up — its problem
        except Exception as e:  # noqa: BLE001 — a request must never kill
            self._reg.inc("serving/errors")
            try:
                _reply(req, 500, {"error": "internal", "detail": repr(e)})
            except (BrokenPipeError, ConnectionError, OSError):
                pass

    def _handle_get(self, req: BaseHTTPRequestHandler) -> None:
        self._reg.inc("serving/requests")
        path = req.path.split("?", 1)[0].rstrip("/")
        if path == "/v1/models":
            with self._lock:
                rows = {name: eng.describe()
                        for name, eng in self._engines.items()}
            _reply(req, 200, {"models": rows})
            return
        _reply(req, 404, {"error": "not found",
                          "endpoints": ["/v1/predict/<model>",
                                        "/v1/models"]})

    # -------------------------------------------------------------- receipts
    def servingz_payload(self) -> dict:
        """The /servingz provider payload: live queue depth, bucket
        occupancy, shed rate, window state, controller receipts."""
        with self._lock:
            names = sorted(self._engines)
            models = {}
            for name in names:
                row = {"engine": self._engines[name].describe(),
                       "admission": self._batchers[name].describe()}
                ctrl = self._controllers.get(name)
                if ctrl is not None:
                    row["controller"] = ctrl.describe()
                models[name] = row
        return {"enabled": True,
                "endpoint": self.endpoint if self._server else None,
                "uptime_s": round(time.monotonic() - self._started_mono, 3),
                "windows": self._windows,
                "shed_rate": self._reg.gauge("serving/shed_rate", 0.0),
                "latency_ms": {
                    q: self._reg.gauge(f"serving/latency_{q}_ms")
                    for q in ("p50", "p95", "p99")},
                "models": models}


def _quantiles(latencies_ms) -> dict:
    if not latencies_ms:
        return {}
    arr = np.asarray(latencies_ms, np.float64)
    return {"p50": round(float(np.percentile(arr, 50)), 3),
            "p95": round(float(np.percentile(arr, 95)), 3),
            "p99": round(float(np.percentile(arr, 99)), 3)}


def _top_k_from_query(query: str, num_classes: int, default: int = 5) -> int:
    k = default
    for part in (query or "").split("&"):
        key, sep, value = part.partition("=")
        if sep and key == "k":
            try:
                k = int(value)
            except ValueError:
                pass
    return max(1, min(k, num_classes))


def _reply(req: BaseHTTPRequestHandler, status: int, payload: dict,
           headers: Optional[dict] = None) -> None:
    body = json.dumps(payload).encode()
    req.send_response(status)
    req.send_header("Content-Type", "application/json")
    req.send_header("Content-Length", str(len(body)))
    for key, value in (headers or {}).items():
        req.send_header(key, value)
    req.end_headers()
    req.wfile.write(body)


def serve_from_trainer(trainer, *, start: bool = True) -> PredictServer:
    """The `--mode serve` entry: one engine over the trainer's latest
    checkpoint (run_predict's restore path), routed under the configured
    model's name. Zoo composition is programmatic: build more engines with
    `PredictEngine.from_trainer` (one trainer per checkpoint) and
    `add_engine` them onto the same server."""
    cfg = trainer.cfg
    server = PredictServer(cfg.serving)
    server.add_engine(PredictEngine.from_trainer(trainer))
    if start:
        server.start()
    return server
