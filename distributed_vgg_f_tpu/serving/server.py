"""Always-on predict server — the u8-wire HTTP front end over the dynamic
batcher (r17; ROADMAP item 1, the serving half of arXiv 1605.08695's
training/serving split).

Request contract (deliberately the thinnest thing that carries the u8
wire over HTTP — the wire IS the payload format, HTTP adds routing only):

    POST /v1/predict/<model>        body: raw uint8 pixels, C-order,
                                    exactly image_size*image_size*3 bytes
                                    (1 B/px off the network; the device-
                                    finish prologue normalizes on device)
    → 200 {"model", "top_k": [{"class", "prob"}...], "bucket",
           "latency_ms"}            prob at FULL precision: the bitwise
                                    parity gate vs offline run_predict
                                    needs exact values, not display
                                    rounding
    → 400 {"error": "bad_request", ...}      wrong size/model
    → 503 {"error": "overloaded", "kind": "shed"|"draining",
           "queue_depth", "queue_limit", "retry_after_ms"}
                                    + Retry-After header — the typed shed
                                    payload; the queue is bounded and the
                                    server NEVER converts overload into
                                    unbounded latency
    → 504 {"error": "timeout"}      batcher answered nothing within
                                    serving.request_timeout_s
    GET  /v1/models                 the routing table (one row per
                                    registered engine, descriptor receipt
                                    included)

Observability is the EXISTING plane, extended, not a parallel one:
`serving/*` counters + latency-quantile gauges land in the process
registry (scraped at /metrics), the housekeeping loop heartbeats the
process exporter so `/healthz` is a real LB health check for the serving
process (the heartbeat means "the serve loop is alive", so an idle server
stays healthy), per-window summaries ride the flight recorder's ring (a
crash dumps the same black box a trainer crash does), and `/servingz`
serves the live admission state through the provider-registration pattern
(`telemetry/exporter.set_serving_source` — telemetry never imports this
package).

One server fronts the whole zoo: `add_engine` registers one
`PredictEngine` per model (each with its own batcher + admission
controller), routed by URL path over the `IngestDescriptor` table's
names.

Latency tiers (r23): the routing key is (model, TIER). A request picks
its tier with `?tier=fp32|bf16|int8|student` (unknown values are a typed
400 naming the ladder); absent the parameter it gets the configured
`serving.tier_default`. Every tier is a full engine with its own batcher
— batches never mix tiers, so the per-tier bitwise parity contract and
the per-tier latency quantiles (`serving/tier_latency_*`) are both
meaningful. The whole surface sits behind the kill switch
`serving.tiers.enabled` (default OFF): disabled, `add_engine` refuses
non-fp32 engines, the query parameter is ignored exactly as r22 ignored
it, and the server lowers and routes precisely the r22 fp32-only plane.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

import numpy as np

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.config import SERVING_TIERS
from distributed_vgg_f_tpu.serving.batcher import DynamicBatcher, OverloadShed
from distributed_vgg_f_tpu.serving.controller import AdmissionController
from distributed_vgg_f_tpu.serving.engine import PredictEngine


class _HTTPServer(ThreadingHTTPServer):
    daemon_threads = True
    #: listen() backlog. The stdlib default (5) refuses connections the
    #: moment an open-loop burst arrives faster than accept() turns —
    #: overload must reach the ADMISSION queue and shed with a typed 503,
    #: not die as TCP connection resets three layers below it.
    request_queue_size = 512

#: Counters/gauges pre-created at server start (the r11 discipline: a
#: visible zero reads as "instrumented, nothing happened" — and the README
#: counter-table drift guard scans these literals).
def _precreate(reg) -> None:
    reg.counter("serving/requests")
    reg.counter("serving/admitted")
    reg.counter("serving/shed")
    reg.counter("serving/errors")
    reg.counter("serving/batches")
    reg.counter("serving/batch_images")
    reg.counter("serving/padded_images")
    reg.counter("serving/controller_actuations")
    reg.set_gauge("serving/queue_depth", 0)
    reg.set_gauge("serving/models", 0)
    reg.set_gauge("serving/shed_rate", 0.0)
    reg.set_gauge("serving/window_ms", 0)
    # quantile gauges pre-created literally (the drift guard scans
    # literals); the housekeeping loop refreshes them per window
    reg.set_gauge("serving/latency_p50_ms", 0.0)
    reg.set_gauge("serving/latency_p95_ms", 0.0)
    reg.set_gauge("serving/latency_p99_ms", 0.0)
    # per-tier request counters + latency quantiles (r23) — one literal
    # per (tier, metric): the drift guard scans call literals, so a loop
    # over SERVING_TIERS here would hide the names from the lint
    reg.counter("serving/tier_requests_fp32")
    reg.counter("serving/tier_requests_bf16")
    reg.counter("serving/tier_requests_int8")
    reg.counter("serving/tier_requests_student")
    reg.set_gauge("serving/tier_latency_p50_ms_fp32", 0.0)
    reg.set_gauge("serving/tier_latency_p50_ms_bf16", 0.0)
    reg.set_gauge("serving/tier_latency_p50_ms_int8", 0.0)
    reg.set_gauge("serving/tier_latency_p50_ms_student", 0.0)
    reg.set_gauge("serving/tier_latency_p99_ms_fp32", 0.0)
    reg.set_gauge("serving/tier_latency_p99_ms_bf16", 0.0)
    reg.set_gauge("serving/tier_latency_p99_ms_int8", 0.0)
    reg.set_gauge("serving/tier_latency_p99_ms_student", 0.0)


class PredictServer:
    """HTTP front end + model router + housekeeping loop."""

    def __init__(self, serving_cfg, *, registry=None, flight=None):
        self.cfg = serving_cfg
        self._reg = registry if registry is not None \
            else telemetry.get_registry()
        if flight is None:
            from distributed_vgg_f_tpu.telemetry.flight import get_flight
            flight = get_flight()
        self._flight = flight
        _precreate(self._reg)
        # routing key: (model, tier) — one engine + one batcher per pair,
        # so batches never mix tiers (r23)
        self._engines: Dict[tuple, PredictEngine] = {}
        self._batchers: Dict[tuple, DynamicBatcher] = {}
        self._controllers: Dict[tuple, AdmissionController] = {}
        self._lock = threading.Lock()
        self._server: Optional[ThreadingHTTPServer] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._house_thread: Optional[threading.Thread] = None
        self._closed = threading.Event()
        self._windows = 0
        self._started_mono = time.monotonic()
        # ONE bound-method object for register AND compare-and-clear:
        # `self.servingz_payload` is a fresh object per attribute access,
        # so clearing with a second access would never match `is`
        self._servingz_source = self.servingz_payload

    # --------------------------------------------------------------- routing
    def _tiers_enabled(self) -> bool:
        tiers = getattr(self.cfg, "tiers", None)
        return bool(tiers is not None and tiers.enabled)

    def add_engine(self, engine: PredictEngine) -> None:
        """Register one (model, tier) engine — its own batcher and (when
        configured) admission controller; the URL path routes by
        `engine.model_name`, the `?tier=` query by `engine.tier`. With
        `serving.tiers.enabled` false (the kill switch) only fp32 engines
        register: the disabled server cannot even HOLD a tier ladder, so
        its lowered surface is structurally the r22 one."""
        tier = str(getattr(engine, "tier", "fp32"))
        if tier != "fp32" and not self._tiers_enabled():
            raise ValueError(
                f"engine ({engine.model_name!r}, tier={tier!r}) refused: "
                "serving.tiers.enabled is false — the kill switch pins "
                "this server to the fp32-only surface")
        key = (engine.model_name, tier)
        with self._lock:
            if key in self._engines:
                raise ValueError(f"model {engine.model_name!r} tier "
                                 f"{tier!r} already registered")
            batcher = DynamicBatcher(
                engine, max_batch=self.cfg.max_batch,
                window_ms=self.cfg.max_latency_ms,
                queue_limit=self.cfg.queue_limit,
                # queue entries older than the request timeout are
                # expired, never run: their handlers already replied 504
                reap_after_s=self.cfg.request_timeout_s,
                registry=self._reg)
            self._engines[key] = engine
            self._batchers[key] = batcher
            if self.cfg.controller:
                self._controllers[key] = AdmissionController(
                    self.cfg, batcher, registry=self._reg,
                    flight=self._flight)
            # the gauge keeps its r22 meaning: distinct MODELS, not engines
            self._reg.set_gauge(
                "serving/models", len({m for m, _ in self._engines}))
        if self.cfg.warmup:
            engine.warmup()

    def engine(self, model: str,
               tier: str = "fp32") -> Optional[PredictEngine]:
        with self._lock:
            return self._engines.get((model, tier))

    def _model_tiers(self, model: str):
        """Registered tiers for one model, ladder order."""
        with self._lock:
            mine = {t for m, t in self._engines if m == model}
        return [t for t in SERVING_TIERS if t in mine]

    # ------------------------------------------------------------- lifecycle
    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    @property
    def endpoint(self) -> str:
        return f"{self.cfg.host}:{self.port}"

    def start(self) -> int:
        """Bind + serve + start housekeeping; returns the BOUND port (the
        port-0 contract every server in this repo follows)."""
        if self._server is not None:
            return self.port
        srv = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802 — quiet
                pass

            def do_POST(self):  # noqa: N802
                srv._handle_post(self)

            def do_GET(self):  # noqa: N802
                srv._handle_get(self)

        self._server = _HTTPServer(
            (self.cfg.host, int(self.cfg.port)), Handler)
        self._started_mono = time.monotonic()
        self._serve_thread = threading.Thread(
            target=self._server.serve_forever, name="serving-http",
            daemon=True)
        self._serve_thread.start()
        self._house_thread = threading.Thread(
            target=self._housekeeping, name="serving-housekeeping",
            daemon=True)
        self._house_thread.start()
        from distributed_vgg_f_tpu.telemetry import exporter as _exp
        _exp.set_serving_source(self._servingz_source)
        return self.port

    def wait(self) -> None:
        """Block the caller (the CLI serve mode) until close()."""
        self._closed.wait()

    def close(self) -> None:
        """Drain, don't drop: stop admission + the listener, answer every
        in-flight request, then tear the threads down."""
        if self._closed.is_set():
            return
        self._closed.set()
        from distributed_vgg_f_tpu.telemetry import exporter as _exp
        _exp.clear_serving_source(self._servingz_source)
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        with self._lock:
            batchers = list(self._batchers.values())
        for b in batchers:
            b.close()
        for t in (self._serve_thread, self._house_thread):
            if t is not None:
                t.join(timeout=10)

    # ---------------------------------------------------------- housekeeping
    def _housekeeping(self) -> None:
        """The serve loop's pulse: per interval, feed each model's
        controller its window evidence, refresh the latency-quantile
        gauges, append a window to the flight ring, and heartbeat the
        process exporter (the serving heartbeat /healthz reads — ticked
        whether or not traffic arrives, so an idle server is healthy and a
        wedged one goes 503)."""
        interval = max(0.01, float(self.cfg.controller_interval_s))
        while not self._closed.wait(interval):
            self._windows += 1
            # the whole window body is receipts: an exception here must
            # never kill the loop — a dead housekeeping thread silences
            # the heartbeat and an LB would drain a server that is still
            # answering requests
            try:
                self._housekeeping_window(interval)
            except Exception:  # noqa: BLE001 — receipts never kill serving
                self._reg.inc("serving/errors")
            from distributed_vgg_f_tpu.telemetry import exporter as _exp
            exp = _exp.get_exporter()
            if exp is not None:
                exp.heartbeat(self._windows)

    def _housekeeping_window(self, interval: float) -> None:
        lat_all = []
        lat_by_tier: Dict[str, list] = {}
        shed = admitted = 0
        depth_total = 0
        window_max = 0
        verdicts = {}
        with self._lock:
            items = list(self._batchers.items())
            controllers = dict(self._controllers)
        for key, batcher in items:
            stats = batcher.window_stats()
            lat_all.extend(stats["latencies_ms"])
            lat_by_tier.setdefault(key[1], []).extend(
                stats["latencies_ms"])
            shed += stats["shed"]
            admitted += stats["admitted"]
            depth_total += stats["queue_depth"]
            window_max = max(window_max, batcher.window_ms)
            ctrl = controllers.get(key)
            if ctrl is not None:
                verdicts[key] = ctrl.observe_window(stats)[
                    "serving_verdict"]
            else:
                verdicts[key] = "steady"
        # process-global gauges AGGREGATE across models (sum of depths,
        # widest live window) — per-model detail lives on /servingz; two
        # batchers writing one gauge would be last-writer-wins garbage
        self._reg.set_gauge("serving/queue_depth", depth_total)
        self._reg.set_gauge("serving/window_ms", window_max)
        total = shed + admitted
        self._reg.set_gauge("serving/shed_rate",
                            round(shed / total, 4) if total else 0.0)
        quantiles = _quantiles(lat_all)
        for key, value in quantiles.items():
            self._reg.set_gauge(f"serving/latency_{key}_ms", value)
        # per-tier quantiles (precreated literally in _precreate; refreshed
        # dynamically here — the drift guard scans literals, not refreshes)
        for tier, lats in lat_by_tier.items():
            tq = _quantiles(lats)
            if tq:
                self._reg.set_gauge(
                    f"serving/tier_latency_p50_ms_{tier}", tq["p50"])
                self._reg.set_gauge(
                    f"serving/tier_latency_p99_ms_{tier}", tq["p99"])
        # the worst per-model verdict labels the window in the ring
        verdict = "queue_pressure" if "queue_pressure" in \
            verdicts.values() else "steady"
        self._flight.record_window(
            step=self._windows,
            wall_s=interval,
            stall={"verdict": verdict,
                   "shed": shed, "admitted": admitted,
                   **({"p99_ms": quantiles["p99"]}
                      if quantiles else {})},
            counters={"serving/shed": shed,
                      "serving/admitted": admitted})

    # -------------------------------------------------------------- handling
    def _handle_post(self, req: BaseHTTPRequestHandler) -> None:
        self._reg.inc("serving/requests")
        t0 = time.monotonic()
        try:
            path = req.path.split("?", 1)[0]
            query = req.path.partition("?")[2]
            if not path.startswith("/v1/predict/"):
                _reply(req, 404, {"error": "not found",
                                  "endpoints": ["/v1/predict/<model>",
                                                "/v1/models"]})
                return
            model = path[len("/v1/predict/"):].strip("/")
            tiers_on = self._tiers_enabled()
            requested = _tier_from_query(query) if tiers_on else None
            if requested is not None and requested not in SERVING_TIERS:
                # the typed tier 400: names the offending value AND the
                # ladder, so a client can self-correct without docs
                _reply(req, 400, {"error": "bad_request",
                                  "detail": f"unknown tier {requested!r}",
                                  "tier": requested,
                                  "tiers": list(SERVING_TIERS)})
                return
            tier = requested if requested is not None else (
                self.cfg.tier_default if tiers_on else "fp32")
            engine = self.engine(model, tier)
            if engine is None and requested is None and tier != "fp32":
                # the model never registered the configured default tier —
                # an implicit default degrades to fp32; an EXPLICIT ask
                # never silently substitutes (400 below instead)
                tier = "fp32"
                engine = self.engine(model, tier)
            if engine is None:
                registered = self._model_tiers(model)
                if registered:
                    _reply(req, 400, {
                        "error": "bad_request",
                        "detail": f"model {model!r} does not serve tier "
                                  f"{tier!r}",
                        "tier": tier, "tiers": registered})
                    return
                with self._lock:
                    known = sorted({m for m, _ in self._engines})
                _reply(req, 400, {"error": "bad_request",
                                  "detail": f"unknown model {model!r}",
                                  "models": known})
                return
            self._reg.inc(f"serving/tier_requests_{tier}")
            length = int(req.headers.get("Content-Length") or 0)
            expect = engine.image_size * engine.image_size * 3
            if length != expect:
                _reply(req, 400, {
                    "error": "bad_request",
                    "detail": f"payload must be exactly {expect} bytes of "
                              f"raw uint8 pixels "
                              f"({engine.image_size}x{engine.image_size}"
                              f"x3, the u8 wire), got {length}"})
                return
            body = req.rfile.read(length)
            if len(body) != length:
                # truncated upload: a CLIENT fault (400), not a server
                # error — serving/errors is the counter ops alert on
                _reply(req, 400, {
                    "error": "bad_request",
                    "detail": f"body truncated: declared {length} bytes, "
                              f"received {len(body)}"})
                return
            image = np.frombuffer(body, np.uint8).reshape(
                engine.image_size, engine.image_size, 3)
            with self._lock:
                batcher = self._batchers[(model, tier)]
            # client-supplied correlation id (optional header): tags this
            # request's span AND the engine-flush span that carries it, so
            # telemetry/stitch.py can draw the request→flush flow arrow
            trace_id = str(req.headers.get("X-DVGGF-Trace-Id") or "") or None
            t0_ns = time.monotonic_ns()
            try:
                pending = batcher.submit(image, trace_id=trace_id)
            except OverloadShed as shed:
                # the header is SECOND-granular (RFC 9110): round the ms
                # hint UP so a compliant client never retries early; the
                # JSON field carries the precise hint
                retry_s = -(-int(self.cfg.shed_retry_after_ms) // 1000) or 1
                _reply(req, 503, {
                    "error": "overloaded", "kind": shed.kind,
                    "model": model,
                    "queue_depth": shed.queue_depth,
                    "queue_limit": shed.queue_limit,
                    "retry_after_ms": int(self.cfg.shed_retry_after_ms),
                }, headers={"Retry-After": str(retry_s)})
                return
            if not pending.event.wait(float(self.cfg.request_timeout_s)):
                self._reg.inc("serving/errors")
                _reply(req, 504, {"error": "timeout", "model": model,
                                  "timeout_s": self.cfg.request_timeout_s})
                return
            if pending.error is not None:
                self._reg.inc("serving/errors")
                if isinstance(pending.error, TimeoutError):
                    # reaped from the queue past the request deadline —
                    # same class as the handler's own wait timeout
                    _reply(req, 504, {"error": "timeout", "model": model,
                                      "detail": str(pending.error)})
                    return
                _reply(req, 500, {"error": "predict_failed",
                                  "detail": repr(pending.error)})
                return
            if trace_id:
                telemetry.record(
                    "serving_request", "serving", t0_ns,
                    time.monotonic_ns() - t0_ns,
                    {"trace_id": trace_id, "flow": "out", "model": model})
            k = _top_k_from_query(query, engine.num_classes)
            from distributed_vgg_f_tpu.train.predict import top_k_records
            _reply(req, 200, {
                "model": model,
                # the answering tier rides the payload only when the tier
                # plane is on — disabled, the response body is r22's
                **({"tier": tier} if tiers_on else {}),
                "top_k": top_k_records(pending.probs, k,
                                       full_precision=True),
                "bucket": pending.bucket,
                "latency_ms": round((time.monotonic() - t0) * 1e3, 3),
            })
        except (BrokenPipeError, ConnectionError):
            pass  # client hung up — its problem
        except Exception as e:  # noqa: BLE001 — a request must never kill
            self._reg.inc("serving/errors")
            try:
                _reply(req, 500, {"error": "internal", "detail": repr(e)})
            except (BrokenPipeError, ConnectionError, OSError):
                pass

    def _handle_get(self, req: BaseHTTPRequestHandler) -> None:
        self._reg.inc("serving/requests")
        path = req.path.split("?", 1)[0].rstrip("/")
        if path == "/v1/models":
            tiers_on = self._tiers_enabled()
            with self._lock:
                engines = dict(self._engines)
            rows: Dict[str, dict] = {}
            for (name, tier), eng in engines.items():
                # the row keeps its r22 shape — the fp32 engine's receipt
                # — and the tier ladder rides a "tiers" sub-table when the
                # plane is enabled
                if tier == "fp32":
                    base = dict(eng.describe())
                    base.update(rows.get(name) or {})
                    rows[name] = base
                if tiers_on:
                    rows.setdefault(name, {}).setdefault(
                        "tiers", {})[tier] = eng.describe()
            _reply(req, 200, {"models": rows})
            return
        _reply(req, 404, {"error": "not found",
                          "endpoints": ["/v1/predict/<model>",
                                        "/v1/models"]})

    # -------------------------------------------------------------- receipts
    def servingz_payload(self) -> dict:
        """The /servingz provider payload: live queue depth, bucket
        occupancy, shed rate, window state, controller receipts — plus,
        with tiers enabled, each model's ladder (per-tier engine/admission
        rows) and the ladder BUILD receipt (per-bucket compile seconds +
        the HBM residency estimate, satellite 6: warmup cost used to be
        invisible to the flight recorder)."""
        tiers_on = self._tiers_enabled()
        with self._lock:
            keys = sorted(self._engines)
            models: Dict[str, dict] = {}
            for key in keys:
                name, tier = key
                row = {"engine": self._engines[key].describe(),
                       "admission": self._batchers[key].describe()}
                ctrl = self._controllers.get(key)
                if ctrl is not None:
                    row["controller"] = ctrl.describe()
                if tier == "fp32":
                    models.setdefault(name, {}).update(row)
                if tiers_on:
                    models.setdefault(name, {}).setdefault(
                        "tiers", {})[tier] = row
        payload = {"enabled": True,
                   "endpoint": self.endpoint if self._server else None,
                   "uptime_s": round(
                       time.monotonic() - self._started_mono, 3),
                   "windows": self._windows,
                   "shed_rate": self._reg.gauge("serving/shed_rate", 0.0),
                   "latency_ms": {
                       q: self._reg.gauge(f"serving/latency_{q}_ms")
                       for q in ("p50", "p95", "p99")},
                   "models": models}
        if tiers_on:
            payload["tier_default"] = self.cfg.tier_default
            payload["ladder"] = self.ladder_receipt()
        return payload

    def ladder_receipt(self) -> dict:
        """Per (model, tier) build cost: bucket→compile seconds + the HBM
        residency estimate — the start-record / /servingz ladder receipt."""
        with self._lock:
            engines = dict(self._engines)
        out: Dict[str, dict] = {}
        for (name, tier), eng in sorted(engines.items()):
            out.setdefault(name, {})[tier] = {
                "served_by": getattr(eng, "served_by", name),
                "compile_s": {str(b): s for b, s in
                              sorted(getattr(eng, "compile_log",
                                             {}).items())},
                "hbm_estimate_bytes": int(getattr(
                    eng, "hbm_estimate_bytes", 0))}
        return out


def _quantiles(latencies_ms) -> dict:
    if not latencies_ms:
        return {}
    arr = np.asarray(latencies_ms, np.float64)
    return {"p50": round(float(np.percentile(arr, 50)), 3),
            "p95": round(float(np.percentile(arr, 95)), 3),
            "p99": round(float(np.percentile(arr, 99)), 3)}


def _tier_from_query(query: str) -> Optional[str]:
    """The `?tier=` value, verbatim (validation is the caller's: an
    unknown value must 400 with the ladder, not silently default)."""
    for part in (query or "").split("&"):
        key, sep, value = part.partition("=")
        if sep and key == "tier":
            return value
    return None


def _top_k_from_query(query: str, num_classes: int, default: int = 5) -> int:
    k = default
    for part in (query or "").split("&"):
        key, sep, value = part.partition("=")
        if sep and key == "k":
            try:
                k = int(value)
            except ValueError:
                pass
    return max(1, min(k, num_classes))


def _reply(req: BaseHTTPRequestHandler, status: int, payload: dict,
           headers: Optional[dict] = None) -> None:
    body = json.dumps(payload).encode()
    req.send_response(status)
    req.send_header("Content-Type", "application/json")
    req.send_header("Content-Length", str(len(body)))
    for key, value in (headers or {}).items():
        req.send_header(key, value)
    req.end_headers()
    req.wfile.write(body)


def serve_from_trainer(trainer, *, start: bool = True) -> PredictServer:
    """The `--mode serve` entry: one engine over the trainer's latest
    checkpoint (run_predict's restore path), routed under the configured
    model's name. With `serving.tiers.enabled` the derivable tiers (bf16,
    int8 for the vggf family) are built over that base engine; the student
    tier needs its own distilled weights (train/distill.py) and is added
    programmatically. Zoo composition likewise: build more engines with
    `PredictEngine.from_trainer` (one trainer per checkpoint) and
    `add_engine` them onto the same server."""
    cfg = trainer.cfg
    server = PredictServer(cfg.serving)
    base = PredictEngine.from_trainer(trainer)
    server.add_engine(base)
    if getattr(cfg.serving, "tiers", None) is not None \
            and cfg.serving.tiers.enabled:
        from distributed_vgg_f_tpu.serving.tiers import build_tier_engines
        tiers = ["bf16"]
        # int8 quantizes the CNN-F head stack — vggf family only
        if cfg.model.name.startswith("vggf"):
            tiers.append("int8")
        for eng in build_tier_engines(base, cfg.serving.tiers,
                                      tiers=tiers).values():
            server.add_engine(eng)
    if start:
        server.start()
    # the ladder build receipt lands in the run log as a start-class
    # record: per-tier compile seconds + HBM estimate (satellite 6)
    logger = getattr(trainer, "logger", None)
    if logger is not None:
        logger.log("serving_start", {
            "endpoint": server.endpoint if start else None,
            "tiers_enabled": server._tiers_enabled(),
            "ladder": server.ladder_receipt()})
    return server
