"""Latency tiers for the predict server (r23): bf16 / int8 / student
engine variants behind one router.

Each tier is a full `PredictEngine` — its own AOT bucket ladder over its
own forward — so the PR 14 parity contract holds PER TIER: a tier's
server response is bitwise-equal to that tier's own offline `engine.run`,
because both are the same executables. The ladder:

- **fp32** — the base engine, unchanged (the r17 surface).
- **bf16** — the same architecture with `compute_dtype` flipped to
  bfloat16: params cast ONCE at build (not per request), the
  device-finish prologue emits bf16 activations, logits come back fp32
  (every zoo model casts its output, train/predict softmaxes in f32).
- **int8** — post-training quantization of the FC-heavy heads (fc6/fc7/
  fc8 are ~90 % of CNN-F's parameters, arXiv 2004.13336's exact
  workload): per-OUT-channel symmetric weight scales, per-tensor
  activation scales from a deterministic calibration pass over the u8
  wire. The per-tensor activation scale forces a structural fact this
  tier exploits for latency: any channel whose calibrated range falls
  below half the activation LSB (`scale/2`) rounds to ZERO under int8
  quantization, so its row of the next weight matrix contributes nothing
  — the engine elides those channels from the compacted GEMMs instead of
  multiplying zeros. On calibration-range inputs the compacted network
  computes exactly what dense int8 emulation computes (pinned in
  tests/test_serving_tiers.py); off-range inputs are where the tier's
  committed accuracy-delta receipt earns its keep. The conv trunk stays
  in the model's serving compute dtype (bf16 on the TPU presets) — heads
  are where the quantizable parameter mass lives.
- **student** — the half-width `vggf_student` (train/distill.py) serving
  the flagship's route: same wire, same descriptor contract, ~4x fewer
  head parameters.

Quantized execution note: weights are STORED int8 + f32 scales (that is
the receipt and the device-residency win); the host executes the heads as
dequantized-constant GEMMs (XLA folds `wq * scale` once at compile), and
activations are still rounded/clamped onto the int8 grid so the numerics
are int8-faithful. XLA:CPU has no fast int8 GEMM kernel (measured ~6x
SLOWER than f32 at batch 8 on this host — benchmarks/runs/host_r23
protocol notes); the MXU int8 path is the queued device row
(benchmarks/tpu_session_r18.sh tier grid).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

import numpy as np

from distributed_vgg_f_tpu.config import SERVING_TIERS, ServingTiersConfig
from distributed_vgg_f_tpu.serving.engine import PredictEngine

#: Router vocabulary, descending fidelity (mirrors config.SERVING_TIERS;
#: telemetry/schema.py keeps its own literal by the leaf-module contract).
TIERS = SERVING_TIERS

#: The FC head stack the int8 tier quantizes (CNN-F naming, models/vggf.py
#: — the int8 builder refuses architectures without it).
_HEAD_LAYERS = ("fc6", "fc7", "fc8")


# --------------------------------------------------------------------- bf16
def _cast_tree(tree, dtype):
    import jax
    import jax.numpy as jnp
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype) if np.dtype(a.dtype) == np.float32 else a,
        tree) if tree is not None else None


def build_bf16_engine(base: PredictEngine) -> PredictEngine:
    """The bf16 tier: clone the model at compute_dtype=bfloat16, cast the
    params once, finish the wire into bf16 — logits stay fp32 (the zoo
    models cast their outputs; the shared predict forward softmaxes f32)."""
    import jax.numpy as jnp
    model = base._model.clone(compute_dtype=jnp.bfloat16)
    return PredictEngine(
        model_name=base.model_name, model=model,
        params=_cast_tree(base._params, jnp.bfloat16),
        batch_stats=base._batch_stats,
        image_size=base.image_size, num_classes=base.num_classes,
        buckets=base.buckets, max_batch=base.buckets[-1],
        image_dtype="bfloat16", mean_rgb=base._mean, stddev_rgb=base._std,
        tier="bf16")


# --------------------------------------------------------------------- int8
@dataclasses.dataclass(frozen=True)
class CalibrationResult:
    """The committed activation-range pass: one per-tensor scale per head
    input plus the kept-channel index sets the sub-LSB elision derives
    from them. `receipt()` is the JSON the bench commits next to the
    latency rows so a re-run can reproduce the exact quantization."""
    scales: Dict[str, float]          # head layer -> activation LSB a
    keep: Dict[str, np.ndarray]       # head layer -> kept input channels
    widths: Dict[str, int]            # head layer -> dense input width
    batches: int
    batch_size: int
    seed: int

    def receipt(self) -> dict:
        return {"scales": {k: float(v) for k, v in self.scales.items()},
                "kept": {k: int(len(v)) for k, v in self.keep.items()},
                "widths": {k: int(v) for k, v in self.widths.items()},
                "batches": self.batches, "batch_size": self.batch_size,
                "seed": self.seed}


def calibration_images(image_size: int, *, batches: int, batch_size: int,
                       seed: int) -> np.ndarray:
    """Deterministic u8-wire calibration stream. Drawn from the teacher
    task's procedural textures (data/teacher.py `_raw_images`) — the
    distribution the teacher-task weights actually serve — at a seeded
    index range disjoint from both train and eval splits."""
    from distributed_vgg_f_tpu.data.teacher import _raw_images
    n = batches * batch_size
    idx = np.arange(n) + (int(seed) << 16) + (1 << 24)
    raw = _raw_images(idx, image_size, base_seed=11)
    return np.clip(np.rint(raw), 0, 255).astype(np.uint8)


def _split_params(params):
    """(trunk_params, head_params) — refuses non-CNN-F head stacks."""
    p = {k: v for k, v in dict(params).items()}
    missing = [k for k in _HEAD_LAYERS if k not in p]
    if missing:
        raise ValueError(
            f"int8 tier needs the CNN-F head stack {list(_HEAD_LAYERS)}; "
            f"params are missing {missing} — only the vggf family serves "
            "this tier")
    heads = {k: p.pop(k) for k in _HEAD_LAYERS}
    return p, heads


def _make_trunk(model, trunk_variables, finish):
    """The conv trunk as a standalone function: run the model capturing
    conv5's output, then apply the SAME relu/pool/flatten the model does
    (ops imported, not duplicated). XLA dead-code-eliminates the unused
    head computation when this is jitted, so the trunk costs trunk."""
    import flax.linen as nn
    import jax.numpy as jnp
    from distributed_vgg_f_tpu.ops.pooling import maxpool_3x3s2_ceil

    def trunk(images):
        _, inter = model.apply(
            trunk_variables, finish(images), train=False,
            capture_intermediates=lambda mdl, _: mdl.name == "conv5")
        c5 = inter["intermediates"]["conv5"]["__call__"][0]
        h = maxpool_3x3s2_ceil(nn.relu(c5))
        return h.reshape((h.shape[0], -1)).astype(jnp.float32)

    return trunk


def quantize_dense(kernel: np.ndarray):
    """Per-OUT-channel symmetric int8 weight quantization:
    `scale_j = max_i |W_ij| / 127`, `Wq = clip(round(W / scale), ±127)`.
    Returns (int8 kernel, f32 per-column scales)."""
    w = np.asarray(kernel, np.float32)
    scale = np.max(np.abs(w), axis=0) / 127.0
    scale = np.where(scale > 0, scale, 1.0).astype(np.float32)
    wq = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return wq, scale


def calibrate(base: PredictEngine, images: np.ndarray, *,
              batch_size: int, seed: int) -> CalibrationResult:
    """The activation-range pass over the u8 wire: run the fp32 forward on
    the calibration stream capturing each head layer's INPUT, record the
    per-tensor max (→ the activation LSB a = max/127) and per-channel
    maxima (→ which channels stay below a/2 and therefore always quantize
    to zero — the elision set's complement)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish

    model, params = base._model, base._params
    finish = make_device_finish(base._mean, base._std)
    variables = {"params": params}
    if base._batch_stats:
        variables["batch_stats"] = base._batch_stats

    def head_inputs(imgs):
        _, inter = model.apply(
            variables, finish(imgs), train=False,
            capture_intermediates=lambda mdl, _: mdl.name in
            ("conv5",) + _HEAD_LAYERS)
        from distributed_vgg_f_tpu.ops.pooling import maxpool_3x3s2_ceil
        c5 = inter["intermediates"]["conv5"]["__call__"][0]
        x6 = maxpool_3x3s2_ceil(nn.relu(c5))
        x6 = x6.reshape((x6.shape[0], -1)).astype(jnp.float32)
        x7 = nn.relu(inter["intermediates"]["fc6"]["__call__"][0]) \
            .astype(jnp.float32)
        x8 = nn.relu(inter["intermediates"]["fc7"]["__call__"][0]) \
            .astype(jnp.float32)
        return x6, x7, x8

    fn = jax.jit(head_inputs)
    per_channel = {k: None for k in _HEAD_LAYERS}
    n = int(images.shape[0])
    batches = 0
    for i in range(0, n, batch_size):
        chunk = images[i:i + batch_size]
        if chunk.shape[0] != batch_size:
            break  # AOT discipline: one shape, one executable
        batches += 1
        for layer, x in zip(_HEAD_LAYERS, fn(chunk)):
            m = np.max(np.abs(np.asarray(x)), axis=0)
            per_channel[layer] = m if per_channel[layer] is None \
                else np.maximum(per_channel[layer], m)
    if batches == 0:
        raise ValueError(
            f"calibration stream of {n} images yields no full batch of "
            f"{batch_size}")
    scales, keep, widths = {}, {}, {}
    for layer, m in per_channel.items():
        a = float(np.max(m)) / 127.0
        if a <= 0:
            raise ValueError(
                f"calibration saw an all-zero input to {layer} — the "
                "weights are untrained garbage or the stream is empty")
        scales[layer] = a
        # channels whose calibrated range stays below half an LSB round
        # to 0 under clip(round(x / a)) — eliding them is int8-exact on
        # calibration-range data
        keep[layer] = np.flatnonzero(m >= a / 2).astype(np.int32)
        widths[layer] = int(m.size)
    return CalibrationResult(scales=scales, keep=keep, widths=widths,
                             batches=batches, batch_size=int(batch_size),
                             seed=int(seed))


def _quantized_heads(params, calib: CalibrationResult):
    """Quantize + compact the head stack. Returns (folded f32 constants
    for execution, int8 residency bytes for the HBM estimate)."""
    _, heads = _split_params(params)
    k6, k7, k8 = (calib.keep[layer] for layer in _HEAD_LAYERS)
    a6, a7, a8 = (calib.scales[layer] for layer in _HEAD_LAYERS)
    folded, int8_bytes = {}, 0
    for layer, a, rows, cols in (("fc6", a6, k6, k7), ("fc7", a7, k7, k8),
                                 ("fc8", a8, k8, None)):
        w = np.asarray(heads[layer]["kernel"], np.float32)
        b = np.asarray(heads[layer]["bias"], np.float32)
        wq, s = quantize_dense(w)
        wq = wq[rows]
        if cols is not None:
            wq, s, b = wq[:, cols], s[cols], b[cols]
        # executed form: dequantized-constant GEMM (XLA folds this once);
        # stored form: the int8 matrix + f32 scales the receipt counts
        folded[layer] = {"w": wq.astype(np.float32) * (a * s), "b": b}
        int8_bytes += wq.size + s.size * 4 + b.size * 4
    return folded, int8_bytes


def dense_int8_reference(params, calib: CalibrationResult):
    """Dense (no-elision) int8 emulation with the same scales — the
    equivalence oracle for the compacted engine (tests pin compacted ≡
    dense on calibration-range inputs)."""
    import jax.numpy as jnp
    _, heads = _split_params(params)

    def q(x, a):
        return jnp.clip(jnp.round(x / a), -127, 127)

    mats = {}
    for layer in _HEAD_LAYERS:
        wq, s = quantize_dense(np.asarray(heads[layer]["kernel"]))
        a = calib.scales[layer]
        mats[layer] = (jnp.asarray(wq.astype(np.float32) * (a * s)),
                       jnp.asarray(np.asarray(heads[layer]["bias"],
                                              np.float32)))

    def heads_fn(x):
        import jax.nn
        w, b = mats["fc6"]
        x = jax.nn.relu(q(x, calib.scales["fc6"]) @ w + b)
        w, b = mats["fc7"]
        x = jax.nn.relu(q(x, calib.scales["fc7"]) @ w + b)
        w, b = mats["fc8"]
        return q(x, calib.scales["fc8"]) @ w + b

    import jax
    return heads_fn


def build_int8_engine(base: PredictEngine,
                      calib: Optional[CalibrationResult] = None, *,
                      tiers_cfg: Optional[ServingTiersConfig] = None
                      ) -> PredictEngine:
    """The int8 tier over a base engine: calibrate (unless handed a
    committed `CalibrationResult`), quantize + compact the heads, build
    the tier forward (trunk → activation-quantized compacted GEMMs → f32
    softmax) and wrap it in a fresh AOT bucket ladder."""
    import jax
    import jax.numpy as jnp
    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish

    cfg = tiers_cfg if tiers_cfg is not None else ServingTiersConfig()
    if calib is None:
        images = calibration_images(
            base.image_size, batches=cfg.calibration_batches,
            batch_size=cfg.calibration_batch_size,
            seed=cfg.calibration_seed)
        calib = calibrate(base, images,
                          batch_size=cfg.calibration_batch_size,
                          seed=cfg.calibration_seed)
    trunk_params, _ = _split_params(base._params)
    folded, int8_bytes = _quantized_heads(base._params, calib)
    finish = make_device_finish(base._mean, base._std)
    variables = {"params": base._params}
    if base._batch_stats:
        variables["batch_stats"] = base._batch_stats
    trunk = _make_trunk(base._model, variables, finish)
    k6 = jnp.asarray(calib.keep["fc6"])
    a6, a7, a8 = (calib.scales[layer] for layer in _HEAD_LAYERS)
    w6, b6 = jnp.asarray(folded["fc6"]["w"]), jnp.asarray(folded["fc6"]["b"])
    w7, b7 = jnp.asarray(folded["fc7"]["w"]), jnp.asarray(folded["fc7"]["b"])
    w8, b8 = jnp.asarray(folded["fc8"]["w"]), jnp.asarray(folded["fc8"]["b"])

    def forward(images):
        x = trunk(images)
        q = jnp.clip(jnp.round(x / a6), -127, 127)
        x = jax.nn.relu(jnp.take(q, k6, axis=1) @ w6 + b6)
        q = jnp.clip(jnp.round(x / a7), -127, 127)
        x = jax.nn.relu(q @ w7 + b7)
        q = jnp.clip(jnp.round(x / a8), -127, 127)
        logits = q @ w8 + b8
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    eng = PredictEngine(
        model_name=base.model_name, model=base._model, params=trunk_params,
        batch_stats=base._batch_stats, image_size=base.image_size,
        num_classes=base.num_classes, buckets=base.buckets,
        max_batch=base.buckets[-1], image_dtype=base._image_dtype,
        mean_rgb=base._mean, stddev_rgb=base._std, tier="int8",
        forward=forward, extra_param_bytes=int8_bytes)
    eng.calibration = calib
    return eng


# ------------------------------------------------------------------ student
def build_student_engine(base: PredictEngine, *, student_model,
                         student_params, student_batch_stats=None
                         ) -> PredictEngine:
    """The student tier: the distilled half-width architecture serving the
    flagship's route — its own forward, its own ladder, the flagship's
    wire contract (same descriptor family, same class count)."""
    return PredictEngine(
        model_name=base.model_name, model=student_model,
        params=student_params, batch_stats=student_batch_stats,
        image_size=base.image_size, num_classes=base.num_classes,
        buckets=base.buckets, max_batch=base.buckets[-1],
        image_dtype=base._image_dtype, mean_rgb=base._mean,
        stddev_rgb=base._std, tier="student", served_by="vggf_student")


def build_tier_engines(base: PredictEngine, cfg: ServingTiersConfig, *,
                       tiers: Sequence[str] = ("bf16", "int8"),
                       calib: Optional[CalibrationResult] = None,
                       student_model=None, student_params=None,
                       student_batch_stats=None) -> Dict[str, PredictEngine]:
    """Build the requested tier ladder over one base engine. The student
    tier is included iff its distilled weights are supplied (it cannot be
    derived from the flagship's checkpoint)."""
    out: Dict[str, PredictEngine] = {}
    for tier in tiers:
        if tier == "fp32":
            continue
        if tier == "bf16":
            out[tier] = build_bf16_engine(base)
        elif tier == "int8":
            out[tier] = build_int8_engine(base, calib, tiers_cfg=cfg)
        elif tier == "student":
            continue  # handled below: needs its own weights
        else:
            raise ValueError(f"unknown tier {tier!r}; ladder is {TIERS}")
    if student_model is not None and student_params is not None:
        out["student"] = build_student_engine(
            base, student_model=student_model, student_params=student_params,
            student_batch_stats=student_batch_stats)
    return out
