"""Per-model predict compute plane — AOT-lowered executables per batch
bucket (r17).

A dynamic batcher hands this engine variable-size groups of u8 images; the
engine pads each group to the nearest batch BUCKET, runs that bucket's
ahead-of-time-compiled executable, and slices the real rows back out. The
bucket set is the whole compile surface: a persistent server must never
trip a fresh XLA compile on a novel batch size mid-traffic (the latency
cliff would read as an outage), so every admissible shape is lowered and
compiled up front (`warmup`) or, at the latest, on its first use.

Parity is STRUCTURAL, not re-verified: the forward comes from
`train/predict.build_forward` — the one place the predict math (variables
assembly, device-finish prologue, f32 softmax) lives — so the server and
the offline `run_predict` array path share one implementation, and the
bitwise-equality gate in tests/test_serving.py checks the batching
machinery, not a second copy of the model call.

Pad rows are uint8 zeros and their results are DISCARDED by the slice.
XLA does not promise bitwise row-independence across batch geometries
(measured: vggf/vit differ at ~1e-8 between batch-3 and batch-4 runs on
CPU), which is exactly why the offline array path routes through THIS
engine with the same buckets: equal inputs through equal geometry are
equal bits; cross-geometry agreement is only ever a tolerance claim.

Per-model routing metadata rides the `IngestDescriptor` table
(models/ingest.py): the descriptor names the wire (u8 for the whole zoo),
the stem contract, and the normalize constants a from-table engine uses —
one server fronts the whole zoo by holding one engine per descriptor row.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from distributed_vgg_f_tpu.models.ingest import (IngestDescriptor,
                                                 ingest_descriptor)


#: The admissible batch shapes — THE single ladder implementation lives
#: next to its config surface (config.resolve_serving_buckets); this
#: module re-exports it so engine callers and tests keep one import site.
from distributed_vgg_f_tpu.config import \
    resolve_serving_buckets as resolve_buckets  # noqa: E402
from distributed_vgg_f_tpu.config import SERVING_TIERS  # noqa: E402


def _tree_bytes(tree) -> int:
    """Parameter-residency bytes of a pytree at its STORAGE dtypes — the
    per-tier HBM-estimate building block (ladder build cost on /servingz)."""
    if tree is None:
        return 0
    import jax
    return sum(int(np.asarray(a).size) * np.dtype(a.dtype).itemsize
               for a in jax.tree_util.tree_leaves(tree))


class PredictEngine:
    """One model's serving executables + routing metadata (since r23: one
    (model, tier) pair's — tier variants are separate engines behind the
    same router, each with its own AOT bucket ladder)."""

    def __init__(self, *, model_name: str, model, params, batch_stats,
                 image_size: int, num_classes: int,
                 buckets: Sequence[int] = (), max_batch: int = 32,
                 image_dtype: str = "float32",
                 mean_rgb: Optional[Sequence[float]] = None,
                 stddev_rgb: Optional[Sequence[float]] = None,
                 tier: str = "fp32",
                 served_by: Optional[str] = None,
                 forward: Optional[Callable] = None,
                 extra_param_bytes: int = 0):
        from distributed_vgg_f_tpu.data.device_ingest import (
            make_device_finish)
        from distributed_vgg_f_tpu.train.predict import build_forward
        if tier not in SERVING_TIERS:
            raise ValueError(f"tier {tier!r} not one of {SERVING_TIERS}")
        self.model_name = str(model_name)
        self.tier = str(tier)
        # the architecture actually answering (the student tier serves the
        # flagship's route with vggf_student weights)
        self.served_by = str(served_by) if served_by else self.model_name
        self.descriptor: IngestDescriptor = ingest_descriptor(model_name)
        self.image_size = int(image_size)
        self.num_classes = int(num_classes)
        self.buckets = resolve_buckets(buckets, max_batch)
        # normalize constants: the caller's (the trained checkpoint's data
        # config) when given, the descriptor's otherwise — the zoo pins the
        # two equal, and a from-table engine has only the descriptor
        mean = tuple(mean_rgb if mean_rgb is not None
                     else self.descriptor.mean_rgb)
        std = tuple(stddev_rgb if stddev_rgb is not None
                    else self.descriptor.stddev_rgb)
        # retained so serving/tiers.py can derive bf16/int8 variants from a
        # base engine without re-restoring the checkpoint
        self._model, self._params, self._batch_stats = model, params, \
            batch_stats
        self._image_dtype, self._mean, self._std = image_dtype, mean, std
        # predict convention: batches stay (S, S, 3) — the stem relayouts
        # itself where it wants the packed layout (models/vggf.py accepts
        # both), so the serving wire never ships packed pixels
        finish = make_device_finish(mean, std, image_dtype=image_dtype)
        # a tier builder may hand a pre-built forward (the int8 quantized
        # heads); the default is THE shared predict forward — structural
        # parity per tier means each tier is bitwise-equal to ITS OWN
        # offline forward, through these same executables
        self._forward = forward if forward is not None else build_forward(
            model, params, batch_stats, finish)
        self._compiled: Dict[int, object] = {}
        self._compile_lock = threading.Lock()
        # per-bucket AOT build cost, filled as buckets compile — the start
        # record / /servingz ladder-build receipt (r23 satellite: the
        # warmup window used to be invisible to the flight recorder)
        self.compile_log: Dict[int, float] = {}
        self._hbm_params_bytes = _tree_bytes(params) \
            + _tree_bytes(batch_stats) + int(extra_param_bytes)

    @property
    def hbm_estimate_bytes(self) -> int:
        """Analytic serving-residency lower bound: parameters at their
        storage dtypes plus the top bucket's wire-in/probs-out buffers."""
        top = self.buckets[-1]
        io = top * (self.image_size * self.image_size * 3 * 4  # f32 finish
                    + self.image_size * self.image_size * 3    # u8 wire
                    + self.num_classes * 4)                    # f32 probs
        return self._hbm_params_bytes + io

    # ----------------------------------------------------------- executables
    def _spec(self, bucket: int):
        import jax
        import jax.numpy as jnp
        return jax.ShapeDtypeStruct(
            (bucket, self.image_size, self.image_size, 3), jnp.uint8)

    def executable(self, bucket: int):
        """The bucket's compiled executable (AOT `lower().compile()`, cached
        for the engine lifetime — the whole point is that steady-state
        serving never compiles)."""
        exe = self._compiled.get(bucket)
        if exe is not None:
            return exe
        if bucket not in self.buckets:
            raise ValueError(f"batch {bucket} is not one of this engine's "
                             f"buckets {list(self.buckets)}")
        import jax
        with self._compile_lock:
            exe = self._compiled.get(bucket)
            if exe is None:
                t0 = time.monotonic()
                exe = jax.jit(self._forward).lower(
                    self._spec(bucket)).compile()
                self.compile_log[bucket] = round(time.monotonic() - t0, 4)
                self._compiled[bucket] = exe
        return exe

    def warmup(self) -> int:
        """Compile every bucket now (server start), so the first request of
        any shape pays dispatch, not XLA. Returns the bucket count."""
        for b in self.buckets:
            self.executable(b)
        return len(self.buckets)

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that fits a group of n."""
        if n < 1:
            raise ValueError(f"empty batch (n={n})")
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"group of {n} exceeds the top bucket "
                         f"{self.buckets[-1]} — the batcher's max_batch "
                         "must not exceed it")

    # ------------------------------------------------------------------- run
    def validate_payload(self, arr: np.ndarray) -> None:
        """One request image: uint8 (S, S, 3) — raw resampled pixels, the
        u8 wire contract; anything else is a 400, not a crash."""
        expect = (self.image_size, self.image_size, 3)
        if arr.dtype != np.uint8 or tuple(arr.shape) != expect:
            raise ValueError(
                f"payload must be uint8 {expect} (raw resampled pixels on "
                f"the u8 wire), got {arr.dtype} {tuple(arr.shape)}")

    def run(self, images: np.ndarray) -> Tuple[np.ndarray, int]:
        """(probs[n, num_classes] float32, bucket) for a u8 group of n —
        pad to the nearest bucket, run its executable, slice the real rows
        back. The pad region's outputs never leave this function."""
        n = int(images.shape[0])
        bucket = self.bucket_for(n)
        if bucket != n:
            padded = np.zeros((bucket,) + tuple(images.shape[1:]), np.uint8)
            padded[:n] = images
        else:
            padded = np.ascontiguousarray(images, np.uint8)
        probs = np.asarray(self.executable(bucket)(padded))[:n]
        return probs, bucket

    # -------------------------------------------------------------- receipts
    def describe(self) -> dict:
        """Routing-table row for /servingz and GET /v1/models."""
        return {"model": self.model_name,
                "tier": self.tier,
                "served_by": self.served_by,
                "image_size": self.image_size,
                "num_classes": self.num_classes,
                "buckets": list(self.buckets),
                "payload_bytes": self.image_size * self.image_size * 3,
                "compiled_buckets": sorted(self._compiled),
                "compile_s": {str(b): s
                              for b, s in sorted(self.compile_log.items())},
                "hbm_estimate_bytes": self.hbm_estimate_bytes,
                "ingest": self.descriptor.describe()}

    # ---------------------------------------------------------- construction
    @classmethod
    def from_trainer(cls, trainer, *, buckets: Sequence[int] = (),
                     max_batch: Optional[int] = None) -> "PredictEngine":
        """Engine over the trainer's latest checkpoint — the same restore +
        EMA-selection path `run_predict` uses (train/predict.py
        restore_predict_params), so server and offline predictions come
        from identical weights."""
        from distributed_vgg_f_tpu.train.predict import restore_predict_params
        cfg = trainer.cfg
        params, batch_stats = restore_predict_params(trainer)
        serving = getattr(cfg, "serving", None)
        if max_batch is None:
            max_batch = serving.max_batch if serving is not None else 32
        if not buckets and serving is not None:
            buckets = serving.buckets
        return cls(model_name=cfg.model.name, model=trainer.model,
                   params=params, batch_stats=batch_stats,
                   image_size=cfg.data.image_size,
                   num_classes=cfg.model.num_classes,
                   buckets=buckets, max_batch=max_batch,
                   image_dtype=cfg.data.image_dtype,
                   mean_rgb=cfg.data.mean_rgb,
                   stddev_rgb=cfg.data.stddev_rgb)
