"""Dynamic batcher — bounded admission, max-latency + max-batch flush,
shed-not-collapse overload behavior (r17).

The serving latency/throughput trade is the admission WINDOW: a request
admitted while a batch is forming waits at most `window_ms` for company
(amortizing dispatch + winning the bucket's throughput), and a burst that
fills `max_batch` flushes immediately without waiting the window out. Both
flush conditions are tested from the OLDEST queued request, so the window
is a per-request latency bound, not a server-side poll interval.

Overload contract: the admission queue is BOUNDED (`queue_limit`). A full
queue rejects the new arrival with `OverloadShed` — the HTTP layer turns
that into a typed 503 the client can back off on — instead of queueing
unboundedly, where every admitted request's latency grows without limit
and the server "works" while serving nothing within its SLO (the collapse
mode the TF-system serving split, arXiv 1605.08695, designs against).
Shedding the NEWEST arrival keeps the bound O(1) and keeps already-made
admission promises: everything in the queue still meets window + queue/
throughput latency, which is what "p99 of admitted requests stays within
budget while shed rate rises" in the acceptance receipt means.

Shutdown drains: `close()` stops admission (new arrivals shed with
``kind="draining"``) but the flush loop keeps flushing until the queue is
empty — every in-flight request is answered, pinned in tests/test_serving.

The admission window is the controller's knob (`window_ms` /
`set_window_ms` — the same get/apply surface data/autotune.Knob binds);
`window_stats()` hands the controller its per-window evidence (sheds,
admitted, queue peak, completed latencies) with delta semantics.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from distributed_vgg_f_tpu import telemetry

#: Latencies retained between controller polls; a poll drains the ring, so
#: this bounds memory only when no controller runs.
_LATENCY_RING = 8192


class OverloadShed(RuntimeError):
    """Admission refused — bounded queue full (kind="shed") or the server
    is draining (kind="draining"). Carries the typed-503 payload fields."""

    def __init__(self, kind: str, queue_depth: int, queue_limit: int):
        super().__init__(f"admission refused ({kind}): queue "
                         f"{queue_depth}/{queue_limit}")
        self.kind = kind
        self.queue_depth = int(queue_depth)
        self.queue_limit = int(queue_limit)


class _Pending:
    """One admitted request riding the queue."""

    __slots__ = ("image", "event", "probs", "error", "bucket",
                 "t_submit", "t_done", "trace_id")

    def __init__(self, image: np.ndarray,
                 trace_id: Optional[str] = None):
        self.image = image
        self.event = threading.Event()
        self.probs: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.bucket: Optional[int] = None
        self.t_submit = time.monotonic()
        self.t_done: Optional[float] = None
        # cross-process correlation id (X-DVGGF-Trace-Id): carried onto
        # the flush span so stitch links the request to its batch
        self.trace_id = trace_id

    @property
    def latency_ms(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return (self.t_done - self.t_submit) * 1e3


class DynamicBatcher:
    """Bounded admission queue + one flush thread over a PredictEngine."""

    def __init__(self, engine, *, max_batch: int, window_ms: float,
                 queue_limit: int, reap_after_s: Optional[float] = None,
                 registry=None):
        if int(max_batch) > engine.buckets[-1]:
            raise ValueError(
                f"max_batch={max_batch} exceeds the engine's top bucket "
                f"{engine.buckets[-1]}")
        self.engine = engine
        self.max_batch = int(max_batch)
        self.queue_limit = int(queue_limit)
        # reap horizon: a queued request older than this is EXPIRED at
        # group-formation time (error=TimeoutError, never run) — its
        # client already got the 504, and spending engine time on it
        # under sustained overload is the collapse mode (100% compute on
        # requests nobody is waiting for) the bounded queue exists to
        # prevent. None = never reap (direct-submit callers own waiting).
        self.reap_after_s = None if reap_after_s is None \
            else float(reap_after_s)
        self._window_ms = max(1, int(round(window_ms)))
        self._reg = registry if registry is not None \
            else telemetry.get_registry()
        self._cond = threading.Condition()
        self._q: deque = deque()
        self._closed = False
        self._drained = threading.Event()
        # controller-facing evidence (cumulative; window_stats deltas them)
        self._shed_total = 0
        self._admitted_total = 0
        self._completed_total = 0
        self._reaped_total = 0
        self._queue_peak = 0        # since the last controller poll
        self._queue_peak_life = 0   # lifetime: the bounded-queue receipt
        self._latencies: deque = deque(maxlen=_LATENCY_RING)
        self._bucket_counts: Dict[int, int] = {}
        self._prev = {"shed": 0, "admitted": 0, "completed": 0}
        # the thread name carries the full routing key — one batcher per
        # (model, tier) since r23, and a stack dump must say which
        tier = str(getattr(engine, "tier", "fp32"))
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"serving-batcher-{engine.model_name}-{tier}")
        self._thread.start()

    # ------------------------------------------------------------ knob surface
    @property
    def window_ms(self) -> int:
        return self._window_ms

    def set_window_ms(self, ms: int) -> Optional[int]:
        """Admission-window setter — the controller's apply() hook (returns
        the now-active value, the data/autotune.Knob contract)."""
        with self._cond:
            self._window_ms = max(1, int(ms))
            self._cond.notify_all()
            return self._window_ms

    # --------------------------------------------------------------- admission
    def submit(self, image: np.ndarray,
               trace_id: Optional[str] = None) -> _Pending:
        """Admit one request or shed it. Raises OverloadShed on a full
        queue / draining server; the caller owns turning that into a 503."""
        with self._cond:
            if self._closed:
                self._shed_total += 1
                self._reg.inc("serving/shed")
                raise OverloadShed("draining", len(self._q),
                                   self.queue_limit)
            if len(self._q) >= self.queue_limit:
                self._shed_total += 1
                self._reg.inc("serving/shed")
                raise OverloadShed("shed", len(self._q), self.queue_limit)
            pending = _Pending(image, trace_id)
            self._q.append(pending)
            self._admitted_total += 1
            self._reg.inc("serving/admitted")
            # gauges are owned by the server's housekeeping loop (summed
            # across models there — two batchers writing one
            # process-global gauge would be last-writer-wins garbage)
            depth = len(self._q)
            if depth > self._queue_peak:
                self._queue_peak = depth
            if depth > self._queue_peak_life:
                self._queue_peak_life = depth
            self._cond.notify_all()
        return pending

    @property
    def queue_depth(self) -> int:
        with self._cond:
            return len(self._q)

    # -------------------------------------------------------------- flush loop
    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait()
                if not self._q and self._closed:
                    self._drained.set()
                    return
                self._reap_expired_locked()
                if not self._q:
                    continue
                if not self._closed:
                    # window from the OLDEST queued request: flush when the
                    # batch fills OR its wait hits the admission window —
                    # whichever first. Draining skips the wait entirely.
                    # The deadline is recomputed each wakeup so a
                    # controller set_window_ms lands on the CURRENT batch
                    # (its notify_all wakes this wait exactly for that).
                    head = self._q[0].t_submit
                    while len(self._q) < self.max_batch \
                            and not self._closed:
                        remaining = head + self._window_ms / 1e3 \
                            - time.monotonic()
                        if remaining <= 0:
                            break
                        self._cond.wait(remaining)
                self._reap_expired_locked()
                group = [self._q.popleft()
                         for _ in range(min(len(self._q), self.max_batch))]
            if group:
                self._flush(group)

    def _reap_expired_locked(self) -> None:
        """Expire queue-head requests older than the reap horizon (their
        clients already received 504) instead of burning engine time on
        them — the oldest sit at the head, so this is O(expired)."""
        if self.reap_after_s is None:
            return
        now = time.monotonic()
        while self._q and now - self._q[0].t_submit > self.reap_after_s:
            p = self._q.popleft()
            p.error = TimeoutError(
                f"expired in the admission queue after "
                f"{now - p.t_submit:.1f}s (> reap_after_s="
                f"{self.reap_after_s})")
            p.t_done = now
            self._reaped_total += 1
            p.event.set()

    def _flush(self, group: List[_Pending]) -> None:
        images = np.stack([p.image for p in group])
        # the requests' correlation ids ride the flush span's args
        # (`trace_ids` — one batched span serves many requests, each id an
        # inbound flow edge for telemetry/stitch.py)
        ids = [p.trace_id for p in group if p.trace_id]
        t0_ns = time.monotonic_ns()
        try:
            # a span per flush: serving execution shows up on /trace and
            # in the span-occupancy window summaries like any other
            # dispatch-category work
            probs, bucket = self.engine.run(images)
        except BaseException as e:  # noqa: BLE001 — answer, don't die
            self._reg.inc("serving/errors")
            for p in group:
                p.error = e
                p.t_done = time.monotonic()
                p.event.set()
            return
        telemetry.record(
            f"serving_flush_{self.engine.model_name}", "dispatch",
            t0_ns, time.monotonic_ns() - t0_ns,
            {"trace_ids": ids, "flow": "in"} if ids else None)
        n = len(group)
        self._reg.inc("serving/batches")
        self._reg.inc("serving/batch_images", n)
        self._reg.inc("serving/padded_images", bucket - n)
        t_done = time.monotonic()
        with self._cond:
            self._bucket_counts[bucket] = \
                self._bucket_counts.get(bucket, 0) + 1
            self._completed_total += n
            # latencies recorded under the SAME lock window_stats drains
            # them with — an unlocked append racing list()+clear() would
            # silently drop exactly the loaded-tail samples the quantile
            # gauges exist to show
            for p in group:
                self._latencies.append((t_done - p.t_submit) * 1e3)
        for i, p in enumerate(group):
            p.probs = probs[i]
            p.bucket = bucket
            p.t_done = t_done
            p.event.set()

    # -------------------------------------------------------------- controller
    def window_stats(self) -> dict:
        """Evidence since the previous poll: shed/admitted/completed deltas,
        queue peak (reset), and the completed latencies drained from the
        ring — the controller's verdict inputs."""
        with self._cond:
            shed = self._shed_total - self._prev["shed"]
            admitted = self._admitted_total - self._prev["admitted"]
            completed = self._completed_total - self._prev["completed"]
            self._prev = {"shed": self._shed_total,
                          "admitted": self._admitted_total,
                          "completed": self._completed_total}
            peak, self._queue_peak = self._queue_peak, len(self._q)
            lat = list(self._latencies)
            self._latencies.clear()
            depth = len(self._q)
        return {"shed": shed, "admitted": admitted, "completed": completed,
                "queue_peak": peak, "queue_depth": depth,
                "latencies_ms": lat}

    def describe(self) -> dict:
        """/servingz row: live admission state + lifetime totals."""
        with self._cond:
            return {"tier": str(getattr(self.engine, "tier", "fp32")),
                    "queue_depth": len(self._q),
                    "queue_peak": self._queue_peak_life,
                    "queue_limit": self.queue_limit,
                    "window_ms": self._window_ms,
                    "max_batch": self.max_batch,
                    "admitted_total": self._admitted_total,
                    "shed_total": self._shed_total,
                    "completed_total": self._completed_total,
                    "reaped_total": self._reaped_total,
                    "bucket_occupancy": {str(k): v for k, v in
                                         sorted(self._bucket_counts.items())},
                    "draining": self._closed}

    # ------------------------------------------------------------------ close
    def close(self, timeout: float = 30.0) -> None:
        """Stop admission, flush everything still queued, join the loop —
        every in-flight request is answered before this returns."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._drained.wait(timeout)
        self._thread.join(timeout)
