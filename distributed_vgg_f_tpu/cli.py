"""Console entry point (`dvggf-train`, also `python train.py`) — the
reference's `python train.py --flags` CLI surface (SURVEY.md §1), packaged
so an installed framework exposes the same commands as the checkout:

    dvggf-train --config vggf_cifar10_smoke --set train.steps=100
    dvggf-train --mode eval --config vggf_imagenet_dp \
        --set train.checkpoint_dir=/ckpts
    dvggf-train --config vggf_imagenet_dp --set data.wire=u8  # uint8 ingest
        # wire: ship raw resampled pixels, finish normalize/cast/space-to-
        # depth on device (data/device_ingest.py; falls back to the host
        # wire with a logged warning when the native u8 path is refused)
    dvggf-train --mode serve --config vggf_imagenet_dp \
        --set train.checkpoint_dir=/ckpts --set serving.enabled=true
        # always-on dynamic-batching predict server (serving/, r17): u8
        # payloads over HTTP, bounded admission + typed-503 shed; prints
        # "serving on host:port" (port-0 contract) and runs until SIGINT
"""

from __future__ import annotations

import sys


def main(argv=None) -> None:
    from distributed_vgg_f_tpu.config import parse_cli
    from distributed_vgg_f_tpu.train.trainer import Trainer
    from distributed_vgg_f_tpu.utils.logging import MetricLogger

    cfg, args = parse_cli(argv, with_mode=True)
    mode = args.mode
    # Context-managed logger: a crashing run still flushes/closes the JSONL
    # stream and the TB writer exactly once, so the on-disk record archive
    # is complete up to the failure.
    with MetricLogger(jsonl_path=(f"{cfg.train.checkpoint_dir}/metrics.jsonl"
                                  if cfg.train.checkpoint_dir else None),
                      tensorboard_dir=cfg.train.tensorboard_dir
                      or None) as logger:
        trainer = Trainer(cfg, logger=logger)

        def require_checkpoint():
            # eval/predict must fail loudly rather than silently score random
            # weights (run_predict also guards internally for library callers)
            if trainer.checkpoints is None or \
                    trainer.checkpoints.latest_step() is None:
                raise SystemExit(
                    f"{mode} mode: no checkpoint found under "
                    f"{cfg.train.checkpoint_dir!r} (set train.checkpoint_dir "
                    "to a directory containing checkpoints)")

        if mode == "serve":
            # explicit double opt-in (kill-switch discipline): the mode
            # names the intent, the config flag arms the subsystem — a
            # preset with serving off must never start listening because
            # of a mistyped --mode
            if not cfg.serving.enabled:
                raise SystemExit(
                    "serve mode: serving is disabled — pass "
                    "--set serving.enabled=true (the server is off by "
                    "default; see README 'Serving')")
            from distributed_vgg_f_tpu.serving.server import (
                serve_from_trainer)
            require_checkpoint()
            server = serve_from_trainer(trainer)
            # launchers scrape this line for the bound port (the port-0
            # contract, same as the exporter sidecar and ingest workers)
            print(f"serving on {server.endpoint}", flush=True)
            try:
                server.wait()
            except KeyboardInterrupt:
                pass
            except BaseException as e:
                # a serving crash leaves the same black box a trainer
                # crash does — the ring already holds the admission
                # windows and controller actuations triage needs
                trainer.dump_flight_black_box(exc=e)
                raise
            finally:
                server.close()
                trainer.export_telemetry()
            return
        if mode == "predict":
            from distributed_vgg_f_tpu.train.predict import run_predict
            require_checkpoint()
            if not args.images:
                raise SystemExit("predict mode: pass --images <files/dirs>")
            # finally: like fit(), crashing standalone modes still export —
            # the telemetry of a failed pass is the diagnosis material
            try:
                run_predict(trainer, args.images)
            finally:
                trainer.export_telemetry()
            return
        if mode == "eval":
            # Standalone validation (SURVEY.md §3.4): restore latest
            # checkpoint, run the full held-out split, report top-1/top-5.
            require_checkpoint()
            try:
                trainer.evaluate(trainer.restore_or_init(),
                                 trainer.make_dataset("eval"))
            finally:
                trainer.export_telemetry()
            return
        eval_ds = None
        try:
            eval_ds = trainer.make_dataset("eval")
        except (FileNotFoundError, NotADirectoryError, ValueError) as e:
            # train-mode eval cadence is best-effort (e.g. no data_dir yet) —
            # but say so, and let anything unexpected propagate.
            logger.log("eval_dataset_unavailable", {"error": repr(e)})
        trainer.fit(eval_dataset=eval_ds)



if __name__ == "__main__":
    main(sys.argv[1:])
