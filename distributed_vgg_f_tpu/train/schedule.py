"""Learning-rate schedules.

Reference: SGD-momentum with step-LR decay (BASELINE.json north_star; SURVEY.md
§2.1 #4). Built as optax schedules evaluated *inside* the jitted step from the
step counter, so LR decay costs nothing and checkpoint-resume reproduces the
schedule position automatically (SURVEY.md §5 checkpoint/resume)."""

from __future__ import annotations

import optax

from distributed_vgg_f_tpu.config import ExperimentConfig


def build_schedule(cfg: ExperimentConfig) -> optax.Schedule:
    peak_lr = cfg.scaled_lr
    spe = cfg.steps_per_epoch
    warmup_steps = int(cfg.optim.warmup_epochs * spe)

    if cfg.optim.schedule == "constant":
        main = optax.constant_schedule(peak_lr)
    elif cfg.optim.schedule == "step":
        boundaries_and_scales = {
            int(e * spe): cfg.optim.decay_factor for e in cfg.optim.decay_epochs
        }
        main = optax.piecewise_constant_schedule(peak_lr, boundaries_and_scales)
    elif cfg.optim.schedule == "cosine":
        decay_steps = max(1, cfg.total_steps - warmup_steps)
        main = optax.cosine_decay_schedule(peak_lr, decay_steps)
    else:
        raise ValueError(f"unknown schedule {cfg.optim.schedule!r}")

    if warmup_steps > 0:
        warmup = optax.linear_schedule(0.0, peak_lr, warmup_steps)
        return optax.join_schedules([warmup, main], [warmup_steps])
    return main


def build_optimizer(cfg: ExperimentConfig, *,
                    lr_scale: float = 1.0) -> tuple:
    """SGD with momentum on the schedule. Weight decay is L2-in-loss
    (ops/losses.py), NOT added here — coupled-through-momentum TF semantics
    (SURVEY.md §7 hard parts).

    Gradient clipping is deliberately NOT in this chain: under ZeRO-1 the
    transform sees only this replica's 1/N gradient shard, so a chained
    `clip_by_global_norm` would clip by the *shard* norm. The train step owns
    global-norm clipping for both layouts (train/step.py, `grad_clip_norm`).

    `lr_scale` (r19, parallel/elastic.py `scale_lr` batch policy): a
    multiplicative factor over the WHOLE schedule — the linear-scaling rule
    for a mid-run global-batch change. Applied as a wrapping schedule, so
    the optimizer chain (and therefore the opt-state STRUCTURE the elastic
    reshard converts through) is identical to lr_scale=1.0."""
    schedule = build_schedule(cfg)
    if lr_scale != 1.0:
        base, factor = schedule, float(lr_scale)
        schedule = lambda step: base(step) * factor  # noqa: E731
    return optax.sgd(learning_rate=schedule,
                     momentum=cfg.optim.momentum,
                     nesterov=cfg.optim.nesterov), schedule
