"""The jitted SPMD train/eval steps — the heart of the framework.

Reference call stack (SURVEY.md §3.1): fetch → forward → loss(+wd) → backward →
[SYNC] ring all-reduce(grads) over NCCL/MPI → SGD-momentum apply → step-LR decay.

TPU-native design: the *entire* chain from forward through optimizer apply —
including the gradient all-reduce — is ONE XLA computation, built with
`shard_map` over the device mesh so the cross-replica `lax.pmean` is explicit in
user code (mirroring the reference's visible sync point) while XLA schedules the
ICI all-reduce and overlaps it with backward compute. The Python loop only feeds
batches and reads metrics (BASELINE.json north_star).
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Mapping, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

# check_vma-kwarg-translating shim over jax.shard_map /
# jax.experimental.shard_map (parallel/compat.py)
from distributed_vgg_f_tpu.parallel.compat import shard_map

from distributed_vgg_f_tpu.ops.losses import l2_regularization, softmax_cross_entropy
from distributed_vgg_f_tpu.ops.metrics import topk_correct
from distributed_vgg_f_tpu.parallel.collectives import (
    all_reduce_gradients,
    cross_replica_mean,
    cross_replica_sum,
    fold_rng_per_replica,
)
from distributed_vgg_f_tpu.parallel.zero import padded_flat_size
from distributed_vgg_f_tpu.train.state import TrainState

Batch = Mapping[str, jnp.ndarray]


def _clip_by_global_norm(tree, grad_norm, clip_norm):
    """Scale a gradient pytree so its global norm is at most `clip_norm`.
    Shared by both layouts so the replicated and ZeRO-1 paths cannot drift."""
    scale = jnp.minimum(1.0, clip_norm / (grad_norm + 1e-12))
    return jax.tree.map(lambda g: g * scale, tree)


def _apply_model(model, params, batch_stats, images, *, train: bool,
                 dropout_rng=None):
    """Run the model, handling mutable BN state uniformly for all models."""
    variables = {"params": params}
    has_bn = bool(batch_stats)
    if has_bn:
        variables["batch_stats"] = batch_stats
    rngs = {"dropout": dropout_rng} if (train and dropout_rng is not None) else None
    if train and has_bn:
        logits, new_vars = model.apply(variables, images, train=True, rngs=rngs,
                                       mutable=["batch_stats"])
        return logits, new_vars["batch_stats"]
    logits = model.apply(variables, images, train=train, rngs=rngs)
    return logits, batch_stats


def build_train_step(model, tx: optax.GradientTransformation, mesh: Mesh,
                     weight_decay: float,
                     schedule: optax.Schedule | None = None,
                     data_axis: str = "data",
                     zero1: bool = False,
                     state_specs=None,
                     grad_clip_norm: float = 0.0,
                     grad_accum_steps: int = 1,
                     grad_accum_shard: bool = False,
                     shard_gradients: bool = False,
                     shard_params: bool = False,
                     params_struct=None,
                     comm_bucket_mb: float = 0.0,
                     ema_decay: float = 0.0,
                     reduce_dtype: str = "float32",
                     skip_nonfinite: bool = False,
                     device_finish: Callable | None = None,
                     device_augment: Callable | None = None,
                     ) -> Callable[[TrainState, Batch, jax.Array],
                                   Tuple[TrainState, Mapping[str, jnp.ndarray]]]:
    """Returns jitted `train_step(state, batch, base_rng) -> (state, metrics)`.

    - `state` and `base_rng` are replicated across the mesh; `batch` is sharded on
      its leading dim over the data axis.
    - Per-replica dropout keys are derived with `fold_in(axis_index)`
      (SURVEY.md §7 hard parts).
    - Plain DP (`zero1=False`): gradients are `pmean`-all-reduced before the optax
      update, so every replica applies the identical update — synchronous
      replicated SGD, the reference's semantics (SURVEY.md §2.4).
    - `zero1=True`: optimizer-state sharding (parallel/zero.py) — gradients are
      reduce-SCATTERED (`psum_scatter`), the optimizer updates only this
      replica's 1/N flat shard against the sharded opt state, and the updated
      parameter shards are all-gathered. `state_specs` must then be the
      PartitionSpec tree from `zero.train_state_specs`.
    - `grad_accum_steps=k>1`: the per-device batch is split into k
      micro-batches folded through ONE `lax.scan` — only one micro-batch's
      activations are ever live, trading k× step latency for 1/k activation
      memory at an UNCHANGED optimizer batch (the logical global batch, LR
      schedule, and gradient sync point all stay identical; for BN-free
      models the summed micro-gradients equal the big-batch gradient
      exactly, tested). Gradients accumulate in the scan carry (O(params),
      never k×); dropout keys fold per micro-batch; BN batch stats update
      sequentially per micro-batch (the standard accumulation semantics).
      The cross-replica all-reduce still happens ONCE, on the accumulated
      gradient — accumulation also divides collective bandwidth per sample.
    - `grad_accum_shard=True` (requires BOTH of the above): the ZeRO-2-
      flavored composition — each micro-gradient is reduce-scattered
      INSIDE the scan and only this replica's 1/N flat shard accumulates
      in the carry, so the persistent accumulator is O(params/N) instead
      of O(params) (the transient per-micro-batch gradient still
      materializes, as in any backward pass). Cost: k scatter legs per
      step instead of one — k× the scatter-leg wire bytes, the explicit
      memory-for-bandwidth trade. The update it computes is the same mean
      gradient (scatter-then-sum == sum-then-scatter up to fp summation
      order; with a bf16 wire each micro-leg rounds once, k roundings
      instead of one — both compositions tested).
    - `skip_nonfinite=True` (resilience layer): the step decides ON DEVICE
      whether loss and gradient norm are finite — both are cross-replica-
      reduced values, so a NaN/inf on ANY replica propagates to every
      replica and all replicas take the identical keep/skip select — and on
      a bad step keeps params/opt-state/BN/EMA bit-identical while still
      advancing the step counter (the data stream stays aligned with the
      loop index). Note the schedule split this implies: the OPTIMIZER's
      schedule position lives in the reverted opt_state, so skipped steps
      deliberately do not consume warmup/decay (a diverging phase must not
      burn the warmup); `metrics["lr"]` reads `schedule(state.step)` and
      therefore runs ahead of the applied LR by the number of skips so far
      (bounded by the guard's abort threshold for consecutive streaks).
      The verdict is reported as the `bad_step` metric (0/1) for the
      host-side NonFiniteGuard; cost is one `where` per state leaf,
      nothing cross-replica beyond what the step already reduces.
    - `shard_gradients=True` (requires `zero1`): ZeRO-2 — gradient state is
      held ONLY as this replica's 1/N flat shard. At `grad_accum_steps=1`
      the (bucketed) reduce-scatter consumes each bucket's transient
      gradients directly, so no persistent full-gradient buffer exists; at
      `grad_accum_steps>1` the scan accumulator is the 1/N shard (the
      `grad_accum_shard` composition, now implied — the accumulator drops
      from O(params) to O(params/N), utils/scaling_model.py
      `gradient_state_bytes_per_chip`). Grad-norm/clipping already ran on
      the sharded form under ZeRO-1 (psum of shard partials); ZeRO-2 keeps
      that exact expression.
    - `shard_params=True` (requires `shard_gradients` + `params_struct`):
      ZeRO-3 — `state.params` (and `state.ema_params`) are held ONLY as
      this replica's 1/N flat shard of the padded flat vector
      (bucket-major when bucketed, canonical ravel order otherwise). The
      step [SYNC] all-gathers the full param tree ONCE up front — one
      `all_gather` PER BUCKET under the bucketed exchange, each depending
      only on the step's param-shard INPUT (zero compute ancestry), so
      every gather is overlap-capable and the lowering carries gathers ==
      buckets (`hlo_overlap_report` gather witness). The gathered replica
      is a step TRANSIENT: XLA frees it after its last consumer, nothing
      downstream persists it — per-chip persistent param bytes drop to
      O(params/N) (utils/scaling_model.py `param_bytes_per_chip`). The
      gradient side is byte-for-byte the ZeRO-2 scatter; the optimizer
      updates the resident shard directly and the ZeRO-1/2 trailing
      re-sync gather DISAPPEARS (next step's just-in-time gather plays
      that role), so zero3 moves the same gather bytes per step as zero2
      — earlier in the step, and on the `mesh.reduce_dtype` wire (the
      single-sourced cast_to_wire/cast_from_wire; fp32 truth stays in the
      shard). At the default fp32 wire the gathered tree is bit-identical
      to the ZeRO-2 replicated params, so loss trajectories are EQUAL
      (tests/test_zero3.py pins the grid); a narrowed wire trades that
      strict equality for halved gather bytes — zero3 is the only basis
      where BOTH legs narrow. `grad_accum_steps>1` gathers once OUTSIDE
      the scan (the carry stays the 1/N gradient shard). Off (default):
      the ZeRO-2 step, lowered-text-identical (kill-switch pin).
    - `comm_bucket_mb>0` (parallel/buckets.py): bucketed, overlap-capable
      gradient exchange — the param tree partitions into size-targeted
      buckets in reverse-backward order and each bucket's collective
      (per-bucket pmean in plain DP, per-bucket psum_scatter under
      sharding) is emitted against ONLY that bucket's gradients, so the
      lowered HLO carries >= 2 gradient collectives with no dependency
      path to the rest of the backward — the structure XLA's
      latency-hiding scheduler overlaps (committed assertion:
      buckets.hlo_overlap_report, tests/test_comm_buckets.py,
      benchmarks/comm_overlap_bench.py). Under sharding the opt-state
      flat layout becomes bucket-major replica-interleaved
      (GradBucketLayout.to_global; checkpoint migration via
      parallel/zero.convert_opt_state + the geometry receipt in the
      checkpoint's `extra`). Unset (0) keeps the pre-r14 monolithic
      exchange and flat layout byte-for-byte — the kill-switch
      lowered-text identity is pinned.
    - `device_augment` (r13, data/augment.py): the fused on-device
      augmentation stage, applied to the post-finish batch inside the
      shard_map body off a constant fold of the per-replica train key
      (dropout stream untouched). Returns possibly-mixed images plus the
      mixup/cutmix label pairing, which the loss consumes as
      lam*CE(y) + (1-lam)*CE(y[perm]). None = structurally absent (the
      augment-off kill-switch is byte-identical to a pre-r13 step). Only
      the TRAIN step takes this — eval/predict never augment.
    """
    if state_specs is None:
        state_specs = P()
    if grad_accum_shard and not (zero1 and grad_accum_steps > 1):
        raise ValueError(
            "grad_accum_shard requires zero1 optimizer-state sharding AND "
            f"grad_accum_steps > 1 (got zero1={zero1}, "
            f"grad_accum_steps={grad_accum_steps}) — without both there is "
            "no sharded accumulator to build")
    if shard_gradients and not zero1:
        raise ValueError(
            "shard_gradients (ZeRO-2) requires zero1 optimizer-state "
            "sharding — there is no shard frame to hold gradients in")
    if shard_params:
        if not (zero1 and shard_gradients):
            raise ValueError(
                "shard_params (ZeRO-3) requires shard_gradients (ZeRO-2) — "
                "the sharding ladder is cumulative; params sharded without "
                "a sharded gradient frame would re-materialize O(params) "
                "gradient state every step")
        if params_struct is None:
            raise ValueError(
                "shard_params (ZeRO-3) requires params_struct — "
                "state.params is the flat shard, so the step cannot "
                "recover the tree geometry from it")
    # ZeRO-2 implies the sharded scan accumulator whenever a scan exists
    # (the explicit grad_accum_shard flag stays as the ZeRO-1 opt-in).
    grad_accum_shard = grad_accum_shard or (shard_gradients
                                            and grad_accum_steps > 1)
    # Bucketed exchange (parallel/buckets.py): geometry is decided at trace
    # time from the params tree — 0 keeps the monolithic pre-r14 paths.
    bucket_bytes = int(round(comm_bucket_mb * 1024 * 1024)) \
        if comm_bucket_mb else 0
    # Static per-run exchange receipt, filled at first trace (the layout
    # needs leaf shapes). Read by the trainer's per-window `comm` JSONL
    # block and the comm/* counters below.
    comm_meta: dict = {}
    num_shards = mesh.shape[data_axis]
    # mesh.reduce_dtype: wire dtype for the gradient sync only (None = the
    # gradients' own fp32). Halves collective bytes at ~16 mantissa bits of
    # gradient precision; momentum/params/param-all-gather stay fp32.
    wire_dtype = (None if reduce_dtype in ("float32", None)
                  else jnp.dtype(reduce_dtype))

    def step_fn(state: TrainState, batch: Batch, base_rng: jax.Array):
        images, labels = batch["image"], batch["label"]
        if device_finish is not None:
            # u8-wire finish (data/device_ingest.py): normalize + cast +
            # space-to-depth INSIDE the shard_map body, so XLA fuses the
            # elementwise math into the step. Dispatches on dtype — float
            # (host-normalized) batches pass through untouched, so the
            # prologue is safe to install for every wire.
            images = device_finish(images)
        rng = jax.random.fold_in(base_rng, state.step)
        rng = fold_rng_per_replica(rng, data_axis)
        # Fused on-device augmentation (r13, data/augment.py): flip/jitter/
        # photometric/mix applied to the post-finish batch INSIDE the step,
        # keyed off a constant fold of the per-replica train key — every
        # draw is reproducible from (seed, step, replica), the dropout
        # stream below is untouched, and augment-off is structurally
        # absent (device_augment=None adds zero equations — the
        # kill-switch byte-identity contract). mix_labels/mix_lam carry
        # the mixup/cutmix label pairing into the loss.
        mix_labels = mix_lam = None
        if device_augment is not None:
            from distributed_vgg_f_tpu.data.augment import AUGMENT_RNG_FOLD
            images, mix_labels, mix_lam = device_augment(
                jax.random.fold_in(rng, AUGMENT_RNG_FOLD), images, labels)

        def make_loss_fn(images, labels, mix_labels, batch_stats,
                         dropout_rng):
            def loss_fn(params):
                logits, new_batch_stats = _apply_model(
                    model, params, batch_stats, images, train=True,
                    dropout_rng=dropout_rng)
                if mix_labels is not None:
                    # mixup/cutmix with INTEGER labels: the mixed target is
                    # a two-point distribution, so its CE decomposes as the
                    # lam-weighted sum of the two integer-label CEs — no
                    # one-hot materialization.
                    ce = mix_lam * softmax_cross_entropy(logits, labels) \
                        + (1.0 - mix_lam) * softmax_cross_entropy(
                            logits, mix_labels)
                else:
                    ce = softmax_cross_entropy(logits, labels)
                l2 = l2_regularization(params, weight_decay)
                loss = ce + l2
                n = jnp.asarray(labels.shape[0], jnp.float32)
                metrics = {
                    "loss": ce,
                    # top1 scores against the PRIMARY labels (the standard
                    # mixup-training convention; eval is unaugmented anyway)
                    "l2_loss": l2,
                    "top1": topk_correct(logits, labels, 1).astype(jnp.float32) / n,
                }
                return loss, (new_batch_stats, metrics)
            return loss_fn

        # Bucketed-exchange geometry (trace-time, pure function of leaf
        # shapes — deterministic, so the trainer's separately-built layout
        # for specs/init/checkpointing can never disagree with the step's).
        # Under ZeRO-3 state.params IS the flat shard, so the tree geometry
        # comes from params_struct instead (same leaves, same layout).
        param_geom = params_struct if shard_params else state.params
        bucket_layout = None
        if bucket_bytes > 0:
            from distributed_vgg_f_tpu.parallel.buckets import (
                build_bucket_layout)
            bucket_layout = build_bucket_layout(param_geom, num_shards,
                                                bucket_bytes)

        # ZeRO flat-shard geometry — computed ONCE so the scan carry shape,
        # the scatter padding, and the param-shard slicing below can never
        # disagree (they all derive from these three numbers).
        if zero1:
            from jax.flatten_util import ravel_pytree
            n_elem = sum(x.size for x in jax.tree.leaves(param_geom))
            if bucket_layout is not None:
                shard_size = bucket_layout.shard_size
            else:
                padded = padded_flat_size(n_elem, num_shards)
                shard_size = padded // num_shards

        if not comm_meta:
            from distributed_vgg_f_tpu.parallel.buckets import (
                exchange_wire_bytes, sharding_basis)
            n_all = sum(x.size for x in jax.tree.leaves(param_geom))
            comm_meta.update({
                # the EFFECTIVE basis: zero1/shard_gradients are already
                # post-downgrade here (single source: buckets.sharding_basis)
                "sharding": sharding_basis(zero1,
                                           zero1 and shard_gradients,
                                           shard_params),
                "bucketed": bucket_layout is not None,
                "buckets": (bucket_layout.num_buckets
                            if bucket_layout is not None
                            else (1 if zero1
                                  else len(jax.tree.leaves(param_geom)))),
                "bucket_mb": float(comm_bucket_mb or 0.0),
                "reduce_dtype": reduce_dtype or "float32",
                "grad_accum_steps": grad_accum_steps,
                # all_gather collectives per step: 0 in plain DP; the single
                # trailing (S,) re-sync gather under ZeRO-1/2; one PER
                # BUCKET under bucketed ZeRO-3 (the just-in-time fetch —
                # hlo_overlap_report's `gathers` witnesses this count)
                "gathers": (0 if not zero1
                            else (bucket_layout.num_buckets
                                  if shard_params
                                  and bucket_layout is not None else 1)),
            })
            # one shared byte accounting for bucketed AND monolithic
            # (bucketing changes the schedule, never the byte totals)
            padded_total = (bucket_layout.total_padded
                            if bucket_layout is not None
                            else (padded if zero1 else 0))
            comm_meta.update(exchange_wire_bytes(
                n_all, padded_total, zero=zero1, wire_dtype=wire_dtype,
                shard_params=shard_params))
            # scatter-leg bytes scale with the scan: k micro-scatters
            if grad_accum_shard and grad_accum_steps > 1:
                comm_meta["scatter_bytes"] *= grad_accum_steps
                comm_meta["wire_bytes"] = (comm_meta["scatter_bytes"]
                                           + comm_meta["gather_bytes"])

        def scatter_mean_shard(g_tree):
            """Ravel + pad + [SYNC] reduce-scatter one gradient pytree to
            this replica's fp32 mean 1/N flat shard — PER BUCKET when the
            bucketed exchange is on (each bucket's collective consumes only
            its own gradients: the overlap-capable emission), one flat
            monolith otherwise. mesh.reduce_dtype: the scatter leg may move
            a narrower wire dtype through the single-sourced cast
            (collectives.cast_to_wire; cast back for the mean and
            everything downstream); the param all-gather below ALWAYS
            stays fp32 — replicas must re-sync exactly."""
            if bucket_layout is not None:
                return bucket_layout.scatter_mean_shards(
                    g_tree, data_axis, wire_dtype=wire_dtype)
            from distributed_vgg_f_tpu.parallel.collectives import (
                cast_from_wire, cast_to_wire)
            flat_g, _ = ravel_pytree(g_tree)
            send = cast_to_wire(jnp.pad(flat_g, (0, padded - n_elem)),
                                wire_dtype)
            return cast_from_wire(jax.lax.psum_scatter(
                send, data_axis, scatter_dimension=0,
                tiled=True), jnp.float32) / num_shards

        # ZeRO-3 just-in-time parameter gather — ONCE, up front (and OUTSIDE
        # the grad-accum scan: the scan carry stays the 1/N gradient shard;
        # re-gathering per micro-batch would move k× the gather bytes for
        # params that cannot have changed mid-step). Each bucket's
        # all_gather consumes a static slice of the step's param-shard
        # INPUT, so none has compute ancestry — the overlap license the
        # committed gather witness asserts. The gathered tree is a step
        # transient; at a fp32 wire it is bit-identical to the ZeRO-2
        # replicated params (the equality-grid pin).
        if shard_params:
            if bucket_layout is not None:
                full_params = bucket_layout.gather_param_tree(
                    state.params, data_axis, wire_dtype=wire_dtype)
            else:
                from distributed_vgg_f_tpu.parallel.collectives import (
                    cast_from_wire, cast_to_wire)
                from distributed_vgg_f_tpu.parallel.zero import (
                    _unflatten_like)
                full = cast_from_wire(jax.lax.all_gather(
                    cast_to_wire(state.params, wire_dtype), data_axis,
                    tiled=True), jnp.float32)
                full_params = _unflatten_like(full[:n_elem], params_struct)
        else:
            full_params = state.params

        if grad_accum_steps > 1:
            b_local = images.shape[0]
            if b_local % grad_accum_steps:
                raise ValueError(
                    f"per-device batch {b_local} not divisible by "
                    f"grad_accum_steps={grad_accum_steps}")
            micro = b_local // grad_accum_steps
            im = images.reshape(grad_accum_steps, micro, *images.shape[1:])
            lb = labels.reshape(grad_accum_steps, micro)
            # mixup pairing crosses micro-batch boundaries (the permutation
            # ran over the whole local batch BEFORE the split), so the
            # paired labels ride the scan as a third sequence — lam is one
            # scalar per step, shared by every micro-batch.
            lb2 = (mix_labels.reshape(grad_accum_steps, micro)
                   if mix_labels is not None else None)

            if grad_accum_shard:
                # ZeRO-2-flavored carry: this replica's 1/N flat gradient
                # shard, fp32 — each micro-gradient is scattered right away
                # and only the shard persists across micro-batches.
                accumulate = lambda g_acc, g: g_acc + scatter_mean_shard(g)
                g_init = jnp.zeros((shard_size,), jnp.float32)
            else:
                accumulate = lambda g_acc, g: jax.tree.map(jnp.add, g_acc, g)
                g_init = jax.tree.map(jnp.zeros_like, state.params)

            def micro_step(carry, xs):
                g_acc, bs = carry
                if lb2 is not None:
                    im_i, lb_i, lb2_i, i = xs
                else:
                    im_i, lb_i, i = xs
                    lb2_i = None
                loss_fn = make_loss_fn(im_i, lb_i, lb2_i, bs,
                                       jax.random.fold_in(rng, i))
                (_, (bs_new, m)), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(full_params)
                return (accumulate(g_acc, g), bs_new), m

            micro_xs = (im, lb) + (() if lb2 is None else (lb2,)) \
                + (jnp.arange(grad_accum_steps),)
            (g_sum, new_batch_stats), metrics_stack = jax.lax.scan(
                micro_step, (g_init, state.batch_stats), micro_xs)
            if grad_accum_shard:
                accum_grad_shard = g_sum / grad_accum_steps
                grads = None   # never materialized whole past a micro-step
            else:
                grads = jax.tree.map(lambda g: g / grad_accum_steps, g_sum)
                accum_grad_shard = None
            metrics = jax.tree.map(lambda m: jnp.mean(m, axis=0),
                                   metrics_stack)
        else:
            loss_fn = make_loss_fn(images, labels, mix_labels,
                                   state.batch_stats, rng)
            (_, (new_batch_stats, metrics)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(full_params)
            accum_grad_shard = None
        metrics = cross_replica_mean(metrics, data_axis)

        if zero1:
            if accum_grad_shard is not None:
                # grad_accum_shard: the scatter already happened per
                # micro-batch inside the scan; the mean shard is in hand.
                grad_shard = accum_grad_shard
            else:
                # reduce-scatter half of the all-reduce: each replica owns
                # the mean gradient for its contiguous 1/N flat shard.
                grad_shard = scatter_mean_shard(grads)
            grad_norm = jnp.sqrt(jax.lax.psum(
                jnp.sum(jnp.square(grad_shard)), data_axis))
            if grad_clip_norm > 0:
                grad_shard = _clip_by_global_norm(grad_shard, grad_norm,
                                                  grad_clip_norm)

            if shard_params:
                # ZeRO-3: the resident (S,) flat shard IS the optimizer's
                # parameter frame — no slicing out of a replicated tree, and
                # (below) no trailing re-sync gather: the NEXT step's
                # just-in-time gather reconstitutes the tree from exactly
                # what the ZeRO-2 step would have stored.
                param_shard = state.params
                unravel = None
            elif bucket_layout is not None:
                # bucket-major flat frame (parallel/buckets.py): the param
                # shard, the opt-state vectors, and the gathered update all
                # live in GradBucketLayout's replica-interleaved layout
                param_shard = bucket_layout.local_param_shard(
                    state.params, data_axis)
            else:
                flat_params, unravel = ravel_pytree(state.params)
                offset = jax.lax.axis_index(data_axis) * shard_size
                param_shard = jax.lax.dynamic_slice_in_dim(
                    jnp.pad(flat_params, (0, padded - n_elem)), offset,
                    shard_size)
            updates_shard, new_opt_state = tx.update(
                grad_shard, state.opt_state, param_shard)
            new_param_shard = optax.apply_updates(param_shard, updates_shard)
            if shard_params:
                # ZeRO-3 persists the shard itself — params stay O(1/N).
                new_params = new_param_shard
            # [SYNC] all-gather half: replicas re-sync the updated parameters.
            elif bucket_layout is not None:
                new_params = bucket_layout.gather_params(new_param_shard,
                                                         data_axis)
            else:
                new_flat = jax.lax.all_gather(
                    new_param_shard, data_axis, tiled=True)
                new_params = unravel(new_flat[:n_elem])
            metrics["grad_norm"] = grad_norm
        else:
            # [SYNC] — the one cross-replica point per step (reference: NCCL/MPI
            # ring all-reduce; here: XLA ICI all-reduce emitted from pmean).
            # Bucketed: one pmean per size-targeted bucket instead of one
            # per leaf — same elementwise math, ICI-friendly message sizes.
            if bucket_layout is not None:
                grads = bucket_layout.pmean_buckets(grads, data_axis,
                                                    wire_dtype=wire_dtype)
            else:
                grads = all_reduce_gradients(grads, data_axis,
                                             reduce_dtype=wire_dtype)
            grad_norm = optax.global_norm(grads)
            if grad_clip_norm > 0:
                grads = _clip_by_global_norm(grads, grad_norm, grad_clip_norm)
            updates, new_opt_state = tx.update(grads, state.opt_state,
                                               state.params)
            new_params = optax.apply_updates(state.params, updates)
            metrics["grad_norm"] = grad_norm

        if schedule is not None:
            metrics["lr"] = schedule(state.step)

        # Parameter EMA (train.ema_decay): stored like params — replicated
        # tree under DP/ZeRO-1/2 (it tracks the post-all-gather params);
        # under ZeRO-3 both sides are the resident (S,) flat shard, so the
        # identical elementwise update shards for free. BN moving stats are
        # averaged with the same decay (the TF recipe's
        # moving_average_variables). Fused into the same XLA computation as
        # the step.
        new_ema = state.ema_params
        new_ema_bs = state.ema_batch_stats
        if ema_decay > 0.0:
            avg = lambda e, p: e * ema_decay + (1.0 - ema_decay) * p
            new_ema = jax.tree.map(avg, state.ema_params, new_params)
            new_ema_bs = jax.tree.map(avg, state.ema_batch_stats,
                                      new_batch_stats)

        if skip_nonfinite:
            # Non-finite step guard: metrics["loss"]/["l2_loss"] are the
            # cross-replica MEANS and grad_norm is psum'd — a non-finite
            # value on any replica is non-finite on every replica, so `ok`
            # is replica-consistent and the selects below cannot desync the
            # mesh. `where` never propagates NaN from the untaken branch.
            # Everything but the step counter reverts on a bad step — incl.
            # EMA, which would otherwise still drift toward the (unchanged)
            # params with one decay's worth of weight, and the optimizer's
            # internal schedule count, so skips don't consume warmup/decay
            # (see the build_train_step docstring for the metrics["lr"]
            # consequence).
            ok = jnp.logical_and(
                jnp.isfinite(metrics["loss"] + metrics["l2_loss"]),
                jnp.isfinite(metrics["grad_norm"]))
            keep = lambda new, old: jax.tree.map(
                lambda n, o: jnp.where(ok, n, o), new, old)
            new_params = keep(new_params, state.params)
            new_opt_state = keep(new_opt_state, state.opt_state)
            new_batch_stats = keep(new_batch_stats, state.batch_stats)
            if ema_decay > 0.0:
                new_ema = keep(new_ema, state.ema_params)
                new_ema_bs = keep(new_ema_bs, state.ema_batch_stats)
            metrics["bad_step"] = 1.0 - ok.astype(jnp.float32)

        new_state = state.replace(step=state.step + 1, params=new_params,
                                  batch_stats=new_batch_stats,
                                  opt_state=new_opt_state,
                                  ema_params=new_ema,
                                  ema_batch_stats=new_ema_bs)
        return new_state, metrics

    sharded = shard_map(
        step_fn, mesh=mesh,
        in_specs=(state_specs, P(data_axis), P()),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    # State donation halves the step's peak param memory on accelerators.
    # NOT on XLA:CPU: jaxlib 0.4.x reloads persistently-cached CPU
    # executables with donation/aliasing metadata unsafely — re-running a
    # cache-deserialized donating step after an Orbax restore corrupts the
    # glibc heap ("corrupted double-linked list"; reproduced 5/5 with
    # donation+cache, 0/5 with either removed — resilience PR). CPU runs
    # are smoke/CI scale, where the memory win is irrelevant anyway.
    donate = () if jax.default_backend() == "cpu" else (0,)
    jitted = jax.jit(sharded, donate_argnums=donate)

    # Telemetry: host DISPATCH time of the jitted step ("dispatch" spans).
    # JAX dispatch is async, so this is NOT device time — but its spikes are
    # diagnostic on their own (first-call spans carry compile time; later
    # spikes mean the dispatch queue back-pressured, i.e. the host got ahead
    # of the device). The wrapper keeps `.lower` (bench.py AOT-compiles the
    # step) and is a plain passthrough when telemetry is disabled.
    from distributed_vgg_f_tpu import telemetry

    @functools.wraps(jitted)
    def train_step(state, batch, rng):
        rec = telemetry.get_recorder()
        if not rec.enabled:
            return jitted(state, batch, rng)
        t0 = time.monotonic_ns()
        out = jitted(state, batch, rng)
        rec.record("train_step_dispatch", "dispatch", t0,
                   time.monotonic_ns() - t0)
        telemetry.inc("step/dispatched")
        # comm/* receipts (ISSUE 11): per-step exchange counters + the
        # static exchange-shape gauges, single-sourced from the geometry
        # the trace actually used (comm_meta fills on first trace, so the
        # first dispatch already sees it)
        if comm_meta:
            telemetry.inc("comm/exchanges")
            telemetry.inc("comm/wire_bytes", comm_meta["wire_bytes"])
            # gather-leg receipts (r21): all_gather collectives this step
            # moved (0 dp / 1 zero1-2 re-sync / per-bucket zero3 fetch) and
            # their wire bytes — off the SAME trace-time geometry
            if comm_meta["gathers"]:
                telemetry.inc("comm/gathers", comm_meta["gathers"])
                telemetry.inc("comm/gather_wire_bytes",
                              comm_meta["gather_bytes"])
            reg = telemetry.get_registry()
            reg.set_gauge("comm/buckets_per_step", comm_meta["buckets"])
            reg.set_gauge("comm/bucket_mb", comm_meta["bucket_mb"])
        return out

    train_step.lower = jitted.lower
    # the static exchange receipt (trainer JSONL `comm` block, bench rows);
    # empty until the first trace fills it
    train_step.comm_meta = comm_meta
    return train_step


def build_eval_step(model, mesh: Mesh, data_axis: str = "data",
                    state_specs=None,
                    device_finish: Callable | None = None,
                    param_gather: Callable | None = None,
                    ) -> Callable[[TrainState, Batch], Mapping[str, jnp.ndarray]]:
    """Jitted eval step returning psum-accumulated correct counts
    (SURVEY.md §3.4): {'top1': n_correct, 'top5': n_correct5, 'count': n}.

    `state_specs` mirrors the train step's so a ZeRO-1-sharded state is consumed
    in place (eval never touches opt state, so no gather is emitted).
    `param_gather` (ZeRO-3, r21): a closure mapping the resident (S,) flat
    param shard back to the full params tree INSIDE the shard_map body (the
    trainer builds it over the same bucket layout the train step uses;
    always fp32 — eval must score the exact weights). None = params are the
    ordinary replicated tree."""
    if state_specs is None:
        state_specs = P()

    def step_fn(state: TrainState, batch: Batch):
        images, labels = batch["image"], batch["label"]
        if device_finish is not None:
            # SAME prologue as the train step (single-normalization
            # contract): eval batches ride the host-normalize wire and pass
            # through untouched; a uint8 batch fed here is finished exactly
            # once — the host/device double-normalize hazard is
            # structurally impossible (tests/test_wire_u8.py).
            images = device_finish(images)
        # Exact eval (data/eval_pad.py): a "valid" mask marks padding rows in
        # the final partial batch; they contribute to neither hits nor count.
        valid = batch.get("valid")
        params = (param_gather(state.params) if param_gather is not None
                  else state.params)
        logits, _ = _apply_model(model, params, state.batch_stats, images,
                                 train=False)
        k5 = min(5, logits.shape[-1])
        counts = {
            "top1": topk_correct(logits, labels, 1, valid),
            "top5": topk_correct(logits, labels, k5, valid),
            "count": (jnp.sum(valid.astype(jnp.int32)) if valid is not None
                      else jnp.asarray(labels.shape[0], jnp.int32)),
        }
        return cross_replica_sum(counts, data_axis)

    sharded = shard_map(step_fn, mesh=mesh,
                        in_specs=(state_specs, P(data_axis)),
                        out_specs=P(),
                        check_vma=False)
    return jax.jit(sharded)
