"""Knowledge distillation for the serving `student` tier (r23).

The student (`vggf_student`, models/registry.py — half-width CNN-F) trains
against the teacher task's FULL logit distribution (`data/teacher.py
Teacher.logits`), not just its argmax labels: the classic softened-softmax
head (Hinton et al., arXiv 1503.02531)

    loss = alpha * T^2 * KL(softmax(t/T) || softmax(s/T))
         + (1 - alpha) * CE(s, hard_labels)

where the `T^2` factor keeps the soft-target gradient magnitude comparable
across temperatures. The same loop trains the serving FLAGSHIP for the
tier-ladder receipts (alpha=0 degrades to plain CE on teacher labels) so
the committed accuracy deltas in benchmarks/runs/host_r23 compare a
trained flagship against a student distilled from the identical task.

Normalization deliberately matches SERVING, not the teacher-task training
default: batches are normalized with the vggf descriptor's IMAGENET
constants so weights trained here drop straight into a `PredictEngine`
(whose device-finish prologue applies exactly those constants to the u8
wire) with zero scale mismatch. Teacher logits are computed on the
DE-normalized pixels the student actually sees — teacher and student
always look at the same image.

Standalone by design: this is an offline weight-production tool (like
benchmarks/), not a trainer mode — it hand-rolls an optax SGD loop rather
than growing a third trainer configuration surface.
"""

from __future__ import annotations

import argparse
import json
from typing import Dict, Optional, Tuple

import numpy as np

from distributed_vgg_f_tpu.models.ingest import ingest_descriptor

#: Disjoint index bases over the teacher task's procedural index space:
#: train draws [0, num_examples), serving calibration sits at +2^24
#: (serving/tiers.calibration_images), the accuracy-receipt eval shard
#: here — all three never overlap.
EVAL_INDEX_BASE = 1 << 20


def distill_loss(student_logits, teacher_logits, labels, *,
                 temperature: float = 2.0, alpha: float = 0.5):
    """The distillation objective (batch mean). `alpha` mixes the softened
    KL term against hard-label cross-entropy; alpha=0 is plain CE (the
    flagship's path), alpha=1 is pure distillation."""
    import jax.nn
    import jax.numpy as jnp
    s = student_logits.astype(jnp.float32)
    t = teacher_logits.astype(jnp.float32)
    logp_s = jax.nn.log_softmax(s / temperature, axis=-1)
    logp_t = jax.nn.log_softmax(t / temperature, axis=-1)
    p_t = jnp.exp(logp_t)
    kl = jnp.sum(p_t * (logp_t - logp_s), axis=-1)
    soft = (temperature ** 2) * jnp.mean(kl)
    onehot = jax.nn.one_hot(labels, s.shape[-1], dtype=jnp.float32)
    ce = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(s, axis=-1),
                           axis=-1))
    return alpha * soft + (1.0 - alpha) * ce


# ------------------------------------------------------------- params I/O
def save_params(path: str, params) -> None:
    """Flat npz of the param pytree ('/'-joined paths) — the student-tier
    weight artifact `build_student_engine` loads."""
    from flax import traverse_util
    flat = traverse_util.flatten_dict(params, sep="/")
    np.savez(path, **{k: np.asarray(v) for k, v in flat.items()})


def load_params(path: str):
    from flax import traverse_util
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    return traverse_util.unflatten_dict(flat, sep="/")


# ------------------------------------------------------------ data plumbing
def _serving_norm(image_size: int):
    """(mean, std) as (1,1,3) arrays — the vggf descriptor's constants,
    i.e. what make_device_finish applies to the u8 wire at serve time."""
    d = ingest_descriptor("vggf")
    return (np.asarray(d.mean_rgb, np.float32).reshape(1, 1, 3),
            np.asarray(d.stddev_rgb, np.float32).reshape(1, 1, 3))


def teacher_eval_shard(image_size: int, num_classes: int,
                       num_examples: int) -> Tuple[np.ndarray, np.ndarray]:
    """The fixed accuracy-receipt shard: u8 images + teacher labels at
    EVAL_INDEX_BASE (disjoint from train and calibration). Labels are
    computed on the uint8-ROUNDED pixels — the exact bytes the serving
    wire carries — so offline eval and server eval see identical inputs."""
    from distributed_vgg_f_tpu.data.teacher import Teacher, _raw_images
    idx = np.arange(num_examples) + EVAL_INDEX_BASE
    raw = _raw_images(idx, image_size, base_seed=11)
    images = np.clip(np.rint(raw), 0, 255).astype(np.uint8)
    teacher = Teacher(image_size, num_classes, seed=7)
    return images, teacher.label(images.astype(np.float32))


# --------------------------------------------------------------- the loop
def train_distilled(model_name: str, *, image_size: int = 32,
                    num_classes: int = 10, steps: int = 1200,
                    batch_size: int = 64, lr: float = 0.02,
                    momentum: float = 0.9, grad_clip: float = 1.0,
                    weight_decay: float = 5e-5, temperature: float = 2.0,
                    alpha: float = 0.5, dropout_rate: float = 0.2,
                    num_examples: int = 4096, seed: int = 0,
                    log_every: int = 200,
                    progress: Optional[callable] = None):
    """Train `model_name` on the teacher task with the distillation head.
    Returns (params, history) — params ready for `build_student_engine`
    (or a flagship `PredictEngine` when model_name='vggf')."""
    import jax
    import jax.numpy as jnp
    import optax
    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.data.teacher import TeacherTaskDataset
    from distributed_vgg_f_tpu.models.registry import build_model

    mean, std = _serving_norm(image_size)
    model = build_model(ModelConfig(
        name=model_name, num_classes=num_classes,
        dropout_rate=dropout_rate, compute_dtype="float32"))
    ds = TeacherTaskDataset(batch_size, image_size, num_classes,
                            seed=seed, num_examples=num_examples,
                            mean=mean, std=std)
    teacher = ds.teacher

    rng = jax.random.PRNGKey(seed)
    rng, init_rng = jax.random.split(rng)
    sample = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    params = model.init(init_rng, sample, train=False)["params"]

    # cosine-to-zero with a short linear warmup — the vggf_teacher preset's
    # shape (config.py) at this task's scale
    warmup = max(1, steps // 20)
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps=warmup, decay_steps=steps)
    tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                     optax.add_decayed_weights(weight_decay),
                     optax.sgd(schedule, momentum=momentum))
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, images, t_logits, labels, dropout_rng):
        def loss_fn(p):
            s_logits = model.apply({"params": p}, images, train=True,
                                   rngs={"dropout": dropout_rng})
            return distill_loss(s_logits, t_logits, labels,
                                temperature=temperature, alpha=alpha)
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    history = []
    for i in range(steps):
        batch = next(ds)
        images = jnp.asarray(batch["image"], jnp.float32)
        # the teacher looks at the SAME pixels the student does
        raw = np.asarray(batch["image"], np.float32) * std + mean
        t_logits = jnp.asarray(teacher.logits(raw))
        labels = jnp.asarray(batch["label"])
        rng, drop = jax.random.split(rng)
        params, opt_state, loss = step(params, opt_state, images,
                                       t_logits, labels, drop)
        if i % log_every == 0 or i == steps - 1:
            history.append({"step": i, "loss": round(float(loss), 4)})
            if progress is not None:
                progress(history[-1])
    return jax.device_get(params), history


def eval_top1(model_name: str, params, *, image_size: int = 32,
              num_classes: int = 10, num_examples: int = 512,
              batch_size: int = 64, dropout_rate: float = 0.2) -> float:
    """Top-1 vs teacher labels on the fixed eval shard, through the SAME
    normalize path serving applies (descriptor constants on u8)."""
    import jax
    import jax.numpy as jnp
    from distributed_vgg_f_tpu.config import ModelConfig
    from distributed_vgg_f_tpu.models.registry import build_model
    model = build_model(ModelConfig(
        name=model_name, num_classes=num_classes,
        dropout_rate=dropout_rate, compute_dtype="float32"))
    mean, std = _serving_norm(image_size)
    images, labels = teacher_eval_shard(image_size, num_classes,
                                        num_examples)

    @jax.jit
    def logits_fn(x):
        return model.apply({"params": params}, x, train=False)

    hits = 0
    for i in range(0, len(images), batch_size):
        chunk = images[i:i + batch_size].astype(np.float32)
        x = jnp.asarray((chunk - mean) / std)
        pred = np.argmax(np.asarray(logits_fn(x)), axis=1)
        hits += int(np.sum(pred == labels[i:i + batch_size]))
    return hits / len(images)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Distill (or plain-train, --alpha 0) a zoo model on "
                    "the teacher task; writes an npz the serving tiers "
                    "load.")
    ap.add_argument("--model", default="vggf_student")
    ap.add_argument("--steps", type=int, default=1200)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=32)
    ap.add_argument("--num-classes", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.02)
    ap.add_argument("--alpha", type=float, default=0.5)
    ap.add_argument("--temperature", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--num-examples", type=int, default=4096)
    ap.add_argument("--eval-examples", type=int, default=512)
    ap.add_argument("--out", required=True, help="npz weights path")
    args = ap.parse_args(argv)

    params, history = train_distilled(
        args.model, image_size=args.image_size,
        num_classes=args.num_classes, steps=args.steps,
        batch_size=args.batch_size, lr=args.lr, alpha=args.alpha,
        temperature=args.temperature, seed=args.seed,
        num_examples=args.num_examples,
        progress=lambda h: print(json.dumps(h), flush=True))
    save_params(args.out, params)
    top1 = eval_top1(args.model, params, image_size=args.image_size,
                     num_classes=args.num_classes,
                     num_examples=args.eval_examples)
    print(json.dumps({"model": args.model, "out": args.out,
                      "eval_top1": round(top1, 4),
                      "final_loss": history[-1]["loss"]}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
