"""Train state pytree: step counter, params, mutable model state (BN stats), and
optimizer state — the unit that is updated per step, checkpointed, and restored
(SURVEY.md §3.5)."""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray            # scalar int32
    params: Any
    batch_stats: Any             # {} for models without BN (VGG-F/VGG-16/ViT)
    opt_state: optax.OptState

    @classmethod
    def create(cls, model, tx, rng: jax.Array, sample_input: jnp.ndarray
               ) -> "TrainState":
        variables = model.init({"params": rng}, sample_input, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   batch_stats=batch_stats, opt_state=tx.init(params))
