"""Train state pytree: step counter, params, mutable model state (BN stats), and
optimizer state — the unit that is updated per step, checkpointed, and restored
(SURVEY.md §3.5)."""

from __future__ import annotations

from typing import Any

import flax.struct
import jax
import jax.numpy as jnp
import optax


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray            # scalar int32
    params: Any
    batch_stats: Any             # {} for models without BN (VGG-F/VGG-16/ViT)
    opt_state: optax.OptState
    # Exponential moving average of params (train.ema_decay > 0); None when
    # disabled — None is an EMPTY pytree subtree, so states and checkpoints
    # written without EMA keep their exact structure. BN moving statistics
    # are averaged too (ema_batch_stats — the TF-era recipe averages
    # `moving_average_variables`, which includes BN moving mean/var; eval
    # with averaged weights against raw-trajectory BN stats would silently
    # mismatch the activation distribution).
    ema_params: Any = None
    ema_batch_stats: Any = None

    @classmethod
    def create(cls, model, tx, rng: jax.Array, sample_input: jnp.ndarray,
               *, zero1_shards: int = 0, ema: bool = False,
               bucket_layout=None, shard_params: bool = False) -> "TrainState":
        """`zero1_shards > 1` initializes the optimizer state over the padded
        flat parameter vector instead of the params pytree — the ZeRO-1 layout
        (parallel/zero.py) whose vector leaves are then sharded over the data
        axis. `bucket_layout` (parallel/buckets.GradBucketLayout, r14) swaps
        that vector for the bucket-major replica-interleaved layout the
        bucketed exchange scatters into — same length semantics, permuted
        elements; must be the SAME layout the train step builds.
        `shard_params=True` (ZeRO-3, r21; requires `zero1_shards > 1`) stores
        the params themselves — and the EMA seed — as that SAME flat vector,
        to be sharded over the data axis alongside the optimizer vectors.
        `ema=True` starts the parameter EMA at the initial params (no
        zero-debias needed)."""
        variables = model.init({"params": rng}, sample_input, train=False)
        params = variables["params"]
        batch_stats = variables.get("batch_stats", {})
        if zero1_shards > 1:
            if bucket_layout is not None:
                flat_params = tx_input = bucket_layout.to_global(params)
            else:
                from jax.flatten_util import ravel_pytree
                from distributed_vgg_f_tpu.parallel.zero import (
                    padded_flat_size)
                flat, _ = ravel_pytree(params)
                padded = padded_flat_size(flat.size, zero1_shards)
                flat_params = tx_input = jnp.pad(
                    flat, (0, padded - flat.size))
            opt_state = tx.init(tx_input)
            if shard_params:
                params = flat_params
        else:
            if shard_params:
                raise ValueError(
                    "shard_params (ZeRO-3) requires zero1_shards > 1 — the "
                    "flat param vector is sharded over the data axis")
            opt_state = tx.init(params)
        return cls(step=jnp.zeros((), jnp.int32), params=params,
                   batch_stats=batch_stats, opt_state=opt_state,
                   ema_params=params if ema else None,
                   ema_batch_stats=batch_stats if ema else None)
