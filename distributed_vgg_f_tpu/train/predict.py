"""Predict mode: classify arbitrary JPEGs with a trained checkpoint.

The reference ships train/eval only; a user switching from it still needs the
obvious third surface — "run the trained model on my images". This runs the
eval decode protocol (resize-short-side-256 → center-crop, mean/std
normalize) through the native loader when available (tf.data fallback), a
single jitted forward, and prints one JSON line per image with the top-k
class indices and probabilities (plus wnids when the data layout provides a
class directory index).
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_JPEG_EXTS = (".jpg", ".jpeg", ".JPG", ".JPEG")


def collect_images(inputs: Sequence[str]) -> list[str]:
    """Expand files/directories into a sorted list of image paths."""
    out: list[str] = []
    for p in inputs:
        if os.path.isdir(p):
            out.extend(os.path.join(p, f) for f in sorted(os.listdir(p))
                       if f.endswith(_JPEG_EXTS))
        elif os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(f"no such file or directory: {p!r}")
    if not out:
        raise FileNotFoundError(f"no images found under {list(inputs)!r}")
    return out


def _decode_batches(files: list[str], cfg, batch: int) -> Iterable[dict]:
    """Center-crop eval decode over `files` — native loader preferred, tf.data
    eval preprocessing as the fallback. Yields {'image', 'valid'} batches."""
    import logging
    it = None
    try:
        from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegEvalIterator
        it = NativeJpegEvalIterator(
            files, [0] * len(files), batch, cfg.image_size,
            mean=np.asarray(cfg.mean_rgb, np.float32),
            std=np.asarray(cfg.stddev_rgb, np.float32),
            num_threads=cfg.native_threads or None)
    except (RuntimeError, OSError, ValueError) as e:
        logging.getLogger(__name__).warning(
            "native decode unavailable for predict (%s); using tf.data", e)
    if it is not None:
        yield from it
        if it.decode_errors():
            # zero-filled inputs produce meaningless predictions — say so
            logging.getLogger(__name__).warning(
                "%d image(s) failed to decode; their predictions are from "
                "zero-filled inputs", it.decode_errors())
        return

    import tensorflow as tf

    from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable
    from distributed_vgg_f_tpu.data.imagenet import _preprocess_fns
    _, eval_fn = _preprocess_fns(tf, cfg)
    size = cfg.image_size

    def decode(path):
        # per-file eager decode so ONE corrupt image zero-fills (like the
        # native path) instead of killing the whole predict run
        try:
            img, _ = eval_fn(tf.io.read_file(path), tf.constant(0, tf.int32))
            return np.asarray(img, np.float32)
        except tf.errors.OpError as e:
            logging.getLogger(__name__).warning(
                "failed to decode %s (%s); prediction is from zero-filled "
                "input", path, e)
            return np.zeros((size, size, 3), np.float32)

    def epoch():
        for start in range(0, len(files), batch):
            chunk = files[start:start + batch]
            yield {"image": np.stack([decode(p) for p in chunk]),
                   "label": np.zeros((len(chunk),), np.int32)}

    # the existing exact-eval pad-and-mask machinery handles the ragged
    # final batch — one implementation of the padding protocol, not two
    yield from FiniteEvalIterable(epoch, batch, (size, size, 3), np.float32)


def run_predict(trainer, inputs: Sequence[str], *, top_k: int = 5,
                batch: int = 32, stream=None) -> list[dict]:
    """Classify `inputs` with the trainer's latest checkpoint; prints one JSON
    line per image to `stream` (default stdout) and returns the records."""
    import sys
    stream = stream or sys.stdout
    cfg = trainer.cfg
    files = collect_images(inputs)
    batch = min(batch, max(1, len(files)))
    # Never silently classify with random weights — the guard lives HERE so
    # every caller (CLI or library) gets it, not just train.py.
    if trainer.checkpoints is None or \
            trainer.checkpoints.latest_step() is None:
        raise RuntimeError(
            "predict requires a checkpoint: none found under "
            f"{cfg.train.checkpoint_dir!r} (set train.checkpoint_dir)")
    state = trainer.restore_or_init()

    # Predict is a host-side convenience surface: pull (possibly sharded)
    # params to host once and run a plain single-device jit — no mesh needed.
    # EMA weights, when tracked, are the deliverable (same default as eval);
    # BN stats swap together with the weights.
    use_ema = state.ema_params is not None
    params = jax.device_get(state.ema_params if use_ema else state.params)
    batch_stats = jax.device_get(state.ema_batch_stats if use_ema
                                 else state.batch_stats)
    model = trainer.model

    # Same device-finish prologue as the train/eval steps (single-
    # normalization contract, data/device_ingest.py): predict's decode
    # path ships host-normalized floats, which pass through untouched; a
    # uint8 batch fed by a caller is finished exactly once on device.
    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish
    finish = make_device_finish(cfg.data.mean_rgb, cfg.data.stddev_rgb,
                                image_dtype=cfg.data.image_dtype)

    @jax.jit
    def forward(images):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, finish(images), train=False)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # wnid mapping when the data layout carries class directories
    from distributed_vgg_f_tpu.data.imagenet import _class_index
    classes = _class_index(cfg.data) if cfg.data.data_dir else None

    k = min(top_k, cfg.model.num_classes)
    results: list[dict] = []
    pos = 0
    for b in _decode_batches(files, cfg.data, batch):
        probs = np.asarray(jax.device_get(forward(b["image"])))
        for row, ok in zip(probs, b["valid"]):
            if not ok or pos >= len(files):
                continue
            top = np.argsort(row)[::-1][:k]
            rec = {
                "file": files[pos],
                "top_k": [{
                    "class": int(c),
                    **({"wnid": classes[c]} if classes and c < len(classes)
                       else {}),
                    "prob": round(float(row[c]), 6),
                } for c in top],
            }
            results.append(rec)
            print(json.dumps(rec), file=stream)
            pos += 1
    return results
