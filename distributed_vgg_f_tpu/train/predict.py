"""Predict mode: classify arbitrary JPEGs with a trained checkpoint.

The reference ships train/eval only; a user switching from it still needs the
obvious third surface — "run the trained model on my images". This runs the
eval decode protocol (resize-short-side-256 → center-crop, mean/std
normalize) through the native loader when available (tf.data fallback), a
single jitted forward, and prints one JSON line per image with the top-k
class indices and probabilities (plus wnids when the data layout provides a
class directory index).

r17 split: this module is now ALSO the single source of the predict math
for the serving plane (serving/engine.py). `restore_predict_params` owns
the checkpoint-restore + EMA-selection contract, `build_forward` owns the
forward expression (variables assembly, device-finish prologue, f32
softmax), and `top_k_records` owns the record shape — the always-on server
and this offline surface share those three, so "server ≡ offline predict"
is a structural property, not a parity test over two copies.

Array inputs (`.npy` files of raw uint8 (S, S, 3) pixels — the u8 wire's
payload, exactly what a serving client POSTs) skip the decode protocol and
route through the SAME bucketed engine the server runs, which is what makes
the bitwise server-vs-offline gate in tests/test_serving.py meaningful:
XLA does not promise bitwise row-independence across batch geometries, so
equality must come from equal inputs through equal executables.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

_JPEG_EXTS = (".jpg", ".jpeg", ".JPG", ".JPEG")
_ARRAY_EXT = ".npy"


def collect_images(inputs: Sequence[str]) -> list[str]:
    """Expand files/directories into a sorted list of image paths."""
    out: list[str] = []
    for p in inputs:
        if os.path.isdir(p):
            out.extend(os.path.join(p, f) for f in sorted(os.listdir(p))
                       if f.endswith(_JPEG_EXTS))
        elif os.path.isfile(p):
            out.append(p)
        else:
            raise FileNotFoundError(f"no such file or directory: {p!r}")
    if not out:
        raise FileNotFoundError(f"no images found under {list(inputs)!r}")
    return out


def restore_predict_params(trainer):
    """(params, batch_stats) from the trainer's latest checkpoint, pulled
    to host — the ONE restore path offline predict and the serving engine
    share. EMA weights, when tracked, are the deliverable (same default as
    eval); BN stats swap together with the weights. Never silently
    classifies with random weights — the guard lives HERE so every caller
    (CLI, library, server) gets it."""
    cfg = trainer.cfg
    if trainer.checkpoints is None or \
            trainer.checkpoints.latest_step() is None:
        raise RuntimeError(
            "predict requires a checkpoint: none found under "
            f"{cfg.train.checkpoint_dir!r} (set train.checkpoint_dir)")
    state = trainer.restore_or_init()
    use_ema = state.ema_params is not None
    # params_tree: under ZeRO-3 (r21) the state holds the flat shard
    # vector — invert it to the tree on host (identity otherwise)
    params = jax.device_get(trainer.params_tree(
        state.ema_params if use_ema else state.params))
    batch_stats = jax.device_get(state.ema_batch_stats if use_ema
                                 else state.batch_stats)
    return params, batch_stats


def build_forward(model, params, batch_stats, finish):
    """The predict forward — the single implementation offline predict
    jits and the serving engine AOT-compiles per bucket. `finish` is the
    device-finish prologue (single-normalization contract,
    data/device_ingest.py): host-normalized float batches pass through
    untouched; a uint8 batch is finished exactly once on device."""

    def forward(images):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, finish(images), train=False)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    return forward


def top_k_records(row, k: int, classes=None,
                  full_precision: bool = False) -> list[dict]:
    """One probability row → the top-k record list every predict surface
    emits. `full_precision=False` keeps the offline JPEG surface's
    display rounding (byte-identical to pre-r17 output); the serving
    responses and the offline ARRAY path pass True so the bitwise
    server-vs-offline gate compares exact values, not rounded ones."""
    top = np.argsort(row)[::-1][:k]
    return [{
        "class": int(c),
        **({"wnid": classes[c]} if classes and c < len(classes) else {}),
        "prob": float(row[c]) if full_precision
        else round(float(row[c]), 6),
    } for c in top]


def _decode_batches(files: list[str], cfg, batch: int) -> Iterable[dict]:
    """Center-crop eval decode over `files` — native loader preferred, tf.data
    eval preprocessing as the fallback. Yields {'image', 'valid'} batches."""
    import logging
    it = None
    try:
        from distributed_vgg_f_tpu.data.native_jpeg import NativeJpegEvalIterator
        it = NativeJpegEvalIterator(
            files, [0] * len(files), batch, cfg.image_size,
            mean=np.asarray(cfg.mean_rgb, np.float32),
            std=np.asarray(cfg.stddev_rgb, np.float32),
            num_threads=cfg.native_threads or None)
    except (RuntimeError, OSError, ValueError) as e:
        logging.getLogger(__name__).warning(
            "native decode unavailable for predict (%s); using tf.data", e)
    if it is not None:
        yield from it
        if it.decode_errors():
            # corrupt-filled inputs produce meaningless predictions — say so
            logging.getLogger(__name__).warning(
                "%d image(s) failed to decode; their predictions are from "
                "corrupt-filled inputs", it.decode_errors())
        return

    import tensorflow as tf

    from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable
    from distributed_vgg_f_tpu.data.imagenet import _preprocess_fns
    from distributed_vgg_f_tpu.data.snapshot_cache import corrupt_fill
    _, eval_fn = _preprocess_fns(tf, cfg)
    size = cfg.image_size

    def decode(path):
        # per-file eager decode so ONE corrupt image degrades to the
        # shared corrupt-image contract (like the native path) instead of
        # killing the whole predict run
        try:
            img, _ = eval_fn(tf.io.read_file(path), tf.constant(0, tf.int32))
            return np.asarray(img, np.float32)
        except tf.errors.OpError as e:
            logging.getLogger(__name__).warning(
                "failed to decode %s (%s); prediction is from a "
                "corrupt-filled input", path, e)
            # the r9 corrupt-image contract, SHARED (data/snapshot_cache
            # corrupt_fill): this path ships host-normalized floats, so
            # the fill is the host-wire zero-fill — the same
            # ~post-normalize-zero a u8-wire mean-fill reads as downstream
            out = np.empty((size, size, 3), np.float32)
            corrupt_fill(out, "float32", cfg.mean_rgb)
            return out

    def epoch():
        for start in range(0, len(files), batch):
            chunk = files[start:start + batch]
            yield {"image": np.stack([decode(p) for p in chunk]),
                   "label": np.zeros((len(chunk),), np.int32)}

    # the existing exact-eval pad-and-mask machinery handles the ragged
    # final batch — one implementation of the padding protocol, not two
    yield from FiniteEvalIterable(epoch, batch, (size, size, 3), np.float32)


def _load_u8_array(path: str, size: int) -> np.ndarray:
    arr = np.load(path)
    if arr.dtype != np.uint8 or tuple(arr.shape) != (size, size, 3):
        raise ValueError(
            f"{path}: array inputs must be uint8 ({size}, {size}, 3) raw "
            f"pixels (the u8 wire payload), got {arr.dtype} "
            f"{tuple(arr.shape)}")
    return arr


def _predict_arrays(trainer, files: list[str], *, top_k: int, batch: int,
                    stream, classes) -> list[dict]:
    """The u8 ARRAY path: route pre-resampled pixels through the SAME
    bucketed serving engine (serving/engine.py) the always-on server runs
    — one compute path, so server responses and these records are
    bitwise-comparable. Probabilities are emitted at full precision for
    exactly that reason (display rounding would destroy the gate)."""
    from distributed_vgg_f_tpu.serving.engine import PredictEngine
    cfg = trainer.cfg
    engine = PredictEngine.from_trainer(trainer, buckets=(batch,),
                                        max_batch=batch)
    k = min(top_k, cfg.model.num_classes)
    results: list[dict] = []
    for start in range(0, len(files), batch):
        chunk = files[start:start + batch]
        images = np.stack([_load_u8_array(p, cfg.data.image_size)
                           for p in chunk])
        probs, _ = engine.run(images)
        for path, row in zip(chunk, probs):
            rec = {"file": path,
                   "top_k": top_k_records(row, k, classes,
                                          full_precision=True)}
            results.append(rec)
            print(json.dumps(rec), file=stream)
    return results


def run_predict(trainer, inputs: Sequence[str], *, top_k: int = 5,
                batch: int = 32, stream=None) -> list[dict]:
    """Classify `inputs` with the trainer's latest checkpoint; prints one JSON
    line per image to `stream` (default stdout) and returns the records.

    Inputs are JPEG files/directories (the eval decode protocol), or —
    all-or-nothing — `.npy` files of raw uint8 (S, S, 3) pixels, which
    skip decode and run the serving engine's bucketed path (see
    `_predict_arrays`). Mixing the two in one call is an error: the two
    paths ship different dtypes through different batching machinery, and
    a silent mix would interleave their records unpredictably."""
    import sys
    stream = stream or sys.stdout
    cfg = trainer.cfg
    files = collect_images(inputs)
    batch = min(batch, max(1, len(files)))
    arrays = [f.endswith(_ARRAY_EXT) for f in files]
    # wnid mapping when the data layout carries class directories
    from distributed_vgg_f_tpu.data.imagenet import _class_index
    classes = _class_index(cfg.data) if cfg.data.data_dir else None
    if any(arrays):
        if not all(arrays):
            raise ValueError(
                "cannot mix .npy array inputs with image files in one "
                "predict call")
        return _predict_arrays(trainer, files, top_k=top_k, batch=batch,
                               stream=stream, classes=classes)
    params, batch_stats = restore_predict_params(trainer)

    # Predict is a host-side convenience surface: pull (possibly sharded)
    # params to host once and run a plain single-device jit — no mesh
    # needed. Same device-finish prologue as the train/eval steps (single-
    # normalization contract, data/device_ingest.py): predict's decode
    # path ships host-normalized floats, which pass through untouched; a
    # uint8 batch fed by a caller is finished exactly once on device.
    from distributed_vgg_f_tpu.data.device_ingest import make_device_finish
    finish = make_device_finish(cfg.data.mean_rgb, cfg.data.stddev_rgb,
                                image_dtype=cfg.data.image_dtype)
    forward = jax.jit(build_forward(trainer.model, params, batch_stats,
                                    finish))

    k = min(top_k, cfg.model.num_classes)
    results: list[dict] = []
    pos = 0
    for b in _decode_batches(files, cfg.data, batch):
        probs = np.asarray(jax.device_get(forward(b["image"])))
        for row, ok in zip(probs, b["valid"]):
            if not ok or pos >= len(files):
                continue
            rec = {
                "file": files[pos],
                "top_k": top_k_records(row, k, classes),
            }
            results.append(rec)
            print(json.dumps(rec), file=stream)
            pos += 1
    return results
