"""Trainer: owns mesh, model, optimizer, jitted steps, and the host feed loop.

Reference equivalent: the session loop (SURVEY.md §1 trainer layer) — but here
everything from forward through optimizer apply (incl. the gradient all-reduce)
is one XLA computation; the Python loop only feeds batches, reads metrics, and
drives eval/checkpoint cadence (SURVEY.md §3.1 TPU mapping).
"""

from __future__ import annotations

import os
import time
from typing import Iterator, Mapping, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_vgg_f_tpu import telemetry
from distributed_vgg_f_tpu.checkpoint.manager import CheckpointManager
from distributed_vgg_f_tpu.config import (
    ExperimentConfig,
    supports_space_to_depth,
)
from distributed_vgg_f_tpu.data import build_dataset
from distributed_vgg_f_tpu.models import build_model
from distributed_vgg_f_tpu.parallel.distributed import (
    coordination_barrier,
    initialize_distributed,
)
from distributed_vgg_f_tpu.parallel.mesh import (
    MeshSpec,
    build_mesh,
    mesh_topology_report,
    shard_host_batch,
)
from distributed_vgg_f_tpu.resilience.errors import CheckpointIntegrityError
from distributed_vgg_f_tpu.resilience.faults import FaultPlan
from distributed_vgg_f_tpu.resilience.guard import NonFiniteGuard
from distributed_vgg_f_tpu.train.schedule import build_optimizer
from distributed_vgg_f_tpu.train.state import TrainState
from distributed_vgg_f_tpu.train.step import build_eval_step, build_train_step
from distributed_vgg_f_tpu.utils.logging import MetricLogger
from distributed_vgg_f_tpu.utils.meter import ThroughputMeter


# Monotone counter naming each alignment barrier: every process creates
# Trainers and calls fit/evaluate in the same program order, so the n-th
# barrier on one rank pairs with the n-th on every other.
_barrier_seq = {"n": 0}

# Separate tag sequence for the best-effort telemetry-sidecar barrier
# (export_telemetry): it must never share numbering with the MANDATORY
# align_N barriers — a rank that skips one telemetry barrier (local export
# failure) would otherwise shift every later align tag and deadlock the run.
_telemetry_barrier_seq = {"n": 0}


def _align_cold_start() -> None:
    """Align ranks on a coordination-service barrier (long explicit timeout)
    before a run's next FIRST collective execution. Gloo's TCP layer has a
    fixed ~30 s deadline both at rendezvous and on in-op reads; inter-rank
    skew accumulates across python phases (per-rank dataset builds,
    asymmetric compile-cache hits) and one aligned rank then times out
    waiting mid-collective for a lagging one. Re-aligning at every fit/eval
    entry collapses the accumulated skew each time — one cheap gRPC round
    per call (observed: a once-per-process barrier was NOT enough; a
    multi-phase child drifted >30 s by its third fit and died in a
    reduce-scatter read)."""
    if jax.process_count() == 1:
        return
    _barrier_seq["n"] += 1
    coordination_barrier(f"align_{_barrier_seq['n']}")


class Trainer:
    def __init__(self, cfg: ExperimentConfig, mesh=None,
                 logger: Optional[MetricLogger] = None):
        initialize_distributed()
        self.cfg = cfg
        # Telemetry spine (telemetry/): configure the process-wide recorder
        # and registry from config BEFORE anything records — the wired call
        # sites (prefetch, checkpoint manager, guards) all write to the
        # defaults this flips.
        telemetry.configure(enabled=cfg.telemetry.enabled,
                            span_capacity=cfg.telemetry.span_capacity,
                            flight_windows=cfg.telemetry.flight_windows)
        if cfg.data.space_to_depth and not supports_space_to_depth(
                cfg.model.name, cfg.data.image_size, cfg.data.name):
            # the packed layout is the VGG-F stem's input contract
            # (models/vggf.py Conv1SpaceToDepth); other models take (S, S, 3),
            # and only some host pipelines implement the packing
            # (config.SPACE_TO_DEPTH_DATASETS)
            raise ValueError(
                "data.space_to_depth needs the vggf model, "
                "image_size % 4 == 0, and a dataset that implements packing "
                f"(got model={cfg.model.name!r}, "
                f"image_size={cfg.data.image_size}, "
                f"dataset={cfg.data.name!r})")
        self.mesh = mesh if mesh is not None else build_mesh(
            MeshSpec((cfg.mesh.data_axis,), (cfg.mesh.num_data,)))
        self.data_axis = cfg.mesh.data_axis
        self.model = build_model(cfg.model)
        self.num_shards = int(self.mesh.shape[self.data_axis])
        self.zero1 = bool(cfg.mesh.shard_opt_state) and self.num_shards > 1
        # ZeRO-2 (r14): gradient state sharded like the opt state —
        # downgrades with zero1 on single-shard meshes (no shard to own)
        self.zero2 = self.zero1 and bool(cfg.mesh.shard_gradients)
        # ZeRO-3 (r21): params (and EMA) persisted ONLY as 1/N flat shards,
        # gathered just-in-time by the step — downgrades with the ladder
        self.zero3 = self.zero2 and bool(cfg.mesh.shard_params)
        # Bucketed exchange (r14, parallel/buckets.py): 0 = monolithic
        # kill-switch. The layout itself (when ZeRO needs one for the
        # opt-state frame) is built in _make_state_specs from the same
        # deterministic geometry function the step uses at trace time.
        self._bucket_bytes = int(round(cfg.mesh.comm_bucket_mb * 1024 * 1024))
        self._bucket_layout = None
        self.tx, self.schedule = build_optimizer(cfg)
        self._replicated = NamedSharding(self.mesh, P())
        self._state_specs = self._make_state_specs()
        if cfg.train.grad_accum_shard and not (
                cfg.mesh.shard_opt_state and cfg.train.grad_accum_steps > 1):
            raise ValueError(
                "train.grad_accum_shard requires mesh.shard_opt_state=true "
                "AND train.grad_accum_steps > 1")
        # Device-finish prologue (data/device_ingest.py, data.wire='u8'):
        # normalize/cast/space-to-depth for uint8-wire batches, fused into
        # the jitted steps. Installed UNCONDITIONALLY — it dispatches on
        # dtype, so host-normalized (float) batches pass through untouched
        # and train/eval/predict can never double-normalize. Eval batches
        # keep the unpacked (S, S, 3) host convention, so the eval finish
        # never packs.
        from distributed_vgg_f_tpu.data.device_ingest import (
            make_device_finish)
        # Fused on-device augmentation (r13, data/augment.py): with the
        # stage enabled, space-to-depth moves BEHIND it (finish stops
        # packing, the host stops packing via host_space_to_depth, and the
        # augment closure performs the relayout post-augment) — flipping a
        # packed block layout would have to permute channels per block.
        # augment.enabled=false keeps the pre-r13 wiring byte-identical.
        augment_on = cfg.data.augment.enabled
        self.device_finish = make_device_finish(
            cfg.data.mean_rgb, cfg.data.stddev_rgb,
            image_dtype=cfg.data.image_dtype,
            space_to_depth=cfg.data.space_to_depth and not augment_on)
        self._eval_finish = make_device_finish(
            cfg.data.mean_rgb, cfg.data.stddev_rgb,
            image_dtype=cfg.data.image_dtype, space_to_depth=False)
        from distributed_vgg_f_tpu.data.augment import make_device_augment
        # None when disabled — structurally absent from the train step
        # (and never handed to eval/predict at all).
        self.device_augment = make_device_augment(
            cfg.data.augment, cfg.data.mean_rgb, cfg.data.stddev_rgb,
            space_to_depth=cfg.data.space_to_depth)
        self._build_steps()
        self.logger = logger or MetricLogger()
        # Live observability endpoint (telemetry/exporter.py): one
        # process-wide HTTP server (/metrics /healthz /stallz /trace),
        # port 0 by default — the BOUND port is logged and written to the
        # run sidecar (exporter_p<rank>.jsonl) so multi-host processes
        # never collide on a fixed port. Started here (not in fit) so
        # standalone eval/predict processes are observable too.
        self.exporter = None
        # fleet identity: the role string every fleet surface keys this
        # process by — exporter sidecar, collector registry, and the
        # Chrome-trace process_name lane (Perfetto shows trainer_rank0,
        # not a bare OS pid)
        self._role = f"trainer_rank{jax.process_index()}"
        telemetry.set_process_label(self._role)
        if cfg.telemetry.enabled and cfg.telemetry.exporter:
            from distributed_vgg_f_tpu.telemetry import exporter as _exp
            try:
                self.exporter = _exp.ensure_started(
                    host=cfg.telemetry.exporter_host,
                    port=cfg.telemetry.exporter_port,
                    stalled_after_s=cfg.telemetry.exporter_stalled_after_s,
                    role=self._role)
            except OSError as e:
                # a taken fixed port (or an exhausted fd table) must cost
                # the run its observability endpoint, never the run
                if jax.process_index() == 0:
                    self.logger.log("telemetry_exporter_failed",
                                    {"error": repr(e),
                                     "port": cfg.telemetry.exporter_port})
            if self.exporter is not None:
                described = self.exporter.describe()
                if cfg.telemetry.sidecar_dir:
                    from distributed_vgg_f_tpu.parallel.distributed import (
                        write_telemetry_sidecar)
                    write_telemetry_sidecar(
                        cfg.telemetry.sidecar_dir,
                        {"event": "telemetry_exporter", **described},
                        prefix="exporter")
                if jax.process_index() == 0:
                    self.logger.log("telemetry_exporter", described)
        # Optional in-process fleet collector on rank 0 (r22,
        # telemetry/collector.py): scrapes every rank's exporter (sidecar
        # discovery) + any static endpoints into /fleetz + one aggregated
        # /metrics. Config-off by default — big fleets run the collector
        # as its own process (`python -m ...telemetry.collector`) instead.
        self.collector = None
        col = cfg.telemetry.collector
        if (cfg.telemetry.enabled and col.enabled
                and jax.process_index() == 0):
            from distributed_vgg_f_tpu.telemetry.collector import (
                FleetCollector)
            try:
                self.collector = FleetCollector(
                    sidecar_dir=col.sidecar_dir or cfg.telemetry.sidecar_dir,
                    endpoints=col.endpoints,
                    interval_s=col.interval_s,
                    stale_after_s=col.stale_after_s,
                    scrape_timeout_s=col.scrape_timeout_s,
                    fleet_log=col.fleet_log,
                    host=col.host, port=col.port)
                self.collector.start()
                self.logger.log("fleet_collector",
                                self.collector.describe())
            except OSError as e:
                # same contract as the exporter: a taken port costs the
                # fleet view, never the run
                self.collector = None
                self.logger.log("fleet_collector_failed",
                                {"error": repr(e), "port": col.port})
        self._restored_from_best = False
        # Position-exact resumable ingest (r18, data/iterator_state.py):
        # the cursor-counting rebuild surface fit() wraps the trainer-owned
        # train stream in (None when data.iterator_state.enabled=false or
        # the caller supplied the dataset), and the iterator-state blob the
        # last restore_or_init read out of the checkpoint's `extra` (None
        # for pre-r18 / receipt-absent checkpoints — those dispatch to the
        # unchanged r17 replay path).
        self._ingest = None
        self._restored_iterator_state = None
        # Live elastic resize (r19, parallel/elastic.py): cumulative
        # receipt state behind the per-window `elastic` JSONL block and
        # the elastic/ counters. topology stays "static" until a resize
        # lands (the regression sentinel's pre-r19 default basis).
        self._elastic_stats = {"resizes": 0, "downtime_ns": 0,
                               "evacuated_shards": 0,
                               "reassigned_data_shards": 0,
                               "topology": "static", "lr_scale": 1.0}
        # Closed-loop ingest autotuner (r11, data/autotune.py): created per
        # fit() once the live pipeline objects exist (the knobs bind to
        # them); None when config-off, env-killed (DVGGF_AUTOTUNE=0), or
        # the run has no verdict stream to steer by.
        self.autotuner = None
        self.checkpoints: Optional[CheckpointManager] = None
        # created lazily by fit() when tracking actually happens — eager
        # creation would litter best/ dirs into eval/predict runs (including
        # a best/best/ when checkpoint_dir itself points at a best slot)
        self.best_checkpoints: Optional[CheckpointManager] = None
        if cfg.train.checkpoint_dir:
            self.checkpoints = CheckpointManager(
                cfg.train.checkpoint_dir,
                max_to_keep=cfg.train.keep_checkpoints,
                save_interval_steps=cfg.train.checkpoint_every_steps,
                save_retries=cfg.train.checkpoint_save_retries)
        # Chaos harness (resilience/faults.py): None in production ("").
        self.faults = FaultPlan.parse(cfg.train.fault_injection)
        if self.faults is not None and jax.process_index() == 0:
            self.logger.log("fault_injection_armed",
                            {"spec": cfg.train.fault_injection})
        if cfg.train.debug_nans:
            jax.config.update("jax_debug_nans", True)

    def _build_steps(self) -> None:
        """(Re)build the jitted train/eval steps for the CURRENT mesh,
        optimizer, and sharding geometry. Called once at construction —
        and again by the elastic resize (r19, `_elastic_resize`) after
        the mesh/specs/tx are swapped for the survivor topology: the step
        closes over all of them, so a resize is a re-trace by
        construction, never a stale-closure bug."""
        cfg = self.cfg
        self.train_step = build_train_step(
            self.model, self.tx, self.mesh, cfg.optim.weight_decay,
            schedule=self.schedule, data_axis=self.data_axis,
            zero1=self.zero1, state_specs=self._state_specs,
            grad_clip_norm=cfg.optim.grad_clip_norm,
            grad_accum_steps=cfg.train.grad_accum_steps,
            # single-device meshes downgrade zero1 itself (no shard to
            # own), so the sharded accumulator downgrades with it
            grad_accum_shard=cfg.train.grad_accum_shard and self.zero1,
            shard_gradients=self.zero2,
            shard_params=self.zero3,
            params_struct=self._params_struct if self.zero3 else None,
            comm_bucket_mb=cfg.mesh.comm_bucket_mb,
            ema_decay=cfg.train.ema_decay,
            reduce_dtype=cfg.mesh.reduce_dtype,
            skip_nonfinite=cfg.train.skip_nonfinite,
            device_finish=self.device_finish,
            device_augment=self.device_augment)
        self.eval_step = build_eval_step(self.model, self.mesh,
                                         data_axis=self.data_axis,
                                         state_specs=self._state_specs,
                                         device_finish=self._eval_finish,
                                         param_gather=self._param_gather())

    # ------------------------------------------------------------------ state
    def _sample_input(self) -> jnp.ndarray:
        return jnp.zeros(
            (1, self.cfg.data.image_size, self.cfg.data.image_size, 3),
            jnp.float32)

    def _make_state_specs(self):
        """PartitionSpec tree for the TrainState: fully replicated for plain DP;
        opt-state vectors sharded over the data axis under ZeRO-1/2. With
        the bucketed exchange on, the flat frame is the bucket-major layout
        (parallel/buckets.py) and `self._padded` is its `total_padded`.
        Under ZeRO-3 the params (and EMA) leaves are that same flat vector,
        sharded like the opt vectors; `self._params_struct` keeps the TREE
        geometry the step/checkpoint/elastic layers need (the flat state no
        longer carries it)."""
        self._padded = None  # ZeRO flat length; None under replicated DP
        self._params_struct = None  # params TREE struct; set under ZeRO-1+
        if not self.zero1:
            return None
        from distributed_vgg_f_tpu.parallel.zero import (
            flat_param_count, padded_flat_size, train_state_specs)
        state_shapes = jax.eval_shape(
            lambda r: TrainState.create(self.model, self.tx, r,
                                        self._sample_input(),
                                        zero1_shards=self.num_shards,
                                        ema=self.cfg.train.ema_decay > 0.0),
            jax.random.key(0))
        self._params_struct = state_shapes.params
        if self._bucket_bytes > 0:
            from distributed_vgg_f_tpu.parallel.buckets import (
                build_bucket_layout)
            # the SAME deterministic geometry the step builds at trace time
            self._bucket_layout = build_bucket_layout(
                state_shapes.params, self.num_shards, self._bucket_bytes)
            padded = self._bucket_layout.total_padded
            # the bucketed opt struct is tx.init over a flat vector of the
            # bucketed length — derive it abstractly instead of re-tracing
            # the whole TrainState.create (model.init is the expensive part)
            state_shapes = state_shapes.replace(opt_state=jax.eval_shape(
                self.tx.init,
                jax.ShapeDtypeStruct((padded,), jnp.float32)))
        else:
            padded = padded_flat_size(flat_param_count(state_shapes.params),
                                      self.num_shards)
        if self.zero3:
            # ZeRO-3 state shape: params/EMA collapse to the flat vector
            # (derived abstractly, same reason as the opt struct above)
            flat = jax.ShapeDtypeStruct((padded,), jnp.float32)
            state_shapes = state_shapes.replace(
                params=flat,
                ema_params=(flat if state_shapes.ema_params is not None
                            else None))
        self._padded = padded
        return train_state_specs(state_shapes, padded, self.data_axis,
                                 shard_params=self.zero3)

    def _state_sharding(self):
        if self._state_specs is None:
            return self._replicated
        return jax.tree.map(lambda spec: NamedSharding(self.mesh, spec),
                            self._state_specs,
                            is_leaf=lambda x: isinstance(x, P))

    def _param_gather(self):
        """ZeRO-3 eval hook: a closure mapping the resident (S,) flat param
        shard back to the full params tree INSIDE a shard_map body — always
        fp32 (eval/predict must score the exact weights; the train step's
        wire-narrowing is a train-only trade). None for every other basis
        (eval consumes the replicated tree in place, pre-r21 behavior)."""
        if not self.zero3:
            return None
        layout = self._bucket_layout
        axis = self.data_axis
        if layout is not None:
            return lambda shard: layout.gather_param_tree(shard, axis)
        from distributed_vgg_f_tpu.parallel.zero import (
            _unflatten_like, flat_param_count)
        struct = self._params_struct
        n_elem = flat_param_count(struct)

        def gather(shard):
            full = jax.lax.all_gather(shard, axis, tiled=True)
            return _unflatten_like(full[:n_elem], struct)
        return gather

    def params_tree(self, params):
        """Host-side inverse of the ZeRO-3 flat params layout: the global
        (T,) flat vector → the params tree; identity for every other basis
        (params already ARE the tree). The offline surfaces (predict /
        serving restore) run outside the mesh, so they invert the layout
        here instead of through the step's in-mesh gathers."""
        if not self.zero3:
            return params
        vec = jnp.asarray(params)
        if self._bucket_layout is not None:
            return self._bucket_layout.from_global(vec)
        from distributed_vgg_f_tpu.parallel.zero import (
            _unflatten_like, flat_param_count)
        return _unflatten_like(vec[:flat_param_count(self._params_struct)],
                               self._params_struct)

    def init_state(self, rng: jax.Array | None = None) -> TrainState:
        """Initialize params on-device: replicated over the mesh, except the
        ZeRO-1 opt-state vectors which land sharded over the data axis."""
        rng = rng if rng is not None else jax.random.key(self.cfg.train.seed)
        sample = self._sample_input()
        shards = self.num_shards if self.zero1 else 0
        layout = self._bucket_layout if self.zero1 else None

        def init_fn(rng):
            return TrainState.create(self.model, self.tx, rng, sample,
                                     zero1_shards=shards,
                                     ema=self.cfg.train.ema_decay > 0.0,
                                     bucket_layout=layout,
                                     shard_params=self.zero3)

        return jax.jit(init_fn, out_shardings=self._state_sharding())(rng)

    def _make_best_manager(self) -> CheckpointManager:
        """The single-slot best-eval manager under <checkpoint_dir>/best.
        Retention is by eval_top1 (Orbax best_fn), so even if a crash mid-
        replacement leaves two steps in the slot, best_step() selects the
        better-SCORED one and the next save garbage-collects the loser."""
        return CheckpointManager(
            os.path.join(self.cfg.train.checkpoint_dir, "best"),
            max_to_keep=1, save_interval_steps=1, best_metric="eval_top1",
            save_retries=self.cfg.train.checkpoint_save_retries)

    def restore_or_init(self) -> TrainState:
        """Reference restart semantics (SURVEY.md §3.5): restore the latest
        checkpoint if one exists, else fresh init. The restored step counter
        reproduces the LR-schedule position inside the jitted step.
        `train.restore_from_best` restores the best-eval slot instead (by
        recorded score, not step number). Sets `self._restored_from_best` so
        fit() can gate branch-point truncation on an ACTUAL best-slot
        restore, never on the config flag alone."""
        self._restored_from_best = False
        self._restored_iterator_state = None
        # first collective of a restart can be the retopology resharding —
        # align ranks before it, not only before the step loop
        _align_cold_start()
        state = self.init_state()
        source = self.checkpoints
        if self.cfg.train.restore_from_best and self.checkpoints is not None:
            best = self._make_best_manager()
            if best.latest_step() is not None:
                source = best
            elif jax.process_index() == 0:
                self.logger.log("restore_from_best_unavailable",
                                {"fallback": "latest"})
        if source is not None and source.latest_step() is not None:
            # Topology-adaptive restore: the checkpoint may have been written
            # on a different mesh size or opt-state layout (replicated vs
            # ZeRO-1) — grow/shrink/migrate without retraining
            # (checkpoint/retopology.py; BASELINE north_star v4-8 → v4-128).
            from distributed_vgg_f_tpu.checkpoint.retopology import (
                restore_any_topology)
            opt_sh = (self._state_sharding().opt_state if self.zero1
                      else self._replicated)
            # ZeRO-3 (r21): params/EMA are the sharded flat vector — the
            # restore converts any saved layout onto this sharding; None
            # keeps the pre-r21 replicated-tree path
            params_sh = (self._state_sharding().params if self.zero3
                         else None)
            # EMA presence is decided from the SAVED tree's metadata, not by
            # try/except (an exception-driven retry buried unrelated restore
            # failures under a misleading structure-mismatch — code-review
            # r3). Four deterministic cases: match either way → plain
            # restore; saved-without/run-with → seed from restored params;
            # saved-with/run-without → restore then drop.
            # Resolve the restored step ONCE and pin every read to it — a
            # concurrent save landing between two independent best_step()
            # resolutions would skew metadata vs restore (code-review r3).
            # best_step() is integrity-verified: a truncated/corrupt newest
            # step falls back to the newest INTACT one (logged below); None
            # with checkpoints on disk means NOTHING intact — refuse to
            # silently reinitialize over a damaged but real training run.
            restore_step = source.best_step()
            if restore_step is None:
                raise CheckpointIntegrityError(
                    f"checkpoints exist under the configured directory but "
                    f"none passed integrity verification "
                    f"({(source.last_integrity_fallback or {}).get('skipped')}"
                    f") — refusing to train from scratch over a damaged "
                    f"run; restore the directory from a replica/backup or "
                    f"clear it to restart deliberately")
            if source.last_integrity_fallback is not None \
                    and jax.process_index() == 0:
                self.logger.log("checkpoint_integrity_fallback",
                                source.last_integrity_fallback)
            meta = source.state_metadata(restore_step)
            saved_has_ema = bool(jax.tree_util.tree_leaves(
                meta.get("ema_params") if hasattr(meta, "get") else None))
            want_ema = state.ema_params is not None
            ema_event = None  # logged after ONE step fetch below
            restore_extra = {}
            if saved_has_ema == want_ema:
                state, restore_extra = restore_any_topology(
                    source, state, self.tx,
                    opt_shardings=opt_sh,
                    target_padded=self._padded,
                    target_bucket_layout=self._bucket_layout,
                    params_tree_struct=self._params_struct,
                    params_shardings=params_sh,
                    step=restore_step)
            elif want_ema:
                # pre-EMA checkpoint into an EMA-enabled run
                tmpl = state.replace(ema_params=None, ema_batch_stats=None)
                restored, restore_extra = restore_any_topology(
                    source, tmpl, self.tx,
                    opt_shardings=opt_sh,
                    target_padded=self._padded,
                    target_bucket_layout=self._bucket_layout,
                    params_tree_struct=self._params_struct,
                    params_shardings=params_sh,
                    step=restore_step)
                # jnp.copy: the seed must be DISTINCT buffers — sharing the
                # params' buffers trips the train step's donation ("attempt
                # to donate the same buffer twice")
                state = restored.replace(
                    ema_params=jax.tree.map(jnp.copy, restored.params),
                    ema_batch_stats=jax.tree.map(jnp.copy,
                                                 restored.batch_stats))
                ema_event = "ema_seeded_from_params"
            else:
                # EMA checkpoint into a run with ema_decay=0: restore the
                # averages into params-shaped buffers, then drop them
                tmpl = state.replace(ema_params=state.params,
                                     ema_batch_stats=state.batch_stats)
                restored, restore_extra = restore_any_topology(
                    source, tmpl, self.tx,
                    opt_shardings=opt_sh,
                    target_padded=self._padded,
                    target_bucket_layout=self._bucket_layout,
                    params_tree_struct=self._params_struct,
                    params_shardings=params_sh,
                    step=restore_step)
                state = restored.replace(ema_params=None,
                                         ema_batch_stats=None)
                ema_event = "ema_dropped_on_restore"
            # Position-exact resume receipt (r18): the iterator-state blob
            # this checkpoint carried, if any — fit()'s resume dispatch
            # keys on its presence (receipt-absent = pre-r18 checkpoint =
            # the unchanged epoch-boundary replay path).
            if self.cfg.data.iterator_state.enabled:
                self._restored_iterator_state = (restore_extra or {}).get(
                    "iterator_state")
            self._restored_from_best = source is not self.checkpoints
            if jax.process_index() == 0:
                # ONE host sync for the whole restore event; the branch log
                # and the restore log share the fetched int (the repeated
                # int(jax.device_get(state.step)) here was a redundant
                # device round-trip per log line)
                restored_step = int(jax.device_get(state.step))
                if ema_event is not None:
                    self.logger.log(ema_event, {"step": restored_step})
                self.logger.log("restore",
                                {"step": restored_step,
                                 "best": source is not self.checkpoints})
        return state

    def _opt_layout_extra(self) -> dict:
        """The ZeRO-2 bucket-geometry receipt that rides EVERY checkpoint's
        `extra` JSON when the bucketed sharded exchange is on: a saved flat
        opt-state vector in the bucket-major layout is indistinguishable
        from the canonical one by shape, so restore
        (checkpoint/retopology.py) reads this to pick the right inverse
        permutation. Absent receipt = canonical layout (every pre-r14
        checkpoint). ZeRO-3 (r21) adds the `param_layout` receipt: the
        SAVED params are the flat vector too, and its kind
        (canonical_flat | bucketed_flat — the bucket geometry itself is the
        opt_layout receipt, one layout for both vectors) tells restore how
        to invert them; absent = params are a tree (every pre-r21
        checkpoint)."""
        extra = {}
        if self._bucket_layout is not None and self.zero1:
            extra["opt_layout"] = self._bucket_layout.describe()
        if self.zero3:
            extra["param_layout"] = {
                "kind": ("bucketed_flat" if self._bucket_layout is not None
                         else "canonical_flat"),
                "num_shards": self.num_shards,
                "total_padded": int(self._padded),
            }
        return extra

    def base_rng(self) -> jax.Array:
        # Built inside jit so the replicated output sharding also works
        # multi-process (device_put to non-addressable devices does not).
        # The dropout key uses the configured PRNG impl ("rbg" by default —
        # much cheaper random bits on TPU than threefry; see TrainConfig).
        seed = self.cfg.train.seed + 1
        impl = self.cfg.train.dropout_rng_impl
        return jax.jit(lambda: jax.random.key(seed, impl=impl),
                       out_shardings=self._replicated)()

    # ------------------------------------------------------------------ data
    def make_dataset(self, split: str = "train", data_cfg=None) -> Iterator:
        """`data_cfg` (r18) overrides the data section for THIS build only
        — the ResumableIngest rebuild factory re-enters here with a
        wire-flipped config, so the wrapped and unwrapped feed paths
        share one build_dataset call site and can never fork."""
        cfg = self.cfg
        state_dir, every = "", 0
        if split == "train" and cfg.train.checkpoint_dir:
            # Per-host iterator snapshots, written at the checkpoint cadence so
            # a snapshot exists for every resumable step (deterministic
            # ImageNet resume, SURVEY.md §5 data-iterator state).
            state_dir = f"{cfg.train.checkpoint_dir}/data_state/" \
                        f"host_{jax.process_index()}"
            every = cfg.train.checkpoint_every_steps
        return build_dataset(data_cfg if data_cfg is not None else cfg.data,
                             split, seed=cfg.train.seed,
                             num_shards=jax.process_count(),
                             shard_index=jax.process_index(),
                             state_dir=state_dir, snapshot_every=every,
                             num_classes=cfg.model.num_classes)

    def _make_train_ingest(self):
        """The trainer-owned train stream for fit(). With
        `data.iterator_state.enabled` (r18) it is wrapped in the
        cursor-counting ResumableIngest surface — the checkpoint blob's
        capture point and the position-exact rebuild the autotuner's wire
        knob actuates through. Kill-switched off, this returns exactly
        what make_dataset('train') returns — the r17 feed path,
        structurally identical (pinned in tests/test_iterator_state.py)."""
        cfg = self.cfg
        if not cfg.data.iterator_state.enabled:
            return self.make_dataset("train")
        from distributed_vgg_f_tpu.data.iterator_state import (
            ResumableIngest)
        return ResumableIngest(
            lambda dc: self.make_dataset("train", data_cfg=dc),
            cfg.data, seed=cfg.train.seed,
            batches_per_epoch=cfg.steps_per_epoch,
            label=cfg.data.service.label)

    def _save_extra(self, next_step: int) -> dict:
        """The host-state JSON riding every checkpoint's `extra`: the r14
        opt-layout receipt plus (r18) the schema-validated iterator-state
        blob captured at the step barrier — `next_step` is the batch the
        restored run will consume first."""
        extra = {"examples_seen":
                 next_step * self.cfg.data.global_batch_size,
                 **self._opt_layout_extra()}
        if self._ingest is not None:
            from distributed_vgg_f_tpu.telemetry import schema
            blob = self._ingest.capture_state(next_step)
            errors: list = []
            schema.validate_iterator_state_blob(blob, "iterator_state",
                                                errors)
            if errors:  # never let a receipt bug block a durable save
                if jax.process_index() == 0:
                    self.logger.log("iterator_state_capture_invalid",
                                    {"errors": errors[:3]})
            else:
                extra["iterator_state"] = blob
        return extra

    @staticmethod
    def _count_state_save(extra: Mapping) -> None:
        """`ingest_state/saves` counts blobs that made it into a DURABLE
        save — call only after the manager reported the save dispatched."""
        if "iterator_state" in extra:
            telemetry.inc("ingest_state/saves")

    def shard(self, batch: Mapping[str, np.ndarray]):
        return shard_host_batch(batch, self.mesh, self.data_axis)

    def _check_first_labels(self, it: Iterator) -> Iterator:
        """Pass-through that validates the FIRST host batch's labels against
        the model head (one host-side max; no per-step cost). Padding labels
        (< 0) are legal — only the upper bound can corrupt the CE gather."""
        first = True
        for batch in it:
            if first:
                first = False
                labels = np.asarray(batch["label"])
                nc = self.cfg.model.num_classes
                if labels.size and int(labels.max()) >= nc:
                    raise ValueError(
                        f"dataset yields label {int(labels.max())} but the "
                        f"model head has num_classes={nc}; out-of-range "
                        f"labels make the cross-entropy gather silently "
                        f"produce nan — align model.num_classes with the "
                        f"dataset's label space")
            yield batch

    # ---------------------------------------------------------------- elastic
    def _elastic_resize(self, next_step: int, state, ds, host_prefetch,
                        consensus):
        """Execute one live N→N−k resize (r19, parallel/elastic.py): plan
        against the flagged ranks, restore a FRESH ingest from the cursor
        blob, then swap mesh/specs/optimizer/steps for the survivor
        topology and reshard the state in place. Ordered so every
        refusable step happens BEFORE any live object is mutated — an
        `ElasticDegraded` raise leaves the r18 stop path untouched.
        Returns the rebuilt `(state, ds, host_prefetch, rng, meter)` fit()
        loop carriers."""
        import dataclasses as _dc

        from distributed_vgg_f_tpu.data.iterator_state import (
            restore_from_blob)
        from distributed_vgg_f_tpu.data.prefetch import maybe_prefetch
        from distributed_vgg_f_tpu.parallel import elastic
        from distributed_vgg_f_tpu.resilience.errors import ElasticDegraded

        cfg = self.cfg
        # WHO died: the rank-targeted chaos token when armed, else the
        # consensus gather (real multi-host SIGTERM — which plan_resize
        # then refuses as multi-controller; the checkpointed restart onto
        # the survivor slice covers that fleet shape).
        dead: tuple = ()
        if self.faults is not None and self.faults.preempt_ranks:
            dead = self.faults.preempt_ranks
        elif consensus is not None:
            dead = consensus.flagged_ranks
        plan = elastic.plan_resize(
            self.mesh, self.data_axis, dead,
            elastic_cfg=cfg.mesh.elastic,
            global_batch=cfg.data.global_batch_size,
            have_cursor=self._ingest is not None)

        # Pure cursor handoff, decided before any teardown: capture the
        # position (zero replayed batches — the blob names the exact next
        # item) and restore it into a FRESH ingest; ResumableIngest
        # refuses restore_state once started, so a new surface over the
        # new topology is the supported path (data/iterator_state.py).
        blob = self._ingest.capture_state(next_step)
        fresh = self._make_train_ingest()
        receipt = restore_from_blob(
            fresh, blob, step=next_step,
            expect={"seed": cfg.train.seed,
                    "batches_per_epoch": cfg.steps_per_epoch,
                    "ingest": cfg.data.service.label})
        if receipt is None:
            raise ElasticDegraded(
                "cursor_restore_refused",
                f"iterator-state blob did not restore into a fresh ingest "
                f"at step {next_step} — resizing without the cursor would "
                "replay or skip batches")

        # Evacuation accounting against the OLD geometry: each dead rank
        # owned one 1/N slice of every data-axis-sharded opt-state leaf.
        old_layout = self._bucket_layout
        old_specs = self._state_specs
        evac = 0
        if old_specs is not None:
            evac = len(plan.dead_ranks) * sum(
                1 for s in jax.tree.leaves(
                    old_specs.opt_state,
                    is_leaf=lambda x: isinstance(x, P))
                if s == P(self.data_axis))

        # --- survivor topology: rebuild exactly what __init__ built, in
        # the same order (mesh → flags → specs → steps), so the resized
        # trainer is indistinguishable from one constructed at size N−k.
        old_params_struct = self._params_struct
        self.mesh = elastic.shrink_mesh(self.mesh, self.data_axis, plan)
        self.num_shards = plan.new_size
        self.zero1 = bool(cfg.mesh.shard_opt_state) and self.num_shards > 1
        self.zero2 = self.zero1 and bool(cfg.mesh.shard_gradients)
        self.zero3 = self.zero2 and bool(cfg.mesh.shard_params)
        self._replicated = NamedSharding(self.mesh, P())
        # _make_state_specs only assigns the layout on the bucketed
        # branch — reset first or a dp/zero1 resize would keep the stale
        # bucket geometry in the checkpoint receipts
        self._bucket_layout = None
        if plan.lr_scale != 1.0:
            self.tx, self.schedule = build_optimizer(
                cfg, lr_scale=plan.lr_scale)
        self._state_specs = self._make_state_specs()
        self._build_steps()
        # the params TREE geometry: under ZeRO-3 state.params is the flat
        # shard vector, so the tree comes from the specs build (identical
        # across topologies — it is a function of the model alone); the
        # pre-resize struct covers a zero1+ → dp downgrade to one shard
        params_struct = (self._params_struct or old_params_struct
                         or jax.eval_shape(lambda p: p, state.params))
        opt_sh = (self._state_sharding().opt_state if self.zero1
                  else self._replicated)
        state = elastic.reshard_train_state(
            state, self.tx, params_struct=params_struct,
            target_padded=self._padded,
            src_bucket_layout=old_layout,
            target_bucket_layout=self._bucket_layout,
            replicated=self._replicated, opt_shardings=opt_sh,
            target_params_padded=self._padded if self.zero3 else None,
            params_shardings=(self._state_sharding().params if self.zero3
                              else None))

        # --- feed over the new mesh: tear down the old chain, clear the
        # fired preempt injector (its >= predicate stays true forever), and
        # re-wrap the surviving injectors at the new start step.
        if hasattr(ds, "close"):
            ds.close()
        if host_prefetch is not None:
            host_prefetch.close()
        if self.autotuner is not None:
            # the controller's knobs bind to the torn-down pipeline
            # objects — disarm rather than steer ghosts (a later fit
            # re-arms over the live chain)
            from distributed_vgg_f_tpu.telemetry import exporter as _exp
            _exp.set_autotune_source(None)
            self.autotuner = None
            if jax.process_index() == 0:
                self.logger.log("elastic_autotune_disarmed",
                                {"step": next_step})
        self._ingest = fresh
        if self.faults is not None:
            self.faults = _dc.replace(self.faults, preempt_step=None,
                                      preempt_ranks=())
        host_batches = fresh
        if self.faults is not None and self.faults.has_data_faults:
            host_batches = self.faults.wrap_iterator(host_batches,
                                                     start_step=next_step)
        if plan.batch_policy == "scale_lr":
            host_batches = elastic.trim_batches(
                host_batches, plan, cfg.data.global_batch_size)
        host_batches = self._check_first_labels(host_batches)
        new_ds = maybe_prefetch(host_batches, self.mesh, self.data_axis,
                                buffer_size=cfg.train.prefetch_to_device,
                                batch_timeout_s=cfg.train.data_timeout_s,
                                timeout_retries=cfg.train.data_timeout_retries)

        # --- receipts
        st = self._elastic_stats
        reassigned = (len(plan.dead_ranks)
                      if plan.batch_policy == "keep_global" else 0)
        st["resizes"] += 1
        st["evacuated_shards"] += evac
        st["reassigned_data_shards"] += reassigned
        st["topology"] = plan.topology_label
        st["lr_scale"] = plan.lr_scale
        telemetry.inc("elastic/resizes")
        if evac:
            telemetry.inc("elastic/evacuated_shards", evac)
        if reassigned:
            telemetry.inc("elastic/reassigned_data_shards", reassigned)
        if jax.process_index() == 0:
            self.logger.log("elastic_resize", {
                "step": next_step, **plan.describe(),
                "evacuated_shards": evac,
                "reassigned_data_shards": reassigned,
                "cursor": receipt})
            if plan.lr_scale != 1.0:
                # the schedule receipt: what the LR rescale actually did
                self.logger.log("elastic_lr_rescale", {
                    "step": next_step, "lr_scale": plan.lr_scale,
                    "old_global_batch": cfg.data.global_batch_size,
                    "new_global_batch": int(round(
                        cfg.data.global_batch_size * plan.lr_scale))})
        return (state, new_ds, None, self.base_rng(),
                ThroughputMeter(self.mesh.devices.size))

    # ------------------------------------------------------------------ loops
    def fit(self, state: TrainState | None = None, *, num_steps: int | None = None,
            dataset: Iterator | None = None,
            eval_dataset: Iterator | None = None) -> TrainState:
        cfg = self.cfg
        branched = False
        if state is None:
            state = self.restore_or_init()
            # only an ACTUAL best-slot restore branches the chain — a fit()
            # called with an explicit state (fresh init, analysis restore)
            # must never delete checkpoints ahead of that state's step
            branched = self._restored_from_best
        rng = self.base_rng()
        total = num_steps if num_steps is not None else cfg.total_steps
        start_step = int(jax.device_get(state.step))
        if branched and self.checkpoints is not None:
            # Branch-point truncation: TRAINING from the best slot abandons
            # the chain beyond it. Stale steps ahead of the branch must go
            # NOW — replacing them lazily on collision would leave a crash
            # window where latest_step() still returns pre-branch state
            # (code-review r3). Eval/predict never call fit, so read-only
            # uses of restore_from_best keep the full chain.
            stale = [s for s in self.checkpoints.all_steps() if s > start_step]
            for s in stale:
                self.checkpoints.delete(s)
            if stale and jax.process_index() == 0:
                self.logger.log("branch_truncate", {
                    "from_step": start_step, "deleted_steps": stale})
        host_ds = dataset if dataset is not None \
            else self._make_train_ingest()
        from distributed_vgg_f_tpu.data.iterator_state import (
            ResumableIngest, restore_from_blob)
        self._ingest = host_ds if isinstance(host_ds, ResumableIngest) \
            else None
        if dataset is None and 0 < start_step < total:
            # Deterministic resume (SURVEY.md §5): restore the data iterator to
            # "next batch = start_step" so the post-resume stream is identical
            # to the uninterrupted one. Dispatch (r18): a checkpoint carrying
            # the iterator-state receipt resumes POSITION-EXACTLY through the
            # blob (validated identity + the read-ahead transplant — zero
            # replayed batches, receipted); a receipt-absent (pre-r18)
            # checkpoint takes the unchanged r17 path — O(1)
            # iterator-snapshot/seek restore when the pipeline supports it,
            # else replay the seeded iterator (cheap for numpy/native
            # iterators).
            restored = False
            if self._ingest is not None \
                    and self._restored_iterator_state is not None:
                receipt = restore_from_blob(
                    self._ingest, self._restored_iterator_state,
                    step=start_step,
                    expect={"seed": cfg.train.seed,
                            "batches_per_epoch": cfg.steps_per_epoch,
                            "ingest": cfg.data.service.label})
                restored = receipt is not None
                if restored and jax.process_index() == 0:
                    self.logger.log("iterator_state_restore", receipt)
            if not restored and getattr(host_ds, "supports_state", False):
                restored = host_ds.restore_state(start_step)
            if jax.process_index() == 0:
                self.logger.log("data_iterator_restore", {
                    "step": start_step, "restored": restored})
            if not restored and cfg.train.resume_data_fast_forward:
                for _ in range(start_step):
                    next(host_ds)
                if jax.process_index() == 0:
                    self.logger.log("data_fast_forward", {"batches": start_step})
        # First-batch label-range guard, for EVERY pipeline: an out-of-range
        # label against the model head is a CE gather past the logits and
        # surfaces as loss=nan with finite grads, nothing louder (found r3
        # via model.num_classes override + synthetic labels; the same
        # mismatch is reachable with any real dataset, code-review r3).
        # Bind the loader's error counter BEFORE wrapping — the generator
        # wrapper has no decode_errors attribute (code-review r3).
        decode_errors_src = getattr(host_ds, "decode_errors", None)
        # Closed-loop ingest autotuner (r11): gate EVERYTHING — the
        # host-prefetch wrapper stage included — on the single activation
        # predicate, so config-off / DVGGF_AUTOTUNE=0 is byte-identical to
        # controller-absent. Caller-supplied datasets are never touched
        # (their read-ahead semantics belong to the caller), and without
        # the stall attributor there is no verdict stream to steer by.
        from distributed_vgg_f_tpu.data.autotune import autotune_active
        autotune_on = (dataset is None
                       and autotune_active(cfg.data.autotune)
                       and cfg.telemetry.enabled
                       and cfg.telemetry.stall_attribution)
        raw_ds = host_ds  # the unwrapped loader the thread knob binds to
        host_prefetch = None
        if autotune_on:
            # resizable read-ahead stage between the host loader and the
            # device-prefetch worker — the controller's data.prefetch knob
            # (constructed AFTER the resume seek above: its worker starts
            # drawing immediately)
            from distributed_vgg_f_tpu.data.prefetch import (
                HostPrefetchIterator)
            host_prefetch = HostPrefetchIterator(
                host_ds, depth=max(1, cfg.data.prefetch))
            host_ds = host_prefetch
        if self.faults is not None and self.faults.has_data_faults:
            # chaos harness: NaN/stall/crash injectors wrap the host stream
            # (resilience/faults.py) — start_step keeps the 1-based fault
            # steps aligned with training steps after a resume
            host_ds = self.faults.wrap_iterator(host_ds,
                                                start_step=start_step)
        host_ds = self._check_first_labels(host_ds)
        # Device prefetch: a background thread lands sharded batches in HBM
        # ahead of compute, so step start never blocks on the H2D copy. Only a
        # trainer-owned iterator is prefetched — the thread reads ahead, which
        # would silently consume extra batches from a caller-supplied one.
        # The prefetcher doubles as the data watchdog (train.data_timeout_s):
        # a stalled/dead loader raises DataStallError instead of hanging.
        from distributed_vgg_f_tpu.data.prefetch import maybe_prefetch
        prefetch_buf = (0 if dataset is not None
                        else cfg.train.prefetch_to_device)
        if prefetch_buf == 0 and cfg.train.data_timeout_s > 0 \
                and jax.process_index() == 0:
            # the sync fallback has no thread to time-bound — a configured
            # watchdog that silently does nothing is the one state the
            # resilience layer must never be in (code-review)
            self.logger.log("data_watchdog_inactive", {
                "reason": ("caller-supplied dataset" if dataset is not None
                           else "train.prefetch_to_device=0"),
                "data_timeout_s": cfg.train.data_timeout_s,
                "hint": "the per-batch timeout needs the device-prefetch "
                        "thread; stalls will hang instead of raising "
                        "DataStallError"})
        ds = maybe_prefetch(host_ds, self.mesh, self.data_axis,
                            buffer_size=prefetch_buf,
                            batch_timeout_s=cfg.train.data_timeout_s,
                            timeout_retries=cfg.train.data_timeout_retries)

        # Arm the autotuner over the live pipeline objects. Knob factories
        # return None when a surface is absent (tf.data loader without a
        # resize ABI, sync-sharding fallback without a device ring, restart
        # path not dispatching) — the controller simply steers what exists
        # and receipts the rest as unbound. The wire knob (r18): bound
        # through the ResumableIngest rebuild surface whenever a
        # position-exact rebuild is available (native imagenet, local
        # ingest) — escalation rebuilds the live source host_f32→u8 AT the
        # captured cursor, read-ahead batches keep their old wire (the
        # device finish dispatches per batch on dtype), and the stream
        # continues byte-identically. This retires the r11 "trainer
        # deliberately leaves it unbound" receipt.
        self.autotuner = None
        from distributed_vgg_f_tpu.telemetry import exporter as _exporter
        if autotune_on:
            from distributed_vgg_f_tpu.data import autotune as _at
            at_cfg = cfg.data.autotune
            # auto (0) resolves to min(16, vCPUs), but never below the
            # configured floor — an inverted rail (min > max) would make
            # every escalation read blocked:rail with the knob ostensibly
            # healthy (the silently-never-steers state the config
            # validator rejects for explicit rails)
            max_threads = at_cfg.max_threads or max(
                at_cfg.min_threads, min(16, os.cpu_count() or 1))
            knobs = [
                _at.thread_knob(raw_ds, min_value=at_cfg.min_threads,
                                max_value=max_threads),
                _at.host_prefetch_knob(host_prefetch,
                                       min_value=at_cfg.min_prefetch,
                                       max_value=at_cfg.max_prefetch),
                _at.device_ring_knob(
                    ds, min_value=at_cfg.min_prefetch_to_device,
                    max_value=at_cfg.max_prefetch_to_device),
                _at.fanout_knob(max_value=at_cfg.max_restart_fanout),
            ]
            if self._ingest is not None:
                # escalation order: the wire is the LAST lever (it changes
                # the batch format; depths/threads are cheaper first moves)
                knobs.append(self._ingest.wire_knob())
            self.autotuner = _at.IngestAutotuner(at_cfg, knobs)
            _exporter.set_autotune_source(self.autotuner.describe)
            if jax.process_index() == 0:
                armed = self.autotuner.describe()
                armed.pop("history", None)
                self.logger.log("autotune_armed", armed)
        else:
            # a prior fit's controller must not keep serving /autotunez
            # for a run that has none
            _exporter.set_autotune_source(None)

        num_chips = self.mesh.devices.size
        meter = ThroughputMeter(num_chips)
        if jax.process_index() == 0:
            self.logger.log("start", {
                "config": cfg.name, "total_steps": total,
                # the configured ingest wire; 'u8' may still have fallen
                # back per-pipeline (data/imagenet.py logs the warning)
                "wire": cfg.data.wire,
                # disaggregated-ingest topology (r16): 'local' or
                # 'service_<N>w' — the run's ingest basis label, matching
                # the regression sentinel's Basis.ingest key
                "ingest": cfg.data.service.label,
                # fused on-device augmentation state (r13): enabled means
                # the device owns flips and the host pipelines never flip
                "augment": cfg.data.augment.enabled,
                **mesh_topology_report(self.mesh)})

        # Telemetry window state (telemetry/): the step log's stall verdict
        # and counter deltas are computed per log window. Pre-creating the
        # core counters makes "zero events" visible as 0 rather than as a
        # missing key, and the delta() call re-baselines the "trainer"
        # consumer so the first window doesn't report process-lifetime
        # totals.
        tele = cfg.telemetry
        reg = telemetry.get_registry()
        rec = telemetry.get_recorder()
        from distributed_vgg_f_tpu.telemetry.flight import get_flight
        flight = get_flight()
        window_start_ns = time.monotonic_ns()
        attributor = None
        if tele.enabled:
            for name in ("resilience/nonfinite_skips",
                         "resilience/data_stall_errors",
                         "checkpoint/saves", "step/dispatched"):
                reg.counter(name)
            reg.set_gauge("decode/errors_total", 0)
            if self.device_augment is not None:
                # augment receipts (r13): steps trained with the fused
                # stage armed (counted per log window) + the armed gauge —
                # the counter-table rows the drift guard cross-checks
                reg.counter("augment/steps")
                reg.set_gauge("augment/enabled", 1)
            # comm receipts (r14): pre-create so "zero exchanges" reads as
            # 0, not a missing key; the step wrapper increments per
            # dispatch and sets the static exchange-shape gauges
            reg.counter("comm/exchanges")
            reg.counter("comm/wire_bytes")
            if cfg.mesh.elastic.enabled:
                # elastic receipts (r19): pre-create so a run that never
                # resizes reads 0, not a missing key — the counter-table
                # rows the drift guard cross-checks
                for name in ("elastic/resizes", "elastic/evacuated_shards",
                             "elastic/reassigned_data_shards",
                             "elastic/downtime_ns"):
                    reg.counter(name)
            reg.delta("trainer")
            if tele.stall_attribution:
                attributor = telemetry.StallAttributor(
                    registry=reg, recorder=rec,
                    infeed_threshold=tele.infeed_threshold,
                    checkpoint_threshold=tele.checkpoint_threshold)

        profiler = None
        if cfg.train.profile:
            from distributed_vgg_f_tpu.utils.profiling import StepProfiler
            profiler = StepProfiler(
                cfg.train.profile_dir,
                start_step=start_step + cfg.train.profile_start_step,
                num_steps=cfg.train.profile_num_steps)

        eval_every = cfg.train.eval_every_steps or cfg.steps_per_epoch
        # Graceful preemption (SIGTERM = the TPU-VM/k8s grace signal): the
        # handler only sets a flag; the loop reacts at a safe point — after a
        # completed step — with a forced checkpoint and a clean stop.
        # Multi-host: a per-step asynchronous consensus collective
        # (parallel/preempt.py) stops every host at the same step within
        # ~3 steps of the signal, independent of log_every.
        preempt_flag = {"set": False}
        consensus = None
        if cfg.train.handle_preemption and jax.process_count() > 1:
            from distributed_vgg_f_tpu.parallel.preempt import (
                PreemptConsensus)
            consensus = PreemptConsensus(self.mesh, self.data_axis)
        # Best-eval tracking: single replaced slot under <checkpoint_dir>/best
        # (train.track_best_eval). A resumed run must not regress the durable
        # best with its first eval, so the threshold seeds from the slot.
        if self.best_checkpoints is None and self.checkpoints is not None \
                and cfg.train.track_best_eval and eval_dataset is not None:
            self.best_checkpoints = self._make_best_manager()
        best_top1 = float("-inf")
        if self.best_checkpoints is not None:
            best_top1 = float((self.best_checkpoints.latest_extra() or {})
                              .get("eval_top1", float("-inf")))
        old_sigterm = None
        if cfg.train.handle_preemption:
            import signal

            def _on_sigterm(signum, frame):
                preempt_flag["set"] = True

            try:
                old_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
            except ValueError:
                old_sigterm = None  # not the main thread — feature disabled
        # The native loader zero-fills corrupt/unreadable images instead of
        # raising (a single bad file must not kill a long run) — so its error
        # counter MUST be surfaced, or quality degradation is invisible.
        decode_errors = decode_errors_src
        # Non-finite step guard (resilience/guard.py): the jitted step
        # reports its all-reduced isfinite verdict as metrics["bad_step"];
        # the guard counts consecutive skips via a lagged poll (never blocks
        # dispatch) and aborts with a NonFiniteStepError diagnostic.
        guard = None
        if cfg.train.skip_nonfinite:
            guard = NonFiniteGuard(cfg.train.max_nonfinite_steps,
                                   logger=self.logger)
        _align_cold_start()
        if self.exporter is not None:
            # first heartbeat BEFORE the first step: a probe hitting
            # /healthz during compile must read "ok, step N, young age",
            # not "idle" (which a fleet health-checker treats as not-yet-
            # scheduled and reaps)
            self.exporter.heartbeat(start_step)
        # One try around the loop AND the end-of-run saves: telemetry is
        # exported on EVERY exit — clean completion (after the final forced
        # save, whose checkpoint spans/counters are often the longest
        # blocking interval of the run and must be IN the artifacts), a
        # crash mid-loop, or a crash in the final save itself: the
        # telemetry of a run that died checkpointing is the telemetry you
        # most need on disk (code-review r8 x2). A crash additionally dumps
        # the flight recorder's black box (telemetry/flight.py) BEFORE the
        # export — the last-N-windows artifact is the triage entry point.
        try:
            last_metrics = {}
            host_wait = 0.0  # time blocked waiting for the input pipeline
            ckpt_wait = 0.0  # time blocked in checkpoint machinery this window
            eval_wait = 0.0  # time inside periodic eval passes this window
            guard_seen = 0   # nonfinite skips already attributed to a window
            decode_errors_seen = 0
            window_first_step = start_step  # for the augment/steps delta
            preempted = False
            elastic_t0 = None  # monotonic_ns at consensus-fire; downtime clock
            try:
                for step in range(start_step, total):
                    if profiler is not None:
                        # device_get drains the async dispatch queue so the trace
                        # window brackets device execution, not host dispatch.
                        profiler.step(step, sync=lambda: jax.device_get(state.step))
                    t_feed = time.monotonic_ns()
                    batch = next(ds)  # already sharded on-device by the prefetcher
                    dt_feed = time.monotonic_ns() - t_feed
                    host_wait += dt_feed / 1e9
                    # "infeed" span: consumer-side block. Overlaps the prefetch
                    # iterator's own wait span — same category, and the span
                    # occupancy union (telemetry/stall.py) dedupes overlaps, so
                    # the sync fallback path is covered without double-counting
                    # the threaded one.
                    rec.record("next_batch", "infeed", t_feed, dt_feed)
                    state, metrics = self.train_step(state, batch, rng)
                    if elastic_t0 is not None:
                        # the resize is OVER only when the first survivor-mesh
                        # step has EXECUTED — block on its metrics, then close
                        # the downtime receipt (consensus-fire → first step)
                        jax.block_until_ready(metrics)
                        dt_rs = int(time.monotonic_ns() - elastic_t0)
                        elastic_t0 = None
                        self._elastic_stats["downtime_ns"] += dt_rs
                        telemetry.inc("elastic/downtime_ns", dt_rs)
                        if jax.process_index() == 0:
                            self.logger.log("elastic_downtime", {
                                "step": step + 1, "downtime_ns": dt_rs})
                    if guard is not None:
                        guard.observe(step + 1, metrics["bad_step"])
                    meter.update(cfg.data.global_batch_size)
                    if (step + 1) % cfg.train.log_every == 0 or step + 1 == total:
                        # device_get syncs: throughput numbers include real device
                        # time.
                        last_metrics = {k: float(v) for k, v in
                                        jax.device_get(metrics).items()}
                        entry = {"step": step + 1, **last_metrics,
                                 **meter.snapshot(),
                                 # host_wait_fraction: share of wall time this
                                 # window spent blocked on the input pipeline —
                                 # ~0 when the device-prefetch hides the host
                                 # path, →1 when host-bound (SURVEY.md §7
                                 # input-pipeline watch-item).
                                 "host_wait_fraction": round(
                                     host_wait / meter.elapsed, 4)}
                        if guard is not None and guard.total:
                            # cumulative skipped (non-finite) steps this run —
                            # quality degradation must be visible in the log
                            # stream, like decode_errors below
                            entry["nonfinite_skips"] = guard.total
                        if callable(decode_errors) or jax.process_count() > 1:
                            # The counter is process-local; sum across hosts so a
                            # corrupt shard on ANY host is visible in process 0's
                            # log (one tiny allgather per log window). EVERY host
                            # participates in the collective — contributing 0 when
                            # its own pipeline has no counter (e.g. it fell back
                            # to tf.data) — or hosts would deadlock.
                            de = decode_errors() if callable(decode_errors) else 0
                            if jax.process_count() > 1:
                                from jax.experimental import multihost_utils
                                de = int(np.asarray(
                                    multihost_utils.process_allgather(
                                        np.asarray(de, np.int64))).sum())
                            if de > 0:
                                entry["data_decode_errors"] = de
                            if de > decode_errors_seen and \
                                    jax.process_index() == 0:
                                self.logger.log("decode_errors", {
                                    "step": step + 1, "total": de,
                                    "new": de - decode_errors_seen})
                            decode_errors_seen = max(decode_errors_seen, de)
                            if tele.enabled:
                                reg.set_gauge("decode/errors_total", de)
                        # Stall attribution + counter deltas: the window's wall
                        # time is attributed to infeed / checkpoint / guard /
                        # compute, and every registry counter that moved this
                        # window (decode stats via poller, prefetch, resilience,
                        # checkpoint, faults) rides the SAME record — one JSONL
                        # stream, one diagnosis per window. Computed on EVERY
                        # rank since the flight recorder (telemetry/flight.py)
                        # retains it — each rank's black box must carry its
                        # OWN windows, and a crash is exactly when rank 0's
                        # view of another host is not enough. (This walks
                        # back the r8 rank-0-only delta: one poller sweep
                        # per rank per LOG WINDOW buys per-rank crash
                        # forensics — the receipt stays inside the <2%
                        # budget, benchmarks/runs/.)
                        stall_record = None
                        window_wall = max(1e-9, meter.elapsed - eval_wait)
                        if attributor is not None:
                            guard_total = (guard.total if guard is not None
                                           else 0)
                            # eval passes inflate the window's wall time
                            # without touching any wait bucket — left in,
                            # they dilute every fraction toward 0 and
                            # stamp an eval-cratered window
                            # "compute_bound" (code-review r8)
                            stall_record = attributor.window(
                                wall_s=window_wall,
                                infeed_wait_s=host_wait,
                                checkpoint_wait_s=ckpt_wait,
                                guard_skips=guard_total - guard_seen)
                            if eval_wait > 0:
                                stall_record["eval_seconds"] = round(
                                    eval_wait, 3)
                            guard_seen = guard_total
                        # Closed-loop actuation (r11): ONE bounded observe
                        # per log window, on EVERY rank — each process
                        # tunes its own pipeline (heterogeneous host
                        # classes converge to their own knob settings).
                        # The returned record is the JSONL receipt.
                        autotune_record = None
                        if self.autotuner is not None:
                            autotune_record = self.autotuner.observe(
                                stall_record)
                        if self.device_augment is not None and tele.enabled:
                            # every step this window carried the fused
                            # augmentation — the counter rides the same
                            # per-window delta as the rest of the receipts
                            telemetry.inc("augment/steps",
                                          (step + 1) - window_first_step)
                        window_first_step = step + 1
                        window_counters = None
                        critical_path = None
                        if tele.enabled:
                            window_counters = reg.delta("trainer")
                            now_ns = time.monotonic_ns()
                            occupancy = telemetry.occupancy_from_spans(
                                rec.snapshot(), window_start_ns, now_ns)
                            flight.record_window(
                                step=step + 1, wall_s=window_wall,
                                stall=stall_record,
                                counters=window_counters,
                                spans=occupancy)
                            # Critical-path split (r22): the window's wall
                            # clock attributed {infeed, checkpoint,
                            # exchange, device} from the SAME occupancy
                            # the flight window records. Sequential clamp
                            # — each bucket takes at most what the earlier
                            # buckets left — so the four parts sum to the
                            # window EXACTLY by construction; device is
                            # the residual (unspanned host time rides it,
                            # same convention as stall's compute_bound).
                            span_wall = max(
                                0.0, (now_ns - window_start_ns) / 1e9)
                            infeed_s = min(
                                occupancy.get("infeed", 0.0), span_wall)
                            ckpt_s = min(
                                occupancy.get("checkpoint", 0.0),
                                span_wall - infeed_s)
                            exchange_s = min(
                                occupancy.get("coord", 0.0),
                                span_wall - infeed_s - ckpt_s)
                            device_s = (span_wall - infeed_s - ckpt_s
                                        - exchange_s)
                            parts = {"infeed": infeed_s,
                                     "checkpoint": ckpt_s,
                                     "exchange": exchange_s,
                                     "device": device_s}
                            critical_path = {
                                "window_s": round(span_wall, 6),
                                "infeed_s": round(infeed_s, 6),
                                "device_s": round(device_s, 6),
                                "checkpoint_s": round(ckpt_s, 6),
                                "exchange_s": round(exchange_s, 6),
                                "dominant": max(parts, key=parts.get),
                            }
                            window_start_ns = now_ns
                            if self.exporter is not None:
                                self.exporter.heartbeat(step + 1)
                        if jax.process_index() == 0:
                            if stall_record is not None:
                                entry["stall"] = stall_record
                            if window_counters is not None:
                                entry["counters"] = window_counters
                            if critical_path is not None:
                                entry["critical_path"] = critical_path
                            if autotune_record is not None:
                                entry["autotune"] = autotune_record
                            if self.device_augment is not None:
                                # schema-validated augment block
                                # (telemetry/schema.py): the per-window
                                # receipt that this run's diversity was
                                # device-side, host flips disabled
                                entry["augment"] = \
                                    cfg.data.augment.describe()
                            # schema-validated comm block (r14): the
                            # gradient-exchange shape this run actually
                            # traced — sharding basis, bucket count, wire
                            # bytes — single-sourced from the step's
                            # trace-time geometry receipt
                            comm_meta = getattr(self.train_step,
                                                "comm_meta", None)
                            if comm_meta:
                                entry["comm"] = dict(comm_meta)
                            if self._ingest is not None:
                                # schema-validated iterator_state block
                                # (r18): the window's stream position —
                                # trainer cursor, source cursor, in-flight
                                # read-ahead, rebuild count, live wire
                                entry["iterator_state"] = \
                                    self._ingest.window_receipt(step + 1)
                            if cfg.mesh.elastic.enabled:
                                # schema-validated elastic block (r19): the
                                # window's topology + resize receipts —
                                # emitted only when the kill switch is on,
                                # so a disabled run's JSONL is byte-shaped
                                # like r18's
                                est = self._elastic_stats
                                entry["elastic"] = {
                                    "topology": est["topology"],
                                    "batch_policy":
                                        cfg.mesh.elastic.batch_policy,
                                    "resizes": est["resizes"],
                                    "downtime_ns": est["downtime_ns"],
                                    "evacuated_shards":
                                        est["evacuated_shards"],
                                    "reassigned_data_shards":
                                        est["reassigned_data_shards"],
                                    "lr_scale": est["lr_scale"]}
                            self.logger.log("train", entry)
                        meter.reset()
                        host_wait = 0.0
                        ckpt_wait = 0.0
                        eval_wait = 0.0
                    if eval_dataset is not None and (step + 1) % eval_every == 0:
                        t_ev = time.monotonic()
                        result = self.evaluate(state, eval_dataset, step=step + 1)
                        eval_wait += time.monotonic() - t_ev
                        # best-eval tracking: one replaced slot under best/. The
                        # psum'd eval result is identical on every host, so all
                        # hosts take the collective save branch together.
                        if self.best_checkpoints is not None and \
                                result["eval_top1"] > best_top1:
                            best_extra = {"eval_top1": result["eval_top1"],
                                          "eval_top5": result["eval_top5"],
                                          "step": step + 1,
                                          # the layout + iterator-state
                                          # receipts ride the best slot
                                          # too: restore_from_best (and a
                                          # branch resumed from it) must
                                          # read the same geometry and
                                          # stream position as a latest
                                          # restore
                                          **self._save_extra(step + 1)}
                            best_metrics = {"eval_top1": result["eval_top1"]}
                            # replace_on_collision: a resumed run re-reaching the
                            # slot's step number must replace the stale entry —
                            # the best-metric manager stages the replacement at
                            # an unused index so the durable best is never gone
                            # mid-replacement (checkpoint/manager.py `save`).
                            t_ck = time.monotonic()
                            saved = self.best_checkpoints.save(
                                state, force=True, extra=best_extra,
                                metrics=best_metrics, replace_on_collision=True)
                            ckpt_wait += time.monotonic() - t_ck
                            if saved:
                                self._count_state_save(best_extra)
                                # only advance the threshold once the slot
                                # actually holds this model
                                best_top1 = result["eval_top1"]
                                if jax.process_index() == 0:
                                    self.logger.log("best_checkpoint", {
                                        "step": step + 1,
                                        "eval_top1": result["eval_top1"]})
                    if self.checkpoints is not None:
                        # manager applies save_interval_steps; async, non-blocking.
                        # replace_on_collision: a run branched from the best slot
                        # (restore_from_best) re-reaches step numbers the stale
                        # chain already holds — those must be overwritten or a
                        # crash mid-branch would resume from pre-branch state.
                        t_ck = time.monotonic()
                        cadence_extra = self._save_extra(step + 1)
                        if self.checkpoints.save(
                                state, extra=cadence_extra,
                                replace_on_collision=True):
                            self._count_state_save(cadence_extra)
                        ckpt_wait += time.monotonic() - t_ck
                    # Injected preemption (fault_injection "preempt@N"): raises
                    # the same local flag a real SIGTERM would, so the full stop
                    # path — consensus collective included on multi-host — is
                    # exercised without an actual signal.
                    if self.faults is not None and \
                            self.faults.preempt_now(step + 1):
                        if not preempt_flag["set"]:
                            # announce the injector in the fault/ namespace like
                            # the data injectors do (first crossing only — the
                            # >= predicate stays true every later step)
                            telemetry.inc("fault/preempt")
                        preempt_flag["set"] = True
                    # Preemption stop-consensus: single-host reacts immediately;
                    # multi-host polls the per-step async consensus collective
                    # (every host at the same loop index — a lone host acting on
                    # its local flag would strand the others in the collective
                    # save). Gated on the CONFIG flag, which is identical across
                    # hosts — gating on whether the handler installed would not
                    # be.
                    stop = False
                    if cfg.train.handle_preemption:
                        stop = (consensus.poll(preempt_flag["set"])
                                if consensus is not None else preempt_flag["set"])
                    if stop:
                        if self.checkpoints is not None:
                            # the preempt save carries the iterator-state
                            # blob like every other save — the restarted
                            # incarnation (parallel/preempt.py semantics)
                            # resumes position-exactly through the same
                            # dispatch as any other restore. It is written
                            # BEFORE an elastic resize is attempted: the
                            # durable fallback must exist whether the
                            # resize succeeds, degrades, or dies.
                            preempt_extra = self._save_extra(step + 1)
                            saved = self.checkpoints.save(
                                state, force=True, extra=preempt_extra,
                                replace_on_collision=True)
                            if saved:
                                self._count_state_save(preempt_extra)
                            self.checkpoints.wait()
                            if not saved and jax.process_index() == 0:
                                self.logger.log("checkpoint_save_dropped", {
                                    "step": step + 1, "forced": True})
                        if cfg.mesh.elastic.enabled:
                            # Live resize (r19, parallel/elastic.py): keep
                            # training on the survivors. A refused plan
                            # degrades to the r18 stop path below with the
                            # NAMED elastic_degraded_restart flight class —
                            # never unhandled_exception. The downtime clock
                            # opens HERE, after the forced save: the durable
                            # fallback is the shared prefix of BOTH recovery
                            # paths (a restart restores from this exact
                            # checkpoint), so the receipt times recovery,
                            # not the save both sides pay identically.
                            elastic_t0 = time.monotonic_ns()
                            from distributed_vgg_f_tpu.resilience.errors \
                                import ElasticDegraded
                            try:
                                (state, ds, host_prefetch, rng,
                                 meter) = self._elastic_resize(
                                     step + 1, state, ds, host_prefetch,
                                     consensus)
                            except ElasticDegraded as e:
                                from distributed_vgg_f_tpu.telemetry \
                                    import flight as _fl
                                _fl.note_crash("elastic_degraded_restart",
                                               f"{e.reason}: {e}")
                                self.dump_flight_black_box()
                                elastic_t0 = None
                                if jax.process_index() == 0:
                                    self.logger.log("elastic_degraded", {
                                        "step": step + 1,
                                        "reason": e.reason,
                                        "detail": str(e)})
                            else:
                                preempt_flag["set"] = False
                                num_chips = self.mesh.devices.size
                                continue
                        preempted = True
                        if jax.process_index() == 0:
                            self.logger.log("preempt", {
                                "step": step + 1,
                                "checkpointed": self.checkpoints is not None})
                        break
                if guard is not None:
                    # flush the lagged tail — a bad streak shorter than the poll
                    # lag at the very end of the run must still be counted (and
                    # can still abort)
                    guard.drain()
            finally:
                if old_sigterm is not None:
                    import signal
                    signal.signal(signal.SIGTERM, old_sigterm)
                if profiler is not None:
                    profiler.stop()
                if hasattr(ds, "close"):
                    ds.close()
                if host_prefetch is not None:
                    host_prefetch.close()
            if self.checkpoints is not None and not preempted:
                final_extra = self._save_extra(total)
                saved = self.checkpoints.save(
                    state, extra=final_extra,
                    force=True, replace_on_collision=True)
                if saved:
                    self._count_state_save(final_extra)
                self.checkpoints.wait()
                if not saved and jax.process_index() == 0:
                    # a dropped FORCED save means the run's end state was not
                    # persisted — must be loud, never silent (ADVICE r2 #1).
                    # state.step == total here (the loop completed un-preempted),
                    # so no device sync for the log line
                    self.logger.log("checkpoint_save_dropped", {
                        "step": total, "forced": True})
            if self.best_checkpoints is not None:
                self.best_checkpoints.wait()
            return state
        except BaseException as e:
            # the black box must land BEFORE the (fallible, barrier-bearing)
            # telemetry export, and must never mask the run exception
            self.dump_flight_black_box(exc=e)
            raise
        finally:
            if self.autotuner is not None:
                # swap the LIVE /autotunez provider for a plain-data final
                # snapshot: the run's last controller state stays readable
                # (and bench.py's last-good recording reads it after fit),
                # but the bound method no longer pins the closed pipeline
                # object graph — and a later run can never be served this
                # one's state as live
                try:
                    final = self.autotuner.describe()
                    final["live"] = False
                    _exporter.set_autotune_source(lambda: final)
                except Exception:  # noqa: BLE001 — receipts never mask
                    _exporter.set_autotune_source(None)
            self.export_telemetry()

    def _flight_dump_dir(self) -> str:
        """Where the black box lands: telemetry.flight_dir explicitly, else
        the sidecar dir (the run's existing artifact home), else
        <checkpoint_dir>/flight. "" = nowhere configured."""
        tele = self.cfg.telemetry
        if tele.flight_dir:
            return tele.flight_dir
        if tele.sidecar_dir:
            return tele.sidecar_dir
        if self.cfg.train.checkpoint_dir:
            return os.path.join(self.cfg.train.checkpoint_dir, "flight")
        return ""

    def config_fingerprint(self) -> str:
        """Stable hash of the full config — the black box's "which exact
        run was this" key (two boxes from runs that differ only in a
        threshold must not look identical in triage)."""
        import dataclasses
        import hashlib
        import json
        blob = json.dumps(dataclasses.asdict(self.cfg), sort_keys=True,
                          default=str)
        return "sha256:" + hashlib.sha256(blob.encode()).hexdigest()[:16]

    def dump_flight_black_box(self, exc: BaseException | None = None) -> \
            str | None:
        """Write this process's flight-recorder black box (crash path; also
        callable for a live snapshot). Best-effort: a dump failure is
        logged, never raised — it runs while unwinding the real error."""
        tele = self.cfg.telemetry
        if not tele.enabled:
            return None
        from distributed_vgg_f_tpu.telemetry.flight import get_flight
        directory = self._flight_dump_dir()
        log_event = getattr(self.logger, "log", None)
        if not directory:
            if log_event is not None and jax.process_index() == 0:
                log_event("flight_dump_skipped", {
                    "reason": "no telemetry.flight_dir / sidecar_dir / "
                              "checkpoint_dir configured"})
            return None
        versions = {"metrics_schema": telemetry.schema.SCHEMA_VERSION,
                    "jax": jax.__version__}
        try:
            from distributed_vgg_f_tpu.data.native_jpeg import (
                JPEG_ABI_VERSION)
            versions["native_jpeg_abi"] = JPEG_ABI_VERSION
        except Exception:  # noqa: BLE001 — decoder optional by design
            pass
        try:
            path = get_flight().dump(
                directory, exc=exc, process=jax.process_index(),
                config_fingerprint=self.config_fingerprint(),
                config_name=self.cfg.name, versions=versions,
                registry=telemetry.get_registry(),
                recorder=telemetry.get_recorder())
        except Exception as e:  # noqa: BLE001 — never mask the run error
            if log_event is not None and jax.process_index() == 0:
                log_event("flight_dump_failed", {"error": repr(e)})
            return None
        if log_event is not None and jax.process_index() == 0:
            log_event("flight_black_box", {"path": path,
                                           "reason_exc": type(exc).__name__
                                           if exc else None})
        return path

    def export_telemetry(self) -> None:
        """Write the configured telemetry artifacts: the span ring buffer as
        Chrome trace-event JSON (`telemetry.trace_export`) and the
        per-process registry-snapshot sidecars + process-0 aggregate
        (`telemetry.sidecar_dir`). Called from fit()'s finally path;
        standalone eval/predict entry points (cli.py) call it explicitly.
        Best-effort by design: an export failure must never mask the run
        exception it is unwinding under."""
        tele = self.cfg.telemetry
        if not tele.enabled:
            return
        rec = telemetry.get_recorder()
        # The sidecar barrier uses its OWN tag sequence, advanced BEFORE any
        # fallible I/O: deriving it from _barrier_seq (or incrementing after
        # a possible exception) would let one rank's local export failure
        # desynchronize the mandatory align_N sequence and deadlock the
        # next fit/eval phase for the 600 s barrier timeout (code-review
        # r8). A telemetry-tag mismatch only costs a swallowed 30 s wait.
        sidecar_barrier = None
        if tele.sidecar_dir and jax.process_count() > 1:
            _telemetry_barrier_seq["n"] += 1
            sidecar_barrier = f"telemetry_{_telemetry_barrier_seq['n']}"
        try:
            if tele.trace_export:
                path = tele.trace_export
                if jax.process_count() > 1:
                    root, ext = os.path.splitext(path)
                    path = f"{root}_p{jax.process_index():05d}" \
                           f"{ext or '.json'}"
                trace = rec.export_chrome_trace(
                    path,
                    process_name=f"trainer_rank{jax.process_index()}")
                if jax.process_index() == 0:
                    self.logger.log("telemetry_trace_exported", {
                        "path": path,
                        "events": len(trace["traceEvents"]),
                        "dropped_spans": rec.dropped})
            if tele.sidecar_dir:
                from distributed_vgg_f_tpu.parallel.distributed import (
                    aggregate_telemetry_sidecars,
                    write_telemetry_sidecar,
                )
                write_telemetry_sidecar(tele.sidecar_dir, {
                    "event": "telemetry_snapshot",
                    **telemetry.get_registry().snapshot_split(),
                    "spans_recorded": rec.recorded,
                    "spans_dropped": rec.dropped})
                if sidecar_barrier is not None:
                    # Bounded-timeout barrier so a CLEAN exit aggregates
                    # every rank's sidecar (all ranks export concurrently;
                    # rank 0 racing ahead would nondeterministically drop
                    # late writers). On crash paths dead ranks time it out
                    # and the aggregate degrades to whatever is on disk —
                    # never hangs the survivors (code-review r8).
                    try:
                        coordination_barrier(sidecar_barrier,
                                             timeout_ms=30_000)
                    except Exception:  # noqa: BLE001 — best-effort
                        pass
                if jax.process_index() == 0:
                    agg = aggregate_telemetry_sidecars(
                        tele.sidecar_dir,
                        expected_processes=jax.process_count())
                    import json
                    with open(os.path.join(tele.sidecar_dir,
                                           "telemetry_aggregate.json"),
                              "w") as f:
                        json.dump(agg, f, indent=1)
        except Exception as e:  # noqa: BLE001 — never mask the run error
            log_event = getattr(self.logger, "log", None)
            if log_event is not None and jax.process_index() == 0:
                log_event("telemetry_export_failed", {"error": repr(e)})

    def evaluate(self, state: TrainState, dataset: Iterator,
                 num_batches: int | None = None,
                 use_ema: bool | None = None,
                 step: int | None = None) -> Mapping[str, float]:
        """One validation pass (SURVEY.md §3.4).

        Finite eval datasets (data/eval_pad.py FiniteEvalIterable) are scored
        EXACTLY: run to exhaustion, padding rows masked out by the eval step.
        Hosts with uneven shards stay in lockstep — a host that runs out keeps
        feeding all-invalid `padding_batch()`es while `_any_host_has_data`
        (a tiny cross-process all-gather) says another host is still scoring,
        so the psum collective inside eval_step can never strand. Infinite
        iterators fall back to a fixed `num_batches` draw (legacy/synthetic).

        `use_ema=None` (default) scores the EMA weights whenever the state
        carries them (the TF-era ImageNet recipe — the averaged weights are
        the deliverable); pass False to score the raw training weights.

        `step`: the host-side step number for the eval log line. The train
        loop already knows it as a Python int — passing it here keeps the
        log path free of a redundant device sync; standalone callers can
        omit it and pay one device_get."""
        cfg = self.cfg
        if use_ema is None:
            use_ema = state.ema_params is not None
        if use_ema:
            if state.ema_params is None:
                raise ValueError("use_ema=True but state has no ema_params "
                                 "(train.ema_decay is 0)")
            # swap BOTH trees: averaged weights against raw-trajectory BN
            # stats would mismatch the activation distribution
            state = state.replace(params=state.ema_params,
                                  batch_stats=state.ema_batch_stats)
        totals = {"top1": 0, "top5": 0, "count": 0}
        _align_cold_start()
        t0 = time.monotonic()
        t0_ns = time.monotonic_ns()

        def accumulate(batch):
            counts = jax.device_get(self.eval_step(state, self.shard(batch)))
            for k in totals:
                totals[k] += int(counts[k])

        if num_batches is None and getattr(dataset, "is_finite", False):
            it = iter(dataset)
            exhausted = False
            while True:
                batch = None
                if not exhausted:
                    batch = next(it, None)
                    exhausted = batch is None
                if not self._any_host_has_data(not exhausted):
                    break
                accumulate(batch if batch is not None
                           else dataset.padding_batch())
        else:
            if num_batches is None:
                num_batches = max(1, cfg.data.num_eval_examples
                                  // cfg.data.global_batch_size)
            it = iter(dataset)
            for _ in range(num_batches):
                accumulate(next(it))
        n = max(1, totals["count"])
        telemetry.record("eval_pass", "eval", t0_ns,
                         time.monotonic_ns() - t0_ns)
        telemetry.inc("eval/passes")
        result = {"eval_top1": totals["top1"] / n, "eval_top5": totals["top5"] / n,
                  "eval_examples": totals["count"],
                  "eval_seconds": time.monotonic() - t0}
        # The native eval iterator zero-fills corrupt images (still counted
        # valid) — surface that, or the "exact" numbers are silently skewed.
        eval_decode_errors = getattr(dataset, "decode_errors", None)
        if callable(eval_decode_errors):
            de = eval_decode_errors()
            if de > 0:
                result["eval_decode_errors"] = de
        if jax.process_index() == 0:
            if step is None:
                step = int(jax.device_get(state.step))
            self.logger.log("eval", {"step": step, **result})
        return result

    @staticmethod
    def _any_host_has_data(local_has_data: bool) -> bool:
        """True while any process still holds unscored eval examples. One tiny
        all-gather per eval batch — negligible next to the step itself, and the
        price of exactness under uneven host shards."""
        if jax.process_count() == 1:
            return local_has_data
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray(local_has_data, np.int32))
        return bool(np.asarray(flags).any())
