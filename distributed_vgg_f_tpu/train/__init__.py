from distributed_vgg_f_tpu.train.schedule import build_optimizer, build_schedule  # noqa: F401
from distributed_vgg_f_tpu.train.state import TrainState  # noqa: F401
from distributed_vgg_f_tpu.train.step import build_eval_step, build_train_step  # noqa: F401
from distributed_vgg_f_tpu.train.trainer import Trainer  # noqa: F401
