"""Loss functions.

Reference semantics (SURVEY.md §2.1 #3, §7 hard parts): softmax cross-entropy with
L2 weight decay *coupled into the loss* (TF style: `loss + wd * sum ||W||^2 / 2`),
not decoupled AdamW-style decay — coupling through momentum matters for parity.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax


def softmax_cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                          label_smoothing: float = 0.0) -> jnp.ndarray:
    """Mean softmax-CE over the batch. `labels` are integer class ids.

    Logits are upcast to float32 so the log-sum-exp is stable under bf16 compute.
    """
    logits = logits.astype(jnp.float32)
    num_classes = logits.shape[-1]
    if label_smoothing > 0.0:
        onehot = jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)
        onehot = onehot * (1.0 - label_smoothing) + label_smoothing / num_classes
        losses = optax.softmax_cross_entropy(logits, onehot)
    else:
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    return jnp.mean(losses)


def _is_decayable(path: tuple, leaf: jnp.ndarray) -> bool:
    """Decay kernels only — biases, normalization scales, ViT position
    embeddings and the class token are exempt: standard ImageNet/ViT practice
    and what TF's `tf.nn.l2_loss`-over-weights idiom amounts to. (pos_embed/cls
    are ndim>=2 parameters but are embeddings, not multiplicative weights.)"""
    names = [str(getattr(p, "key", getattr(p, "name", str(p)))) for p in path]
    if any(n in ("bias", "scale", "pos_embed", "cls") for n in names):
        return False
    return leaf.ndim >= 2


def l2_regularization(params: Any, weight_decay: float) -> jnp.ndarray:
    """0.5 * wd * sum ||W||^2 over kernel weights (TF `l2_loss` convention)."""
    if weight_decay == 0.0:
        return jnp.asarray(0.0, jnp.float32)
    leaves = jax.tree_util.tree_leaves_with_path(params)
    acc = 0.0
    for path, leaf in leaves:
        if _is_decayable(path, leaf):
            leaf = leaf.astype(jnp.float32)
            acc = acc + jnp.sum(leaf * leaf)
    return 0.5 * weight_decay * acc
