from distributed_vgg_f_tpu.ops.lrn import local_response_norm  # noqa: F401
from distributed_vgg_f_tpu.ops.losses import (  # noqa: F401
    l2_regularization,
    softmax_cross_entropy,
)
from distributed_vgg_f_tpu.ops.metrics import topk_correct  # noqa: F401
