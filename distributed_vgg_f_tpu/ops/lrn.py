"""Local Response Normalization across channels.

VGG-F (CNN-F, Chatfield et al. 2014) applies LRN after conv1 and conv2
(SURVEY.md §3.3). JAX/Flax ship no LRN layer (SURVEY.md §7 hard parts), so this is
implemented directly. Three implementations live in this package:

- `local_response_norm` (here): squared-sum over a sliding channel window via
  `lax.reduce_window`. Exact fp32 numerics — this is the test oracle.
- `local_response_norm_matmul` (here): the channel-window sum recast as a banded
  C×C matmul, `S = (x*x) @ B` with `B[i,j] = |i-j| <= r`. On TPU the window sum
  rides the MXU instead of lane-crossing windowed reductions (measured ~1.7× faster
  fwd+bwd than reduce_window on v5e), and for `beta=0.75` the power is computed as
  `rsqrt(d)*sqrt(rsqrt(d))` instead of `exp(0.75*log d)`.
- `ops/lrn_pallas.py`: a Pallas TPU kernel fusing square → band-matmul → scale into
  one VMEM pass with a custom VJP (SURVEY.md §7 named LRN the one Pallas candidate;
  profiling confirmed it: reduce_window LRN was 45% of the VGG-F train step).

Measured inside the full VGG-F fwd+bwd on TPU v5e (batch 256): reduce_window
37.3 ms/step, Pallas 21.1, matmul 14.7. XLA wins over the hand kernel here
because it fuses the square into the preceding ReLU and the scale into the next
conv's input, while the Pallas call boundary forces an HBM materialization (plus
a lane-repacking relayout for C=64). So `lrn()` dispatches to the matmul form by
default everywhere; the Pallas kernel stays available via `set_lrn_impl("pallas")`
and as the template for ops where XLA's fusion is NOT sufficient.

Two parameterizations exist in the wild; both are supported so parity oracles are
exact:
- TF / AlexNet-paper style (`alpha_scaled=False`):  d = (k + alpha     * sum)^beta
- Caffe / torch style      (`alpha_scaled=True`):   d = (k + alpha/n   * sum)^beta
(`n = 2*depth_radius + 1` is the window size.)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def local_response_norm(x: jnp.ndarray,
                        depth_radius: int = 2,
                        bias: float = 2.0,
                        alpha: float = 1e-4,
                        beta: float = 0.75,
                        *,
                        alpha_scaled: bool = False,
                        channel_axis: int = -1) -> jnp.ndarray:
    """LRN over the channel axis (NHWC default).

    out[c] = x[c] / (bias + a * sum_{j=c-r..c+r} x[j]^2) ** beta
    with a = alpha/n when `alpha_scaled` else alpha.
    """
    if channel_axis < 0:
        channel_axis += x.ndim
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha

    # LRN numerics are fp32-sensitive (x^4-ish dynamic range); compute the
    # normalizer in float32 regardless of the activation dtype.
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    sq = xf * xf

    window = [1] * x.ndim
    window[channel_axis] = n
    padding = [(0, 0)] * x.ndim
    padding[channel_axis] = (depth_radius, depth_radius)
    sums = lax.reduce_window(sq, 0.0, lax.add,
                             window_dimensions=tuple(window),
                             window_strides=(1,) * x.ndim,
                             padding=tuple(padding))
    denom = (bias + a * sums) ** beta
    return (xf / denom).astype(orig_dtype)


def band_matrix_np(num_channels: int, depth_radius: int) -> np.ndarray:
    """C×C banded matrix of ones: B[i, j] = 1 iff |i - j| <= depth_radius.
    Right-multiplying squared activations by B computes the LRN window sum;
    B is symmetric, so the backward pass reuses it unchanged. Numpy on purpose:
    the Pallas path builds (block-diagonal copies of) it inside jit traces,
    where jnp constants would become tracers."""
    i = np.arange(num_channels)
    return (np.abs(i[:, None] - i[None, :]) <= depth_radius).astype(np.float32)


def band_matrix(num_channels: int, depth_radius: int,
                dtype=jnp.float32) -> jnp.ndarray:
    return jnp.asarray(band_matrix_np(num_channels, depth_radius), dtype=dtype)


def _pow_neg_beta(d: jnp.ndarray, beta: float) -> jnp.ndarray:
    """d ** -beta, with a sqrt/rsqrt fast path for the canonical beta=0.75
    (VPU sqrt/rsqrt vs transcendental exp/log)."""
    if beta == 0.75:
        inv = lax.rsqrt(d)           # d^-1/2
        return inv * jnp.sqrt(inv)   # d^-3/4
    if beta == 0.5:
        return lax.rsqrt(d)
    return d ** -beta


def local_response_norm_matmul(x: jnp.ndarray,
                               depth_radius: int = 2,
                               bias: float = 2.0,
                               alpha: float = 1e-4,
                               beta: float = 0.75,
                               *,
                               alpha_scaled: bool = False) -> jnp.ndarray:
    """LRN with the window sum as a banded matmul over the channel (last) axis.

    Identical math to `local_response_norm` (window sums of x² are the same
    fp32 values, matmul accumulates in fp32); only the power computation differs
    (`_pow_neg_beta` fast path), measured < 2e-5 relative vs the oracle."""
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha
    band = band_matrix(x.shape[-1], depth_radius)
    xf = x.astype(jnp.float32)
    sums = lax.dot_general(xf * xf, band, (((x.ndim - 1,), (0,)), ((), ())),
                           precision=lax.Precision.HIGHEST)
    scale = _pow_neg_beta(bias + a * sums, beta)
    return (xf * scale).astype(x.dtype)


_IMPL_OVERRIDE: str | None = None


def set_lrn_impl(impl: str | None) -> None:
    """Force an LRN implementation globally: 'pallas' | 'matmul' |
    'reduce_window' | None (auto: the banded-matmul form, fastest measured —
    see module docstring)."""
    global _IMPL_OVERRIDE
    if impl not in (None, "pallas", "matmul", "reduce_window"):
        raise ValueError(f"unknown LRN impl: {impl!r}")
    _IMPL_OVERRIDE = impl


def lrn(x: jnp.ndarray,
        depth_radius: int = 2,
        bias: float = 2.0,
        alpha: float = 1e-4,
        beta: float = 0.75,
        *,
        alpha_scaled: bool = False) -> jnp.ndarray:
    """Dispatching LRN over the last axis — what models should call.

    Auto mode picks the banded-matmul form (fastest measured on TPU v5e — see
    module docstring; implementation choice is a trace-time Python decision,
    every branch is jittable on every backend)."""
    impl = _IMPL_OVERRIDE
    if impl is None:
        impl = "matmul"
    if impl == "pallas":
        from distributed_vgg_f_tpu.ops.lrn_pallas import local_response_norm_pallas
        return local_response_norm_pallas(x, depth_radius, bias, alpha, beta,
                                          alpha_scaled=alpha_scaled)
    if impl == "matmul":
        return local_response_norm_matmul(x, depth_radius, bias, alpha, beta,
                                          alpha_scaled=alpha_scaled)
    return local_response_norm(x, depth_radius, bias, alpha, beta,
                               alpha_scaled=alpha_scaled)
