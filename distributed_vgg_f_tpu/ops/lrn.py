"""Local Response Normalization across channels.

VGG-F (CNN-F, Chatfield et al. 2014) applies LRN after conv1 and conv2
(SURVEY.md §3.3). JAX/Flax ship no LRN layer (SURVEY.md §7 hard parts), so this is
implemented directly. Three implementations live in this package:

- `local_response_norm` (here): squared-sum over a sliding channel window via
  `lax.reduce_window`. Exact fp32 numerics — this is the test oracle.
- `local_response_norm_matmul` (here): the channel-window sum recast as a banded
  C×C matmul, `S = (x*x) @ B` with `B[i,j] = |i-j| <= r`. On TPU the window sum
  rides the MXU instead of lane-crossing windowed reductions (measured ~1.7× faster
  fwd+bwd than reduce_window on v5e), and for `beta=0.75` the power is computed as
  `rsqrt(d)*sqrt(rsqrt(d))` instead of `exp(0.75*log d)`.
- `ops/lrn_pallas.py`: a Pallas TPU kernel fusing square → band-matmul → scale into
  one VMEM pass with a custom VJP (SURVEY.md §7 named LRN the one Pallas candidate;
  profiling confirmed it: reduce_window LRN was 45% of the VGG-F train step).

Measured inside the full VGG-F fwd+bwd on TPU v5e (batch 256): reduce_window
37.3 ms/step, Pallas 21.1, matmul 14.7. XLA wins over the hand kernel here
because it fuses the square into the preceding ReLU and the scale into the next
conv's input, while the Pallas call boundary forces an HBM materialization (plus
a lane-repacking relayout for C=64). On top of the matmul form,
`local_response_norm_matmul_vjp` adds a hand-written VJP that saves NO residuals
(autodiff stores a f32 normalizer tensor per LRN site; the VJP recomputes it
with one extra cheap band matmul) — another ~5% off the whole VGG-F train step.
`lrn()` dispatches to that form by default everywhere; the Pallas kernel stays
available via `set_lrn_impl("pallas")` and as the template for ops where XLA's
fusion is NOT sufficient.

Two parameterizations exist in the wild; both are supported so parity oracles are
exact:
- TF / AlexNet-paper style (`alpha_scaled=False`):  d = (k + alpha     * sum)^beta
- Caffe / torch style      (`alpha_scaled=True`):   d = (k + alpha/n   * sum)^beta
(`n = 2*depth_radius + 1` is the window size.)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def local_response_norm(x: jnp.ndarray,
                        depth_radius: int = 2,
                        bias: float = 2.0,
                        alpha: float = 1e-4,
                        beta: float = 0.75,
                        *,
                        alpha_scaled: bool = False,
                        channel_axis: int = -1) -> jnp.ndarray:
    """LRN over the channel axis (NHWC default).

    out[c] = x[c] / (bias + a * sum_{j=c-r..c+r} x[j]^2) ** beta
    with a = alpha/n when `alpha_scaled` else alpha.
    """
    if channel_axis < 0:
        channel_axis += x.ndim
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha

    # LRN numerics are fp32-sensitive (x^4-ish dynamic range); compute the
    # normalizer in float32 regardless of the activation dtype.
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    sq = xf * xf

    window = [1] * x.ndim
    window[channel_axis] = n
    padding = [(0, 0)] * x.ndim
    padding[channel_axis] = (depth_radius, depth_radius)
    sums = lax.reduce_window(sq, 0.0, lax.add,
                             window_dimensions=tuple(window),
                             window_strides=(1,) * x.ndim,
                             padding=tuple(padding))
    denom = (bias + a * sums) ** beta
    return (xf / denom).astype(orig_dtype)


def band_matrix_np(num_channels: int, depth_radius: int) -> np.ndarray:
    """C×C banded matrix of ones: B[i, j] = 1 iff |i - j| <= depth_radius.
    Right-multiplying squared activations by B computes the LRN window sum;
    B is symmetric, so the backward pass reuses it unchanged. Numpy on purpose:
    the Pallas path builds (block-diagonal copies of) it inside jit traces,
    where jnp constants would become tracers."""
    i = np.arange(num_channels)
    return (np.abs(i[:, None] - i[None, :]) <= depth_radius).astype(np.float32)


def band_matrix(num_channels: int, depth_radius: int,
                dtype=jnp.float32) -> jnp.ndarray:
    return jnp.asarray(band_matrix_np(num_channels, depth_radius), dtype=dtype)


def _pow_neg_beta(d: jnp.ndarray, beta: float) -> jnp.ndarray:
    """d ** -beta, with a sqrt/rsqrt fast path for the canonical beta=0.75
    (VPU sqrt/rsqrt vs transcendental exp/log)."""
    if beta == 0.75:
        inv = lax.rsqrt(d)           # d^-1/2
        return inv * jnp.sqrt(inv)   # d^-3/4
    if beta == 0.5:
        return lax.rsqrt(d)
    return d ** -beta


def local_response_norm_matmul(x: jnp.ndarray,
                               depth_radius: int = 2,
                               bias: float = 2.0,
                               alpha: float = 1e-4,
                               beta: float = 0.75,
                               *,
                               alpha_scaled: bool = False) -> jnp.ndarray:
    """LRN with the window sum as a banded matmul over the channel (last) axis.

    Identical math to `local_response_norm` (window sums of x² are the same
    fp32 values, matmul accumulates in fp32); only the power computation differs
    (`_pow_neg_beta` fast path), measured < 2e-5 relative vs the oracle."""
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha
    band = band_matrix(x.shape[-1], depth_radius)
    xf = x.astype(jnp.float32)
    sums = lax.dot_general(xf * xf, band, (((x.ndim - 1,), (0,)), ((), ())),
                           precision=lax.Precision.HIGHEST)
    scale = _pow_neg_beta(bias + a * sums, beta)
    return (xf * scale).astype(x.dtype)


def _band_sum(sq: jnp.ndarray, depth_radius: int) -> jnp.ndarray:
    """Channel-window sum via 2r+1 shifted slices + adds.

    MEASURED NON-WIN on TPU v5e (kept as the counter-example): although the
    banded matmul does C× the useful FLOPs (band width 5 vs C=64/256 columns)
    and profiling put its dot_generals at ~32% of the VGG-F step, replacing
    them with these slice-adds made the whole step 74.2 vs 50.1 ms/step
    (batch 1024). Offset slices in the minor (lane) dimension force per-element
    lane rotations on the VPU — exactly the shuffle cost that sank the
    reduce_window form — while the MXU eats the redundant band FLOPs at
    HBM-bandwidth-bound speed. TPU lesson twice confirmed: prefer dense MXU
    work over lane-crossing data movement, even at 50× the arithmetic."""
    c = sq.shape[-1]
    r = depth_radius
    padded = jnp.pad(sq, [(0, 0)] * (sq.ndim - 1) + [(r, r)])
    out = None
    for k in range(2 * r + 1):
        s = lax.slice_in_dim(padded, k, k + c, axis=-1)
        out = s if out is None else out + s
    return out


def _lrn_shift_core(x: jnp.ndarray, depth_radius: int, bias: float, a: float,
                    beta: float):
    """Shared fwd math for the shifted-slice LRN: exact f32 window sums."""
    xf = x.astype(jnp.float32)
    S = _band_sum(xf * xf, depth_radius)
    d = bias + a * S
    t = _pow_neg_beta(d, beta)
    return (xf * t).astype(x.dtype), d, t


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _lrn_shift_vjp(x, depth_radius, bias, a, beta):
    return _lrn_shift_core(x, depth_radius, bias, a, beta)[0]


def _lrn_shift_vjp_fwd(x, depth_radius, bias, a, beta):
    out, _, _ = _lrn_shift_core(x, depth_radius, bias, a, beta)
    return out, (x,)


def _lrn_shift_vjp_bwd(depth_radius, bias, a, beta, res, g):
    """Residual-free backward (same derivation as the matmul form — the band
    is symmetric, so the adjoint window sum is the same `_band_sum`):

        grad_i = g_i * t_i - 2*a*beta * x_i * sum_j B_ij (g_j x_j t_j / d_j)
    """
    (x,) = res
    _, d, t = _lrn_shift_core(x, depth_radius, bias, a, beta)
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    v = _band_sum(gf * xf * (t / d), depth_radius)
    grad = gf * t - 2.0 * a * beta * xf * v
    return (grad.astype(x.dtype),)


_lrn_shift_vjp.defvjp(_lrn_shift_vjp_fwd, _lrn_shift_vjp_bwd)


def local_response_norm_shift_vjp(x: jnp.ndarray,
                                  depth_radius: int = 2,
                                  bias: float = 2.0,
                                  alpha: float = 1e-4,
                                  beta: float = 0.75,
                                  *,
                                  alpha_scaled: bool = False) -> jnp.ndarray:
    """Shifted-slice LRN with the residual-free hand VJP. Exact f32 window
    sums, but a measured NON-WIN vs the banded matmul on TPU (see `_band_sum`
    docstring) — kept for oracle cross-checks and non-TPU backends. Not
    twice-differentiable; use the autodiff forms for higher-order grads."""
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha
    return _lrn_shift_vjp(x, depth_radius, float(bias), float(a), float(beta))


def _lrn_mm_core(x: jnp.ndarray, depth_radius: int, bias: float, a: float,
                 beta: float):
    """Shared fwd math for the custom-VJP matmul LRN. Returns (out, d, t) with
    d = bias + a*S (f32 normalizer) and t = d^-beta (f32 scale).

    For bf16 inputs the band matmul runs natively in bf16 on the MXU (f32
    accumulation): the window sum error (~2^-8 relative) enters d scaled by
    `a` (1e-4-ish) against the O(1) bias term, so it is negligible — while a
    f32 matmul would cost multiple MXU passes. f32 inputs keep the exact
    HIGHEST-precision path so oracle tests stay bit-tight."""
    band_dtype = x.dtype if x.dtype == jnp.bfloat16 else jnp.float32
    band = band_matrix(x.shape[-1], depth_radius, band_dtype)
    sq = (x * x) if band_dtype == jnp.bfloat16 else None
    if band_dtype == jnp.bfloat16:
        S = lax.dot_general(sq, band, (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    else:
        xf = x.astype(jnp.float32)
        S = lax.dot_general(xf * xf, band, (((x.ndim - 1,), (0,)), ((), ())),
                            precision=lax.Precision.HIGHEST)
    d = bias + a * S
    t = _pow_neg_beta(d, beta)
    out = (x.astype(jnp.float32) * t).astype(x.dtype)
    return out, d, t


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _lrn_matmul_vjp(x, depth_radius, bias, a, beta):
    return _lrn_mm_core(x, depth_radius, bias, a, beta)[0]


def _lrn_matmul_vjp_fwd(x, depth_radius, bias, a, beta):
    out, _, _ = _lrn_mm_core(x, depth_radius, bias, a, beta)
    return out, (x,)


def _lrn_matmul_vjp_bwd(depth_radius, bias, a, beta, res, g):
    """Hand-derived backward saving NO residuals beyond x (which XLA already
    keeps for the surrounding conv's backward — so the LRN adds zero HBM
    residual traffic; d and t are recomputed, one extra cheap band matmul):

        grad_i = g_i * t_i - 2*a*beta * x_i * sum_j B_ij (g_j x_j t_j / d_j)
    """
    (x,) = res
    _, d, t = _lrn_mm_core(x, depth_radius, bias, a, beta)
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    u = (gf * xf * (t / d)).astype(x.dtype)
    band_dtype = x.dtype if x.dtype == jnp.bfloat16 else jnp.float32
    band = band_matrix(x.shape[-1], depth_radius, band_dtype)
    if band_dtype == jnp.bfloat16:
        v = lax.dot_general(u, band, (((x.ndim - 1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    else:
        v = lax.dot_general(u.astype(jnp.float32), band,
                            (((x.ndim - 1,), (0,)), ((), ())),
                            precision=lax.Precision.HIGHEST)
    grad = gf * t - 2.0 * a * beta * xf * v
    return (grad.astype(x.dtype),)


_lrn_matmul_vjp.defvjp(_lrn_matmul_vjp_fwd, _lrn_matmul_vjp_bwd)


def local_response_norm_matmul_vjp(x: jnp.ndarray,
                                   depth_radius: int = 2,
                                   bias: float = 2.0,
                                   alpha: float = 1e-4,
                                   beta: float = 0.75,
                                   *,
                                   alpha_scaled: bool = False) -> jnp.ndarray:
    """Banded-matmul LRN with a hand-written VJP (the default training impl;
    measured ~5% whole-step gain over autodiff of the matmul form on v5e at
    batch 1024 — autodiff stores a f32 normalizer residual per LRN site, this
    stores nothing). Not twice-differentiable; use the autodiff forms for
    higher-order grads."""
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha
    return _lrn_matmul_vjp(x, depth_radius, float(bias), float(a), float(beta))


_IMPL_OVERRIDE: str | None = None


def set_lrn_impl(impl: str | None) -> None:
    """Force an LRN implementation globally: 'shift_vjp' | 'matmul_vjp' |
    'pallas' | 'matmul' | 'reduce_window' | None (auto: the custom-VJP
    banded-matmul form, fastest measured — see module docstring)."""
    global _IMPL_OVERRIDE
    if impl not in (None, "shift_vjp", "matmul_vjp", "pallas", "matmul",
                    "reduce_window"):
        raise ValueError(f"unknown LRN impl: {impl!r}")
    _IMPL_OVERRIDE = impl


def lrn(x: jnp.ndarray,
        depth_radius: int = 2,
        bias: float = 2.0,
        alpha: float = 1e-4,
        beta: float = 0.75,
        *,
        alpha_scaled: bool = False) -> jnp.ndarray:
    """Dispatching LRN over the last axis — what models should call.

    Auto mode picks the banded-matmul form (fastest measured on TPU v5e — see
    module docstring; implementation choice is a trace-time Python decision,
    every branch is jittable on every backend)."""
    impl = _IMPL_OVERRIDE
    if impl is None:
        impl = "matmul_vjp"
    if impl == "shift_vjp":
        return local_response_norm_shift_vjp(x, depth_radius, bias, alpha,
                                             beta, alpha_scaled=alpha_scaled)
    if impl == "matmul_vjp":
        return local_response_norm_matmul_vjp(x, depth_radius, bias, alpha,
                                              beta, alpha_scaled=alpha_scaled)
    if impl == "pallas":
        from distributed_vgg_f_tpu.ops.lrn_pallas import local_response_norm_pallas
        return local_response_norm_pallas(x, depth_radius, bias, alpha, beta,
                                          alpha_scaled=alpha_scaled)
    if impl == "matmul":
        return local_response_norm_matmul(x, depth_radius, bias, alpha, beta,
                                          alpha_scaled=alpha_scaled)
    return local_response_norm(x, depth_radius, bias, alpha, beta,
                               alpha_scaled=alpha_scaled)
