"""Local Response Normalization across channels.

VGG-F (CNN-F, Chatfield et al. 2014) applies LRN after conv1 and conv2
(SURVEY.md §3.3). JAX/Flax ship no LRN layer (SURVEY.md §7 hard parts), so this is
implemented directly: a squared-sum over a sliding channel window via
`lax.reduce_window`, which XLA lowers to a vectorized windowed reduction that fuses
with the surrounding elementwise ops — no gather/scatter, TPU-friendly static shapes.

Two parameterizations exist in the wild; both are supported so parity oracles are
exact:
- TF / AlexNet-paper style (`alpha_scaled=False`):  d = (k + alpha     * sum)^beta
- Caffe / torch style      (`alpha_scaled=True`):   d = (k + alpha/n   * sum)^beta
(`n = 2*depth_radius + 1` is the window size.)
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def local_response_norm(x: jnp.ndarray,
                        depth_radius: int = 2,
                        bias: float = 2.0,
                        alpha: float = 1e-4,
                        beta: float = 0.75,
                        *,
                        alpha_scaled: bool = False,
                        channel_axis: int = -1) -> jnp.ndarray:
    """LRN over the channel axis (NHWC default).

    out[c] = x[c] / (bias + a * sum_{j=c-r..c+r} x[j]^2) ** beta
    with a = alpha/n when `alpha_scaled` else alpha.
    """
    if channel_axis < 0:
        channel_axis += x.ndim
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha

    # LRN numerics are fp32-sensitive (x^4-ish dynamic range); compute the
    # normalizer in float32 regardless of the activation dtype.
    orig_dtype = x.dtype
    xf = x.astype(jnp.float32)
    sq = xf * xf

    window = [1] * x.ndim
    window[channel_axis] = n
    padding = [(0, 0)] * x.ndim
    padding[channel_axis] = (depth_radius, depth_radius)
    sums = lax.reduce_window(sq, 0.0, lax.add,
                             window_dimensions=tuple(window),
                             window_strides=(1,) * x.ndim,
                             padding=tuple(padding))
    denom = (bias + a * sums) ** beta
    return (xf / denom).astype(orig_dtype)
