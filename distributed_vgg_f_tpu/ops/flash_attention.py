"""Blockwise fused (flash) self-attention — a Pallas TPU kernel.

SURVEY.md §5 marks long-context/sequence-parallel absent in the reference
(an image CNN); this framework builds the capability anyway (PARITY.md
"beyond-parity"): `parallel/ring_attention.py` shards the sequence ACROSS
chips, and this kernel is the WITHIN-chip half — exact attention whose
(T, T) score matrix never exists in HBM. XLA's einsum attention materializes
`probs` (B, H, T, T): at T = 8192, H = 8, B = 1 that is 1 GiB in bf16 *per
direction*, all bandwidth; this kernel streams K/V blocks through VMEM and
carries the classic online-softmax state (running max, running sum,
unnormalized accumulator) in scratch, so HBM traffic stays O(T·D) plus the
O(T) logsumexp residual.

Design notes (tpu):
  - grid (B·H, T/block_q, T/block_k), KV innermost — the Pallas pipeline
    double-buffers the K/V block DMAs while the MXU works; scratch
    (acc, m, l) persists across the innermost dimension.
  - all GEMMs take bf16 inputs when the operands are bf16 (MXU), accumulate
    fp32 (`preferred_element_type`); softmax statistics are fp32 always.
  - the logsumexp residual is stored (B·H, T, 1) — T along SUBLANES — so
    neither the forward store nor the backward broadcast needs a cross-lane
    transpose.
  - causal masking by global position. Two skip strategies for the blocks
    entirely above the diagonal: the default rectangular grids skip their
    MXU work under `@pl.when` (DMAs still run), and `causal_skip="dma"`
    switches all three kernels to flat scalar-prefetched grids that
    enumerate only the live lower-triangular pairs — masked blocks never
    touch HBM (see flash_self_attention's docstring). No -inf/-inf guard
    is needed: KV block 0 is never fully masked for any query row
    (k_pos = 0 is allowed everywhere).
  - backward = two kernels (the standard decomposition): dQ accumulates over
    KV blocks with the forward's grid; dK/dV accumulate over Q blocks with
    the transposed grid. Both recompute p = exp(s − lse) instead of saving
    it — the whole point is that (T, T) tensors are never resident.

`interpret=True` runs the same kernels under the Pallas interpreter — the
CPU test path (tests/test_flash_attention.py); the TPU benchmark is
`benchmarks/flash_attention_bench.py`.
"""

from __future__ import annotations

import functools
import math
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tests on CPU flip this to run the kernels in the Pallas interpreter (same
# convention as ops/lrn_pallas.py); call sites that pass interpret=None get
# this default.
INTERPRET = False

#: causal_skip="auto" switches the jagged DMA-skip grids on from this many
#: tokens. The default crossover was measured on **TPU v5e only**
#: (benchmarks/runs/tpu_r4/flash_attention_causal.json: rectangular 9.5 vs
#: jagged 10.2 ms at T=512, jagged ahead 1.08x at 2048, 1.18x at 4096,
#: 1.29x at 8192); other chip generations — or interpret-mode debugging —
#: can re-pin their own measured value via the env override without
#: touching call sites (ADVICE r4).
try:
    CAUSAL_SKIP_AUTO_THRESHOLD = int(
        os.environ.get("DVGGF_CAUSAL_SKIP_AUTO_THRESHOLD", 2048))
except ValueError as _e:
    raise ValueError(
        "DVGGF_CAUSAL_SKIP_AUTO_THRESHOLD must be an integer token count, "
        f"got {os.environ['DVGGF_CAUSAL_SKIP_AUTO_THRESHOLD']!r}") from _e


def _mask_scores(s, qi, ki, *, block_q, block_k, causal, kv_len):
    """Apply the static masks: causal (by global position) and/or the
    real-key limit `kv_len` (queries never attend to padding keys — the
    pad-to-block contract for sequences like ViT's 197 tokens)."""
    qpos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    kpos = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    if causal:
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    if kv_len is not None:
        s = jnp.where(kpos < kv_len, s, -jnp.inf)
    return s


def pick_block(t: int, requested: int = 128) -> int:
    """Largest block ≤ `requested` that divides `t`: halving first (block
    sizes stay power-of-two MXU/VPU-aligned when that works out), falling
    back to the largest TRUE divisor whenever halving's answer is a cliff
    (< 64). A sequence like t=192 must get 64, not a min(128, t) clamp that
    fails the divisibility check (code-review r3); and the fallback must
    fire on SMALL halving results, not only b == 1 — halving only visits
    t/2^k, so even lengths whose large divisors are odd slipped through it
    (t=130 → 2 though the exact 65 exists, t=160 → 32 though 80 exists;
    ADVICE r3/r5). For prime t this still returns 1 — `pad_to_block` is
    the cure there."""
    b = min(requested, t)
    while b > 1 and t % b:
        b //= 2
    if b < min(64, t):
        b = next(d for d in range(min(requested, t), 0, -1) if t % d == 0)
    return b


def pad_to_block(t: int, requested: int = 128) -> tuple[int, int]:
    """(padded_len, block) for a sequence whose own divisors are a perf
    cliff. pick_block keeps exact lengths when a decent divisor exists, but
    for prime-ish `t` (ring_flash at T=394 on 2 devices → t_loc=197, itself
    prime) the largest divisor degrades toward 1 — numerically fine, a
    severe TPU perf cliff (VERDICT r4 weak #4). When the best TRUE divisor
    of a multi-block sequence falls below 64, pad up to the next `requested`
    multiple instead and mask the tail (the kv_len machinery): pad rows cost
    < one extra block of MXU work vs ~100× from block-1 grids.

    pick_block is divisor-aware (ADVICE r5): it already prefers the largest
    TRUE divisor over a degenerate halving result, so padding here is
    reserved for lengths with genuinely no divisor ≥ 64 — t=130 stays
    exact at (130, 65) instead of paying ~4× score-matmul work on a
    256/block-128 pad, while t=129 (best divisor 43) still pads.

    The pad target is the 64-multiple lattice, not the `requested`
    multiple (VERDICT r5 #8): the `b ≥ 64` acceptance threshold above
    already declares 64 a good block, so t=129 pads to 192/block-64
    (1.49× compute) rather than 256/block-128 (1.98×). Worst case over
    all t is the smallest padded length, 129 → 192: pad overhead is
    ≤ 1.5× at EVERY length (asserted in tests/test_flash_attention.py).
    Lengths whose next 64-multiple has a larger ≤`requested` divisor
    still get it via pick_block (t=197 → 256/block-128, as before).

    Returns (t, pick_block(t)) when `t` needs no padding. The pad is always
    < block (t_pad − t < 64 ≤ block), so every KV block keeps ≥ 1 real key
    (the no-fully-masked-block invariant the kernels' -inf/-inf guard
    relies on)."""
    b = pick_block(t, requested)
    if b >= 64 or b == t or t <= 64:
        return t, b
    lattice = min(64, requested)
    t_pad = -(-t // lattice) * lattice
    return t_pad, pick_block(t_pad, requested)


def _resolve_blocks(tq, tk, block_q, block_k):
    """None → auto (largest ≤128 divisor); explicit sizes are a strict
    contract — clamped to the sequence but never silently changed."""
    if block_q is None:
        block_q = pick_block(tq)
    if block_k is None:
        block_k = pick_block(tk)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        raise ValueError(
            f"sequence lengths ({tq}, {tk}) not divisible by requested "
            f"blocks ({block_q}, {block_k}); pass block_q/block_k=None "
            f"for automatic divisor selection")
    return block_q, block_k


def _live_block(qi, ki, *, block_q, block_k, causal, kv_len):
    """Static-structure predicate: does KV block `ki` contribute anything to
    Q block `qi`? (False → the whole MXU update is skipped; the block DMA
    still runs.) None means always live."""
    preds = []
    if causal:
        preds.append(qi * block_q + block_q - 1 >= ki * block_k)
    if kv_len is not None:
        preds.append(ki * block_k < kv_len)
    if not preds:
        return None
    out = preds[0]
    for p in preds[1:]:
        out = jnp.logical_and(out, p)
    return out


def _fwd_update(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, qi, ki,
                *, scale, block_q, block_k, causal, kv_len):
    """One KV block folded into the online-softmax scratch state — shared
    by the rectangular and jagged (DMA-skipping) forward kernels."""
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal or kv_len is not None:
        s = _mask_scores(s, qi, ki, block_q=block_q, block_k=block_k,
                         causal=causal, kv_len=kv_len)
    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    p = jnp.exp(s - m_new)
    l_ref[:] = jnp.broadcast_to(
        l_prev * corr + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape)
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _fwd_finish(o_ref, lse_ref, acc_ref, m_ref, l_ref):
    l = l_ref[:, :1]
    o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
    lse_ref[0] = m_ref[:, :1] + jnp.log(l)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, scale, block_q, block_k, causal, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    def update():
        _fwd_update(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, qi, ki,
                    scale=scale, block_q=block_q, block_k=block_k,
                    causal=causal, kv_len=kv_len)

    live = _live_block(qi, ki, block_q=block_q, block_k=block_k,
                       causal=causal, kv_len=kv_len)
    if live is None:
        update()
    else:
        pl.when(live)(update)

    @pl.when(ki == nk - 1)
    def _finish():
        _fwd_finish(o_ref, lse_ref, acc_ref, m_ref, l_ref)


def _fwd_kernel_jagged(qi_ref, ki_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                       acc_ref, m_ref, l_ref,
                       *, scale, block_q, block_k):
    """Causal forward over a FLAT grid of only the live (lower-triangular)
    block pairs — `causal_skip="dma"` (VERDICT r3 weak #6: under the
    rectangular grid, skipped above-diagonal blocks still DMA their K/V —
    ~half the kernel's HBM traffic at long T burned on masked work). The
    (qi, ki) for each flat step come from scalar-prefetched index arrays
    (pltpu.PrefetchScalarGridSpec), so the pipeline only ever fetches
    blocks that contribute. Triangle enumerated row-major: per q row, ki
    runs 0..qi — init at ki == 0, finalize at the diagonal ki == qi."""
    t = pl.program_id(1)
    qi = qi_ref[t]
    ki = ki_ref[t]

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[:] = jnp.zeros_like(l_ref)

    # every enumerated pair is live by construction; the diagonal block
    # still needs its triangular mask, which _fwd_update applies
    _fwd_update(q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref, qi, ki,
                scale=scale, block_q=block_q, block_k=block_k,
                causal=True, kv_len=None)

    @pl.when(ki == qi)
    def _finish():
        _fwd_finish(o_ref, lse_ref, acc_ref, m_ref, l_ref)


def _dq_update(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_acc_ref,
               qi, ki, *, scale, block_q, block_k, causal, kv_len):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal or kv_len is not None:
        s = _mask_scores(s, qi, ki, block_q=block_q, block_k=block_k,
                         causal=causal, kv_len=kv_len)
    p = jnp.exp(s - lse_ref[0])              # (bq, bk); masked rows → 0
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])
    dq_acc_ref[:] = dq_acc_ref[:] + scale * jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc_ref, *, scale, block_q, block_k, causal, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    def update():
        _dq_update(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_acc_ref, qi, ki, scale=scale, block_q=block_q,
                   block_k=block_k, causal=causal, kv_len=kv_len)

    live = _live_block(qi, ki, block_q=block_q, block_k=block_k,
                       causal=causal, kv_len=kv_len)
    if live is None:
        update()
    else:
        pl.when(live)(update)

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _dq_kernel_jagged(qi_ref, ki_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                      delta_ref, dq_ref, dq_acc_ref,
                      *, scale, block_q, block_k):
    """dQ over the flat live-pair grid (same tril order as the forward):
    per q row, ki runs 0..qi — init at ki == 0, store at ki == qi."""
    t = pl.program_id(1)
    qi = qi_ref[t]
    ki = ki_ref[t]

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    _dq_update(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_acc_ref,
               qi, ki, scale=scale, block_q=block_q, block_k=block_k,
               causal=True, kv_len=None)

    @pl.when(ki == qi)
    def _finish():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _dkv_update(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_acc_ref, dv_acc_ref, qi, ki,
                *, scale, block_q, block_k, causal, kv_len):
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if causal or kv_len is not None:
        s = _mask_scores(s, qi, ki, block_q=block_q, block_k=block_k,
                         causal=causal, kv_len=kv_len)
    p = jnp.exp(s - lse_ref[0])
    dv_acc_ref[:] = dv_acc_ref[:] + jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta_ref[0])
    dk_acc_ref[:] = dk_acc_ref[:] + scale * jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                *, scale, block_q, block_k, causal, kv_len):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    def update():
        _dkv_update(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_acc_ref, dv_acc_ref, qi, ki, scale=scale,
                    block_q=block_q, block_k=block_k, causal=causal,
                    kv_len=kv_len)

    live = _live_block(qi, ki, block_q=block_q, block_k=block_k,
                       causal=causal, kv_len=kv_len)
    if live is None:
        update()
    else:
        pl.when(live)(update)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _dkv_kernel_jagged(ki_ref, qi_ref, q_ref, k_ref, v_ref, do_ref,
                       lse_ref, delta_ref, dk_ref, dv_ref, dk_acc_ref,
                       dv_acc_ref, *, scale, block_q, block_k, nq):
    """dK/dV over the flat live-pair grid, KV-row-major: per kv row ki, qi
    runs ki..nq−1 (the transposed triangle). Init at the diagonal qi == ki
    (each row's first live step); store at qi == nq−1 (every row's last —
    `nq` is a trace-time constant)."""
    t = pl.program_id(1)
    ki = ki_ref[t]
    qi = qi_ref[t]

    @pl.when(qi == ki)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    _dkv_update(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_acc_ref, dv_acc_ref, qi, ki, scale=scale,
                block_q=block_q, block_k=block_k, causal=True, kv_len=None)

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _bh_layout(x):
    """(B, T, H, D) → (B·H, T, D)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _bthd_layout(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


@functools.lru_cache(maxsize=32)
def _make_op(causal: bool, block_q: int, block_k: int, interpret: bool,
             kv_len: int | None, causal_skip: str = "mxu"):
    jagged = (causal_skip == "dma" and causal and kv_len is None
              and block_q == block_k)

    def _fwd_call(q3, k3, v3):
        bh, t, d = q3.shape
        nq, nk = t // block_q, t // block_k
        scale = 1.0 / math.sqrt(d)
        if jagged:
            # flat grid over the n(n+1)/2 live pairs, row-major; the
            # above-diagonal blocks are never enumerated so their K/V DMAs
            # never issue (the rectangular grid only skipped their MXU work)
            # row-major lower triangle: i ascending, j = 0..i
            qi_np, ki_np = np.tril_indices(nq)
            qi_arr = jnp.asarray(qi_np.astype(np.int32))
            ki_arr = jnp.asarray(ki_np.astype(np.int32))
            grid_spec = pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(bh, len(qi_np)),
                in_specs=[pl.BlockSpec((1, block_q, d),
                                       lambda b, s, qi, ki: (b, qi[s], 0)),
                          pl.BlockSpec((1, block_k, d),
                                       lambda b, s, qi, ki: (b, ki[s], 0)),
                          pl.BlockSpec((1, block_k, d),
                                       lambda b, s, qi, ki: (b, ki[s], 0))],
                out_specs=[pl.BlockSpec((1, block_q, d),
                                        lambda b, s, qi, ki: (b, qi[s], 0)),
                           pl.BlockSpec((1, block_q, 1),
                                        lambda b, s, qi, ki: (b, qi[s], 0))],
                scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                                pltpu.VMEM((block_q, 128), jnp.float32),
                                pltpu.VMEM((block_q, 128), jnp.float32)],
            )
            out, lse = pl.pallas_call(
                functools.partial(_fwd_kernel_jagged, scale=scale,
                                  block_q=block_q, block_k=block_k),
                grid_spec=grid_spec,
                out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                           jax.ShapeDtypeStruct((bh, t, 1), jnp.float32)],
                interpret=interpret,
            )(qi_arr, ki_arr, q3, k3, v3)
            return out, lse
        grid = (bh, nq, nk)
        q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
        kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=scale, block_q=block_q,
                              block_k=block_k, causal=causal, kv_len=kv_len),
            grid=grid,
            in_specs=[q_spec, kv_spec, kv_spec],
            out_specs=[q_spec,
                       pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))],
            out_shape=[jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                       jax.ShapeDtypeStruct((bh, t, 1), jnp.float32)],
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                            pltpu.VMEM((block_q, 128), jnp.float32),
                            pltpu.VMEM((block_q, 128), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3)
        return out, lse

    @jax.custom_vjp
    def op(q, k, v):
        b, t, h, d = q.shape
        out3, _ = _fwd_call(_bh_layout(q), _bh_layout(k), _bh_layout(v))
        return _bthd_layout(out3, b, h)

    def op_fwd(q, k, v):
        b, t, h, d = q.shape
        q3, k3, v3 = _bh_layout(q), _bh_layout(k), _bh_layout(v)
        out3, lse = _fwd_call(q3, k3, v3)
        return _bthd_layout(out3, b, h), (q3, k3, v3, out3, lse, b, h)

    def op_bwd(res, g):
        q3, k3, v3, out3, lse, b, h = res
        do3 = _bh_layout(g)
        bh, t, d = q3.shape
        nq, nk = t // block_q, t // block_k
        scale = 1.0 / math.sqrt(d)
        # delta_i = Σ_d dO_i · O_i, the softmax-backward row constant;
        # elementwise over (B·H, T, D) — jnp, not a kernel
        delta = jnp.sum(do3.astype(jnp.float32) * out3.astype(jnp.float32),
                        axis=-1, keepdims=True)

        if jagged:
            qs = pl.BlockSpec((1, block_q, d),
                              lambda b_, s, a, c: (b_, a[s], 0))
            ks = pl.BlockSpec((1, block_k, d),
                              lambda b_, s, a, c: (b_, c[s], 0))
            rs = pl.BlockSpec((1, block_q, 1),
                              lambda b_, s, a, c: (b_, a[s], 0))
            # dQ: same tril order as the forward — (qi, ki), ki = 0..qi
            qi_np, ki_np = np.tril_indices(nq)
            dq3 = pl.pallas_call(
                functools.partial(_dq_kernel_jagged, scale=scale,
                                  block_q=block_q, block_k=block_k),
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=2,
                    grid=(bh, len(qi_np)),
                    in_specs=[qs, ks, ks, qs, rs, rs],
                    out_specs=qs,
                    scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]),
                out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
                interpret=interpret,
            )(jnp.asarray(qi_np.astype(np.int32)),
              jnp.asarray(ki_np.astype(np.int32)), q3, k3, v3, do3, lse,
              delta)

            # dK/dV: transposed triangle, KV-row-major — per ki, qi=ki..nq−1,
            # which is exactly triu's row-major (row=ki, col=qi≥ki) order
            ki_arr, qi_arr = np.triu_indices(nq)
            qs_t = pl.BlockSpec((1, block_q, d),
                                lambda b_, s, c, a: (b_, a[s], 0))
            ks_t = pl.BlockSpec((1, block_k, d),
                                lambda b_, s, c, a: (b_, c[s], 0))
            rs_t = pl.BlockSpec((1, block_q, 1),
                                lambda b_, s, c, a: (b_, a[s], 0))
            dk3, dv3 = pl.pallas_call(
                functools.partial(_dkv_kernel_jagged, scale=scale,
                                  block_q=block_q, block_k=block_k, nq=nq),
                grid_spec=pltpu.PrefetchScalarGridSpec(
                    num_scalar_prefetch=2,
                    grid=(bh, len(ki_arr)),
                    in_specs=[qs_t, ks_t, ks_t, qs_t, rs_t, rs_t],
                    out_specs=[ks_t, ks_t],
                    scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                                    pltpu.VMEM((block_k, d), jnp.float32)]),
                out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                           jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
                interpret=interpret,
            )(jnp.asarray(ki_arr.astype(np.int32)),
              jnp.asarray(qi_arr.astype(np.int32)), q3, k3, v3, do3, lse,
              delta)
            return (_bthd_layout(dq3, b, h), _bthd_layout(dk3, b, h),
                    _bthd_layout(dv3, b, h))

        q_spec = pl.BlockSpec((1, block_q, d), lambda b_, i, j: (b_, i, 0))
        kv_spec = pl.BlockSpec((1, block_k, d), lambda b_, i, j: (b_, j, 0))
        row_spec = pl.BlockSpec((1, block_q, 1), lambda b_, i, j: (b_, i, 0))
        dq3 = pl.pallas_call(
            functools.partial(_dq_kernel, scale=scale, block_q=block_q,
                              block_k=block_k, causal=causal, kv_len=kv_len),
            grid=(bh, nq, nk),
            in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
            out_specs=q_spec,
            out_shape=jax.ShapeDtypeStruct(q3.shape, q3.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)

        # transposed grid: KV block outer, Q blocks accumulate innermost
        q_spec_t = pl.BlockSpec((1, block_q, d), lambda b_, j, i: (b_, i, 0))
        kv_spec_t = pl.BlockSpec((1, block_k, d), lambda b_, j, i: (b_, j, 0))
        row_spec_t = pl.BlockSpec((1, block_q, 1), lambda b_, j, i: (b_, i, 0))
        dk3, dv3 = pl.pallas_call(
            functools.partial(_dkv_kernel, scale=scale, block_q=block_q,
                              block_k=block_k, causal=causal, kv_len=kv_len),
            grid=(bh, nk, nq),
            in_specs=[q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                      row_spec_t],
            out_specs=[kv_spec_t, kv_spec_t],
            out_shape=[jax.ShapeDtypeStruct(k3.shape, k3.dtype),
                       jax.ShapeDtypeStruct(v3.shape, v3.dtype)],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
        return (_bthd_layout(dq3, b, h), _bthd_layout(dk3, b, h),
                _bthd_layout(dv3, b, h))

    op.defvjp(op_fwd, op_bwd)
    return op


# ---------------------------------------------------------------------------
# Block-update entry points for ring composition (parallel/ring_flash.py).
#
# Same math as the kernels above, restructured for an OUTER loop the caller
# owns (the inter-chip ring): online-softmax state (acc, m, l) and gradient
# accumulators live in HBM between calls and are carried in/out of each
# kernel; causal masking uses DYNAMIC global offsets (the q offset is a
# traced `axis_index` product under shard_map) read from SMEM.
# ---------------------------------------------------------------------------


def _ring_blk_mask(s, qi, ki, offs_ref, *, block_q, block_k, causal, kv_len):
    """Masks for the ring block kernels: causal by DYNAMIC global position
    (offsets from SMEM), plus the static block-LOCAL `kv_len` pad mask —
    when the ring shards are padded to a block multiple (pad_to_block), the
    visiting K/V block's rows past `kv_len` are padding on EVERY device
    (all shards share one padded layout), so the predicate needs no offset."""
    if causal:
        qpos = (offs_ref[0, 0] + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))
        kpos = (offs_ref[1, 0] + ki * block_k
                + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
        s = jnp.where(qpos >= kpos, s, -jnp.inf)
    if kv_len is not None:
        kloc = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(kloc < kv_len, s, -jnp.inf)
    return s


def _ring_fwd_kernel(offs_ref, q_ref, k_ref, v_ref, acc_in_ref, m_in_ref,
                     l_in_ref, acc_ref, m_ref, l_ref,
                     *, scale, block_q, block_k, causal, kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[0] = acc_in_ref[0]
        m_ref[0] = m_in_ref[0]
        l_ref[0] = l_in_ref[0]

    def update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal or kv_len is not None:
            s = _ring_blk_mask(s, qi, ki, offs_ref, block_q=block_q,
                               block_k=block_k, causal=causal, kv_len=kv_len)
        m_prev = m_ref[0]                       # (block_q, 1)
        l_prev = l_ref[0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # exp(-inf − finite) = 0 — safe while anything has ever been folded
        # into m; a still-(-inf) m_new only happens for a fully-masked row,
        # which the ring schedule never produces on its first live step
        # (step 0 is the diagonal block).
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[0] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[0] = m_new
        acc_ref[0] = acc_ref[0] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(offs_ref[0, 0] + qi * block_q + block_q - 1
                 >= offs_ref[1, 0] + ki * block_k)
        def _():
            update()
    else:
        update()


def flash_block_update(q, k_blk, v_blk, acc, m, l, *, q_off, k_off,
                       causal, block_q=None, block_k=None,
                       kv_len: int | None = None,
                       interpret: bool | None = None):
    """Fold one K/V block into the online-softmax state.

    q: (B·H, Tq, D); k_blk/v_blk: (B·H, Tk, D); acc: (B·H, Tq, D) fp32;
    m, l: (B·H, Tq, 1) fp32. q_off/k_off are the GLOBAL positions of row 0 /
    key 0 (traced values are fine). `kv_len` marks the visiting block's rows
    past it as padding (block-LOCAL, static — the pad_to_block layout every
    ring shard shares); padded keys are never attended. Returns updated
    (acc, m, l); finalize with out = acc / l, lse = m + log l.
    """
    if interpret is None:
        interpret = INTERPRET
    bh, tq, d = q.shape
    tk = k_blk.shape[1]
    block_q, block_k = _resolve_blocks(tq, tk, block_q, block_k)
    if kv_len is not None and not 1 <= kv_len <= tk:
        raise ValueError(f"kv_len {kv_len} outside [1, {tk}]")
    scale = 1.0 / math.sqrt(d)
    offs = jnp.array([[q_off], [k_off]], jnp.int32)
    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    return pl.pallas_call(
        functools.partial(_ring_fwd_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, kv_len=kv_len),
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct(acc.shape, jnp.float32),
                   jax.ShapeDtypeStruct(m.shape, jnp.float32),
                   jax.ShapeDtypeStruct(l.shape, jnp.float32)],
        interpret=interpret,
    )(offs, q, k_blk, v_blk, acc, m, l)


def _ring_dq_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dq_in_ref, dq_ref, *, scale, block_q, block_k, causal,
                    kv_len):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_ref[0] = dq_in_ref[0]

    def update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal or kv_len is not None:
            s = _ring_blk_mask(s, qi, ki, offs_ref, block_q=block_q,
                               block_k=block_k, causal=causal, kv_len=kv_len)
        p = jnp.exp(s - lse_ref[0])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dq_ref[0] = dq_ref[0] + scale * jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(offs_ref[0, 0] + qi * block_q + block_q - 1
                 >= offs_ref[1, 0] + ki * block_k)
        def _():
            update()
    else:
        update()


def _ring_dkv_kernel(offs_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                     delta_ref, dk_in_ref, dv_in_ref, dk_ref, dv_ref,
                     *, scale, block_q, block_k, causal, kv_len):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_ref[0] = dk_in_ref[0]
        dv_ref[0] = dv_in_ref[0]

    def update():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal or kv_len is not None:
            s = _ring_blk_mask(s, qi, ki, offs_ref, block_q=block_q,
                               block_k=block_k, causal=causal, kv_len=kv_len)
        p = jnp.exp(s - lse_ref[0])
        dv_ref[0] = dv_ref[0] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dv_ref.dtype)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_ref[0] = dk_ref[0] + scale * jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32).astype(dk_ref.dtype)

    if causal:
        @pl.when(offs_ref[0, 0] + qi * block_q + block_q - 1
                 >= offs_ref[1, 0] + ki * block_k)
        def _():
            update()
    else:
        update()


def flash_block_grads(q, k_blk, v_blk, do, lse, delta, dq, dk_blk, dv_blk, *,
                      q_off, k_off, causal, block_q=None, block_k=None,
                      kv_len: int | None = None,
                      interpret: bool | None = None):
    """One ring step of the backward: accumulate this device's contribution
    into dq (for the local rows) and into the VISITING block's dk/dv
    accumulators (which travel the ring with their block). dk_blk/dv_blk are
    fp32; recomputes p = exp(s − lse), so nothing quadratic is stored.
    `kv_len` as in flash_block_update: padded visiting-block keys get p = 0
    and ds = 0 exactly, so their traveling dk/dv rows stay zero."""
    if interpret is None:
        interpret = INTERPRET
    bh, tq, d = q.shape
    tk = k_blk.shape[1]
    block_q, block_k = _resolve_blocks(tq, tk, block_q, block_k)
    if kv_len is not None and not 1 <= kv_len <= tk:
        raise ValueError(f"kv_len {kv_len} outside [1, {tk}]")
    scale = 1.0 / math.sqrt(d)
    offs = jnp.array([[q_off], [k_off]], jnp.int32)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))
    dq_new = pl.pallas_call(
        functools.partial(_ring_dq_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, kv_len=kv_len),
        grid=(bh, tq // block_q, tk // block_k),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec,
                  q_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(dq.shape, dq.dtype),
        interpret=interpret,
    )(offs, q, k_blk, v_blk, do, lse, delta, dq)

    # transposed grid: the visiting KV block outer, local Q blocks innermost
    q_spec_t = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kv_spec_t = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    row_spec_t = pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0))
    dk_new, dv_new = pl.pallas_call(
        functools.partial(_ring_dkv_kernel, scale=scale, block_q=block_q,
                          block_k=block_k, causal=causal, kv_len=kv_len),
        grid=(bh, tk // block_k, tq // block_q),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  q_spec_t, kv_spec_t, kv_spec_t, q_spec_t, row_spec_t,
                  row_spec_t, kv_spec_t, kv_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[jax.ShapeDtypeStruct(dk_blk.shape, dk_blk.dtype),
                   jax.ShapeDtypeStruct(dv_blk.shape, dv_blk.dtype)],
        interpret=interpret,
    )(offs, q, k_blk, v_blk, do, lse, delta, dk_blk, dv_blk)
    return dq_new, dk_new, dv_new


def resolve_causal_skip_auto(causal: bool, t: int) -> str:
    """The measured causal_skip="auto" rule (r4 v5e causal sweep): jagged
    DMA-skip grids from CAUSAL_SKIP_AUTO_THRESHOLD tokens up; the
    rectangular schedule below it and for non-causal calls (where the
    jagged grids don't apply at all)."""
    return ("dma" if causal and t >= CAUSAL_SKIP_AUTO_THRESHOLD
            else "mxu")


def flash_self_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                         causal: bool = False, block_q: int | None = None,
                         block_k: int | None = None,
                         kv_len: int | None = None,
                         causal_skip: str = "auto",
                         interpret: bool | None = None) -> jnp.ndarray:
    """Exact self-attention, O(T·D) HBM footprint. (B, T, H, D) in and out.

    Block sizes default to the largest ≤128 divisor of T (None = auto); when
    that divisor would fall below 64 on a multi-block sequence (prime-ish T,
    e.g. 197), the inputs are padded internally to the next 128-multiple
    with the tail masked and sliced off — exact incl. grads, never a block-1
    grid (pad_to_block; VERDICT r4 weak #4). EXPLICIT block sizes are
    strict — T must divide by them or ValueError.
    `kv_len` marks the first `kv_len` keys as real and the rest as padding
    (never attended to; their grads are exactly zero) — pad q/k/v to a block
    multiple, pass the true length, slice the output. Padded QUERY rows
    produce normalized-but-meaningless outputs; slicing discards them and
    their zero cotangents keep the backward exact.

    `causal_skip` (causal only): "mxu" keeps the rectangular grids —
    above-diagonal blocks skip their MXU work under `@pl.when` but their
    K/V (and dO/row-stat) DMAs still run. "dma" enumerates ONLY the live
    lower-triangular pairs on flat scalar-prefetched grids — forward, dQ
    (tril order) AND dK/dV (transposed, kv-row-major) — so masked blocks
    never touch HBM: ~2× less block traffic across all three kernels at
    long T (VERDICT r3 weak #6). Requires causal=True; engages when
    kv_len is None and block_q == block_k (falls back to the rectangular
    grids otherwise). Numerics are identical — same update order within
    every row. "auto" (default) picks by the r4 v5e measurements
    (benchmarks/runs/tpu_r4/flash_attention_causal.json: dma wins 1.08×
    at T=2048, 1.18× at 4096, 1.29× at 8192; the rectangular schedule is
    marginally ahead at 512): "dma" from CAUSAL_SKIP_AUTO_THRESHOLD
    tokens up, "mxu" below. Non-causal calls ignore it.
    """
    if interpret is None:
        interpret = INTERPRET
    if causal_skip not in ("auto", "mxu", "dma"):
        raise ValueError(f"causal_skip {causal_skip!r} not one of "
                         f"('auto', 'mxu', 'dma')")
    if causal_skip == "dma" and not causal:
        raise ValueError("causal_skip='dma' only applies to causal "
                         "attention — drop it or set causal=True")
    if q.shape != k.shape or q.shape != v.shape:
        raise ValueError(f"q/k/v shapes differ: {q.shape} {k.shape} {v.shape}")
    t = q.shape[1]
    if causal_skip == "auto":
        causal_skip = resolve_causal_skip_auto(causal, t)
    t_pad = t
    if block_q is None and block_k is None:
        # auto blocks: when t's own divisors are a perf cliff (prime-ish
        # lengths — VERDICT r4 weak #4), pad internally to a proper block
        # multiple and mask the tail via kv_len; explicit block sizes stay
        # a strict divisibility contract. The plan's block is adopted even
        # WITHOUT padding — pad_to_block's divisor search finds exact
        # blocks (t=130 → 65, ADVICE r5) that _resolve_blocks' halving-only
        # pick_block would miss.
        t_pad, auto_block = pad_to_block(t)
        block_q = block_k = auto_block
        if t_pad != t:
            pad = ((0, 0), (0, t_pad - t), (0, 0), (0, 0))
            q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
            # padded keys are masked below; padded query rows are sliced
            # off (their zero cotangents keep the backward exact)
            kv_len = kv_len if kv_len is not None else t
    block_q, block_k = _resolve_blocks(t_pad, t_pad, block_q, block_k)
    if kv_len is not None:
        if not 1 <= kv_len <= t:
            raise ValueError(f"kv_len {kv_len} outside [1, {t}]")
        if kv_len == t_pad:
            kv_len = None   # no padding — don't fragment the op cache
    if causal_skip == "dma" and (kv_len is not None or block_q != block_k):
        causal_skip = "mxu"   # documented rectangular fallback — normalize
        #                       so it shares the mxu op-cache entry instead
        #                       of duplicating an identical compiled op
    out = _make_op(causal, block_q, block_k, interpret, kv_len,
                   causal_skip)(q, k, v)
    return out[:, :t] if t_pad != t else out
