"""3x3/2 ceil-mode (Caffe-semantics) max pooling.

Forward: `lax.reduce_window` over an explicitly padded input — identical
numerics to `nn.max_pool`; at 224 input this ceil-mode sizing is what yields
VGG-F's canonical 6x6x256 conv5 output / 9216-wide fc6 (~61M params).

Also in-tree: a hand-written backward (`set_maxpool_impl("custom_vjp")`) that
was a MEASURED NON-WIN and is kept as the documented counter-example.
Motivation: autodiff of reduce_window-max lowers to `lax.select_and_scatter`,
which the profile put at ~7% of the VGG-F train step, so a scatter-free
backward looked attractive: route each output's cotangent to the FIRST
maximum in its window (row-major tap order — the same winner
select_and_scatter picks) with nine stride-2 slices + dilated `lax.pad`s.
Result on v5e, full VGG-F train step, batch 1024 bf16: **92.1 vs 50.1
ms/step** — the nine strided spatial slices and nine full-size dilated
pad+adds cost far more than the fused select_and_scatter they replace.
Together with the shifted-slice LRN result (ops/lrn.py `_band_sum`), the
repeated TPU lesson: XLA's structured window ops are already well-lowered;
manual decompositions into slices/pads lose to them even when they look
cheaper on paper.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_WINDOW = 3
_STRIDE = 2


def _ceil_pads(shape) -> tuple:
    """Right/bottom padding for ceil-mode output size (>=1 for tiny inputs)."""
    pads = []
    for dim in (1, 2):
        n = shape[dim]
        out = max(1, -(-(n - _WINDOW) // _STRIDE) + 1)
        pads.append((0, max(0, (out - 1) * _STRIDE + _WINDOW - n)))
    return tuple(pads)


def _pool_valid(xp: jnp.ndarray) -> jnp.ndarray:
    return lax.reduce_window(
        xp, -jnp.inf if jnp.issubdtype(xp.dtype, jnp.floating)
        else jnp.iinfo(xp.dtype).min,
        lax.max, (1, _WINDOW, _WINDOW, 1), (1, _STRIDE, _STRIDE, 1), "VALID")


@jax.custom_vjp
def _pool_vjp(xp):
    return _pool_valid(xp)


def _pool_vjp_fwd(xp):
    y = _pool_valid(xp)
    return y, (xp, y)


def _pool_vjp_bwd(res, g):
    xp, y = res
    n, hp, wp, c = xp.shape
    ho, wo = y.shape[1], y.shape[2]
    grad = jnp.zeros(xp.shape, g.dtype)
    claimed = jnp.zeros(y.shape, jnp.bool_)
    for a in range(_WINDOW):
        for b in range(_WINDOW):
            h_end = a + _STRIDE * (ho - 1) + 1
            w_end = b + _STRIDE * (wo - 1) + 1
            xs = lax.slice(xp, (0, a, b, 0), (n, h_end, w_end, c),
                           (1, _STRIDE, _STRIDE, 1))
            sel = jnp.logical_and(xs == y, jnp.logical_not(claimed))
            claimed = jnp.logical_or(claimed, sel)
            m = jnp.where(sel, g, jnp.zeros((), g.dtype))
            # stride-2 scatter = interior (dilation) padding of the tap grid
            grad = grad + lax.pad(
                m, jnp.zeros((), g.dtype),
                ((0, 0, 0),
                 (a, hp - h_end, _STRIDE - 1),
                 (b, wp - w_end, _STRIDE - 1),
                 (0, 0, 0)))
    return (grad,)


_pool_vjp.defvjp(_pool_vjp_fwd, _pool_vjp_bwd)

_IMPL_OVERRIDE: str | None = None


def set_maxpool_impl(impl: str | None) -> None:
    """'autodiff' | 'custom_vjp' | None (auto: autodiff — the custom VJP is a
    measured non-win on TPU, see module docstring)."""
    global _IMPL_OVERRIDE
    if impl not in (None, "custom_vjp", "autodiff"):
        raise ValueError(f"unknown maxpool impl: {impl!r}")
    _IMPL_OVERRIDE = impl


def maxpool_3x3s2_ceil(x: jnp.ndarray) -> jnp.ndarray:
    """3x3/2 ceil-mode max pool — what models should call. At 224 input this
    yields VGG-F's canonical 6x6x256 conv5 output / 9216-wide fc6 (~61M
    params); floor-mode VALID pooling would silently lose ~12M fc6 params."""
    pads = _ceil_pads(x.shape)
    impl = _IMPL_OVERRIDE or "autodiff"
    if impl == "autodiff":
        import flax.linen as nn
        return nn.max_pool(x, window_shape=(_WINDOW, _WINDOW),
                           strides=(_STRIDE, _STRIDE), padding=pads)
    fill = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo(x.dtype).min)
    xp = jnp.pad(x, ((0, 0), pads[0], pads[1], (0, 0)),
                 constant_values=fill)
    return _pool_vjp(xp)
