"""Pallas TPU kernel for Local Response Normalization.

SURVEY.md §7 flagged LRN as the one Pallas-kernel candidate "if XLA fuses it
badly" — profiling on TPU v5e confirmed it does: the `lax.reduce_window`
formulation costs ~45% of the whole VGG-F train step (channel-window reductions
cross the 128-lane axis, and the `**0.75` power lowers to exp/log).

Kernel design (see /opt/skills/guides/pallas_guide.md):
- The activation tensor is viewed as rows of `pack` pixels × C channels so the
  lane dimension is always filled to >=128 even for C=64 (half-empty lanes cost
  2× bandwidth). Each grid step does one VMEM-resident fused pass:
      square (VPU) → window-sum as block-diagonal banded matmul (MXU) →
      d^-beta via rsqrt/sqrt (VPU, no transcendentals for beta=0.75) → scale.
- The window sum over channels is S = (x*x) @ B where B is `pack` copies of the
  C×C band `|i-j| <= r` on the diagonal — pixels packed into the same row cannot
  leak into each other's windows.
- Backward is a second kernel under `jax.custom_vjp`, saving only `x` as the
  residual and recomputing S (one extra tiny matmul beats an HBM round-trip of
  the normalizer):
      y = x * d^-b,  d = k + a*S
      dx = g * d^-b  -  2ab * x * (B @ (g * x * d^-(b+1)))
  (B symmetric, so the same band matrix serves both passes.)

Rows are independent (the contraction is only over the row width), so padding
rows in the final partial tile are garbage-in/masked-out by Pallas block
handling.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_vgg_f_tpu.ops.lrn import _pow_neg_beta, band_matrix_np

# Tests on CPU flip this to run the kernel in the Pallas interpreter, which
# validates kernel logic without TPU hardware (SURVEY.md §4 testing strategy).
INTERPRET = False

# Per-kernel VMEM budget for the row tile (bytes). The scoped VMEM limit is
# ~16 MB; the backward kernel keeps ~4 fp32 row-tile intermediates live.
_TILE_BYTES = 2 * 1024 * 1024


def _tile_rows(width: int) -> int:
    rows = _TILE_BYTES // (4 * width)
    return max(8, (rows // 8) * 8)


def _packed_band(num_channels: int, depth_radius: int, pack: int) -> np.ndarray:
    # Stays pure numpy: this runs inside jit traces, where jnp constants
    # would themselves become tracers under JAX's lazy-constant tracing.
    band = band_matrix_np(num_channels, depth_radius)
    w = pack * num_channels
    out = np.zeros((w, w), np.float32)
    for i in range(pack):
        s = i * num_channels
        out[s:s + num_channels, s:s + num_channels] = band
    return out


def _fwd_kernel(x_ref, band_ref, out_ref, *, a: float, bias: float, beta: float):
    xf = x_ref[:].astype(jnp.float32)
    sums = jnp.dot(xf * xf, band_ref[:], preferred_element_type=jnp.float32)
    scale = _pow_neg_beta(bias + a * sums, beta)
    out_ref[:] = (xf * scale).astype(out_ref.dtype)


def _bwd_kernel(x_ref, g_ref, band_ref, dx_ref, *, a: float, bias: float,
                beta: float):
    xf = x_ref[:].astype(jnp.float32)
    gf = g_ref[:].astype(jnp.float32)
    band = band_ref[:]
    d = bias + a * jnp.dot(xf * xf, band, preferred_element_type=jnp.float32)
    p = _pow_neg_beta(d, beta)                      # d^-beta
    t = gf * xf * (p / d)                           # g·x·d^-(beta+1)
    u = jnp.dot(t, band, preferred_element_type=jnp.float32)
    dx_ref[:] = (gf * p - (2.0 * a * beta) * xf * u).astype(dx_ref.dtype)


def _rowwise_call(kernel, out_dtype, operands, width):
    """Run a row-independent kernel over (M, width) operands on a 1-D M-tile
    grid. The band matrix is the last operand, broadcast to every tile."""
    m = operands[0].shape[0]
    tile = _tile_rows(width)
    row_spec = pl.BlockSpec((tile, width), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    band_spec = pl.BlockSpec((width, width), lambda i: (0, 0),
                             memory_space=pltpu.VMEM)
    return pl.pallas_call(
        kernel,
        grid=(pl.cdiv(m, tile),),
        in_specs=[row_spec] * (len(operands) - 1) + [band_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((m, width), out_dtype),
        interpret=INTERPRET,
    )(*operands)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def _lrn2d(x, channels, depth_radius, bias, a, beta):
    pack = x.shape[-1] // channels
    band = _packed_band(channels, depth_radius, pack)
    return _rowwise_call(
        functools.partial(_fwd_kernel, a=a, bias=bias, beta=beta),
        x.dtype, (x, band), x.shape[-1])


def _lrn2d_fwd(x, channels, depth_radius, bias, a, beta):
    return _lrn2d(x, channels, depth_radius, bias, a, beta), x


def _lrn2d_bwd(channels, depth_radius, bias, a, beta, x, g):
    pack = x.shape[-1] // channels
    band = _packed_band(channels, depth_radius, pack)
    dx = _rowwise_call(
        functools.partial(_bwd_kernel, a=a, bias=bias, beta=beta),
        x.dtype, (x, g, band), x.shape[-1])
    return (dx,)


_lrn2d.defvjp(_lrn2d_fwd, _lrn2d_bwd)


def local_response_norm_pallas(x: jnp.ndarray,
                               depth_radius: int = 2,
                               bias: float = 2.0,
                               alpha: float = 1e-4,
                               beta: float = 0.75,
                               *,
                               alpha_scaled: bool = False) -> jnp.ndarray:
    """LRN over the last (channel) axis as a fused Pallas TPU kernel.

    Same semantics as `ops.lrn.local_response_norm` (NHWC, channel_axis=-1)."""
    n = 2 * depth_radius + 1
    a = alpha / n if alpha_scaled else alpha
    shape = x.shape
    c = shape[-1]
    # Fill the 128-wide lane dimension by packing whole pixels into one row
    # when C < 128 and the flattened length allows it.
    total = int(np.prod(shape))
    pack = max(1, 128 // c)
    while pack > 1 and total % (pack * c) != 0:
        pack //= 2
    x2d = x.reshape(-1, pack * c)
    out = _lrn2d(x2d, c, depth_radius, float(bias), float(a), float(beta))
    return out.reshape(shape)
