"""Classification metrics: top-1 / top-5 correct counts (SURVEY.md §2.1 #3, §3.4).

Counts (not rates) are returned so they can be `psum`-accumulated across replicas
and eval batches, then divided once by the total example count."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def topk_correct(logits: jnp.ndarray, labels: jnp.ndarray, k: int,
                 valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """Number of examples whose true label is in the top-k logits.

    Uses `lax.top_k` (TPU-supported sort-based kernel, static k) rather than a
    full argsort. `valid` (bool per example) masks out padding rows from exact
    eval's pad-and-mask scheme — a zero-padded row would otherwise count as a
    class-0 "hit"."""
    _, topk_idx = lax.top_k(logits.astype(jnp.float32), k)
    hit = jnp.any(topk_idx == labels[:, None], axis=-1)
    if valid is not None:
        hit = jnp.logical_and(hit, valid)
    return jnp.sum(hit.astype(jnp.int32))
