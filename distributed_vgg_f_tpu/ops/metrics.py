"""Classification metrics: top-1 / top-5 correct counts (SURVEY.md §2.1 #3, §3.4).

Counts (not rates) are returned so they can be `psum`-accumulated across replicas
and eval batches, then divided once by the total example count."""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def topk_correct(logits: jnp.ndarray, labels: jnp.ndarray, k: int) -> jnp.ndarray:
    """Number of examples whose true label is in the top-k logits.

    Uses `lax.top_k` (TPU-supported sort-based kernel, static k) rather than a
    full argsort."""
    _, topk_idx = lax.top_k(logits.astype(jnp.float32), k)
    hit = jnp.any(topk_idx == labels[:, None], axis=-1)
    return jnp.sum(hit.astype(jnp.int32))
