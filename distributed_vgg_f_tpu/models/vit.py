"""ViT-S/16 — BASELINE.json config #5: "patch-embed + attention under the same
DP all-reduce".

Dosovitskiy et al. 2020 / Touvron DeiT-S dimensions: patch 16, width 384,
depth 12, heads 6, MLP 1536, cls token, learned position embeddings.

SURVEY.md §5 (long-context): sequence length is 197 tokens under plain DP — no
sequence sharding required or built; attention runs per-replica on the MXU
(bf16 matmuls), with fp32 softmax for stability.
"""

from __future__ import annotations

import math
import os
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


#: attention_layout="auto" switches to the Pallas flash kernel from this
#: many tokens. Evidence-backed edges only (r4 v5e microbench, fwd+bwd,
#: non-causal): XLA's fused einsum wins every measured point up to 4096
#: (31.9 vs 58.9 ms) and FAILS TO COMPILE at 8192 (4 GiB probs), where
#: flash runs 214.9 ms — so the switch sits at 8192 until a measured
#: 4k-8k crossover (the r5 long-context rows) justifies lowering it.
#: Env-overridable for other chip generations, same pattern as
#: ops.flash_attention.CAUSAL_SKIP_AUTO_THRESHOLD.
try:
    ATTENTION_AUTO_FLASH_THRESHOLD = int(
        os.environ.get("DVGGF_ATTENTION_AUTO_FLASH_THRESHOLD", 8192))
except ValueError as _e:
    raise ValueError(
        "DVGGF_ATTENTION_AUTO_FLASH_THRESHOLD must be an integer token "
        "count, got "
        f"{os.environ['DVGGF_ATTENTION_AUTO_FLASH_THRESHOLD']!r}") from _e


class FusedSelfAttention(nn.Module):
    """Self-attention with ONE fused QKV projection.

    Why not `nn.MultiHeadDotProductAttention`: it issues three separate
    (D, D) projection GEMMs per block; fusing them into a single (D, 3·H·hd)
    GEMM keeps the MXU on one large matmul and removes two kernel-launch /
    fusion boundaries per block — a ViT-S/16 step is 12 blocks deep, so the
    savings compound (VERDICT r2 #2 ViT candidate; TPU measurement tracked
    in PARITY.md). Numerics match flax's module exactly given repacked
    params (tests/test_model_zoo.py::test_fused_attention_matches_flax_mha);
    softmax runs in fp32 (bf16 logits lose ~2 decimal digits across 197
    tokens' worth of exp/sum).

    `dropout_rate` here is ATTENTION-WEIGHT dropout (the (B,H,T,T) probs
    tensor). The r3 TPU trace showed generating those masks cost ~10% of the
    ViT step (rng-bit-generator + per-block uniforms), so the model default
    is 0.0 — matching the canonical recipes for these dimensions (DeiT-S and
    the official ViT ImageNet configs both set attention dropout 0.0 while
    keeping 0.1 elsewhere). Set `model.extra.attention_dropout_rate` to
    re-enable.

    `layout` selects where the head axis lives between the projections:
      - "head_major": one explicit (B,T,3,H,hd)→(3,B,H,T,hd) transpose right
        after the QKV GEMM; q/k/v are then free major-axis slices already in
        the (b,h,t,d) layout both attention einsums want, so XLA inserts no
        further operand transposes.
      - "token_major": split+squeeze on the packed middle axis (three strided
        copies) and token-major einsums whose operands XLA must transpose —
        measured 15.5% of the step in `data formatting` HLOs (r3 trace).
      - "flash": the Pallas blockwise kernel (ops/flash_attention.py) — pads
        197 → 256 tokens with kv_len masking; (T, T) probs never reach HBM.
        Incompatible with attention-weight dropout (probs don't exist).
      - "auto": the measured regime rule as code — head_major below
        ATTENTION_AUTO_FLASH_THRESHOLD tokens (XLA's fused einsum wins the
        whole measured range 512–4096: r4 microbench, 31.9 vs 58.9 ms at
        4k), flash from the threshold up (XLA cannot even compile the 4 GiB
        probs at 8192; flash runs it at 214.9 ms — the kernel is the only
        path). Resolved per call from the actual T.
    All layouts share identical param shapes (checkpoint-compatible).
    """

    num_heads: int
    dropout_rate: float
    compute_dtype: Any
    layout: str = "head_major"

    def __post_init__(self):
        # Eager rejection (ADVICE r5): "flash" can never apply attention-
        # weight dropout, and "auto" ROUTES to flash once T crosses the
        # threshold — deferring that to call time made the failure
        # length-dependent (a config validated fine at T=197 and blew up the
        # first long-context batch). Reject at construction, naming the
        # configured layout.
        if self.layout in ("flash", "auto") and self.dropout_rate > 0.0:
            raise ValueError(
                f"attention layout {self.layout!r} uses the flash kernel "
                f"(for 'auto': once T >= ATTENTION_AUTO_FLASH_THRESHOLD), "
                f"which never materializes the attention weights — "
                f"incompatible with attention-weight dropout_rate="
                f"{self.dropout_rate}; pick an einsum layout "
                f"('head_major'/'token_major') or set the attention "
                f"dropout to 0")
        super().__post_init__()

    @nn.compact
    def __call__(self, x, *, train: bool):
        B, T, D = x.shape
        H = self.num_heads
        hd = D // H
        layout = self.layout
        if layout == "auto":
            layout = ("flash" if T >= ATTENTION_AUTO_FLASH_THRESHOLD
                      else "head_major")
        qkv = nn.DenseGeneral((3, H, hd), axis=-1, dtype=self.compute_dtype,
                              param_dtype=jnp.float32, name="qkv")(x)
        # weak python float: a numpy scalar is a STRONG type and would
        # promote q (and the QK^T GEMM) to fp32 under bf16 compute
        scale = 1.0 / math.sqrt(hd)
        if layout == "flash":
            # Pallas blockwise kernel (ops/flash_attention.py): probs never
            # materialize, so attention-weight dropout cannot apply —
            # flash/auto + dropout_rate > 0 is rejected in __post_init__.
            from distributed_vgg_f_tpu.ops.flash_attention import (
                flash_self_attention)
            q, k, v = (jnp.squeeze(t_, 2) for t_ in jnp.split(qkv, 3, axis=2))
            # pad-to-block (197 → 256 with kv_len masking) happens INSIDE
            # flash_self_attention since the r5 pad_to_block work — the
            # hand-rolled copy of that padding that used to live here was
            # the same mechanism at the wrong altitude (simplify r5)
            ctx = flash_self_attention(q, k, v)
            return nn.DenseGeneral(D, axis=(-2, -1), dtype=self.compute_dtype,
                                   param_dtype=jnp.float32, name="out")(ctx)
        if layout == "head_major":
            qkv = jnp.transpose(qkv, (2, 0, 3, 1, 4))  # (3, B, H, T, hd)
            q, k, v = qkv[0] * scale, qkv[1], qkv[2]
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k)
        elif layout == "token_major":
            q, k, v = (jnp.squeeze(t, 2) for t in jnp.split(qkv, 3, axis=2))
            q = q * scale
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k)
        else:
            raise ValueError(f"unknown attention layout {layout!r}")
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        probs = probs.astype(self.compute_dtype)
        if train and self.dropout_rate > 0.0:
            probs = nn.Dropout(self.dropout_rate, deterministic=False)(probs)
        if layout == "head_major":
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
            # contract (H, hd) out of (B, H, T, hd) → (B, T, D); same
            # (H, hd, D) kernel as the token-major path
            return nn.DenseGeneral(D, axis=(1, 3), dtype=self.compute_dtype,
                                   param_dtype=jnp.float32, name="out")(ctx)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        return nn.DenseGeneral(D, axis=(-2, -1), dtype=self.compute_dtype,
                               param_dtype=jnp.float32, name="out")(ctx)


class MlpBlock(nn.Module):
    mlp_dim: int
    dropout_rate: float
    compute_dtype: Any

    @nn.compact
    def __call__(self, x, *, train: bool):
        d = x.shape[-1]
        x = nn.Dense(self.mlp_dim, dtype=self.compute_dtype,
                     param_dtype=jnp.float32, name="fc1")(x)
        x = nn.gelu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(d, dtype=self.compute_dtype, param_dtype=jnp.float32,
                     name="fc2")(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return x


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dropout_rate: float
    compute_dtype: Any
    attention_dropout_rate: float = 0.0
    attention_layout: str = "head_major"

    @nn.compact
    def __call__(self, x, *, train: bool):
        y = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        y = FusedSelfAttention(
            num_heads=self.num_heads,
            dropout_rate=self.attention_dropout_rate,
            compute_dtype=self.compute_dtype,
            layout=self.attention_layout, name="attn")(y, train=train)
        x = x + nn.Dropout(self.dropout_rate, deterministic=not train)(y)
        y = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        y = MlpBlock(self.mlp_dim, self.dropout_rate, self.compute_dtype,
                     name="mlp")(y, train=train)
        return x + y


class ViT(nn.Module):
    num_classes: int = 1000
    patch_size: int = 16
    hidden_dim: int = 384
    depth: int = 12
    num_heads: int = 6
    mlp_dim: int = 1536
    dropout_rate: float = 0.1
    # attention-WEIGHT dropout; 0.0 per the canonical DeiT-S / official ViT
    # recipes AND the r3 trace (mask RNG alone was ~10% of the TPU step)
    attention_dropout_rate: float = 0.0
    attention_layout: str = "head_major"
    compute_dtype: Any = jnp.bfloat16

    def __post_init__(self):
        # Same eager rejection as FusedSelfAttention, but at MODEL build
        # time (registry.build_model) — the inner module is only constructed
        # on the first trace, which is still later than a config error
        # should surface (ADVICE r5).
        if self.attention_layout in ("flash", "auto") \
                and self.attention_dropout_rate > 0.0:
            raise ValueError(
                f"attention_layout {self.attention_layout!r} uses the flash "
                f"kernel (for 'auto': once T crosses the flash threshold), "
                f"which never materializes the attention weights — "
                f"incompatible with attention_dropout_rate="
                f"{self.attention_dropout_rate}; pick an einsum layout "
                f"('head_major'/'token_major') or set "
                f"model.extra.attention_dropout_rate=0")
        super().__post_init__()

    @classmethod
    def s16(cls, **kwargs) -> "ViT":
        return cls(**kwargs)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        from distributed_vgg_f_tpu.models.ingest import reject_raw_uint8
        reject_raw_uint8(x, "ViT")  # u8-wire zoo contract
        B = x.shape[0]
        x = x.astype(self.compute_dtype)
        # patch embedding as a strided conv → (B, H/p, W/p, D), then flatten
        x = nn.Conv(self.hidden_dim,
                    (self.patch_size, self.patch_size),
                    strides=(self.patch_size, self.patch_size),
                    padding="VALID", dtype=self.compute_dtype,
                    param_dtype=jnp.float32, name="patch_embed")(x)
        x = x.reshape(B, -1, self.hidden_dim)

        cls_tok = self.param("cls", nn.initializers.zeros,
                             (1, 1, self.hidden_dim), jnp.float32)
        x = jnp.concatenate(
            [jnp.broadcast_to(cls_tok.astype(self.compute_dtype),
                              (B, 1, self.hidden_dim)), x], axis=1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, x.shape[1], self.hidden_dim), jnp.float32)
        x = x + pos.astype(self.compute_dtype)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)

        for i in range(self.depth):
            x = EncoderBlock(self.num_heads, self.mlp_dim, self.dropout_rate,
                             self.compute_dtype,
                             attention_dropout_rate=self.attention_dropout_rate,
                             attention_layout=self.attention_layout,
                             name=f"block{i}")(x, train=train)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_final")(x)
        x = x[:, 0]
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)
