"""Model registry — keeps the trainer model-agnostic (SURVEY.md §7: configs are
config swaps, not forks). `build_model(cfg.model)` returns a Flax module whose
`__call__(images, train=...)` yields logits.

The registry is also the public surface of the per-model INGEST contract
(r13): `ingest_descriptor(name)` declares what each stem consumes from the
u8 ingest wire — packed vs plain layout, stem dtype, normalize constants —
replacing the VGGF-only preset wiring. The table itself lives in
models/ingest.py (a light module: presets and benches read descriptors
without importing flax)."""

from __future__ import annotations

from typing import Callable, Dict

import flax.linen as nn
import jax.numpy as jnp

from distributed_vgg_f_tpu.config import ModelConfig
from distributed_vgg_f_tpu.models.ingest import (  # noqa: F401 — re-export
    INGEST_DESCRIPTORS,
    IngestDescriptor,
    ingest_descriptor,
)

_REGISTRY: Dict[str, Callable[[ModelConfig], nn.Module]] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def available_models():
    return sorted(_REGISTRY)


def build_model(cfg: ModelConfig) -> nn.Module:
    try:
        builder = _REGISTRY[cfg.name]
    except KeyError:
        raise KeyError(f"unknown model {cfg.name!r}; available: {available_models()}")
    return builder(cfg)


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.compute_dtype)


@register("vggf")
def _build_vggf(cfg: ModelConfig) -> nn.Module:
    from distributed_vgg_f_tpu.models.vggf import VGGF
    return VGGF(num_classes=cfg.num_classes, dropout_rate=cfg.dropout_rate,
                compute_dtype=_dtype(cfg), **cfg.extra)


@register("vggf_student")
def _build_vggf_student(cfg: ModelConfig) -> nn.Module:
    # Half-width CNN-F (stem 32, convs 128, FC 2048) — the distillation
    # target train/distill.py trains against data/teacher.py logits, served
    # as the `student` tier (serving/tiers.py). Serving-only: no training
    # preset derives from it (models/ingest.py serving_only flag).
    from distributed_vgg_f_tpu.models.vggf import VGGF
    return VGGF(num_classes=cfg.num_classes, dropout_rate=cfg.dropout_rate,
                compute_dtype=_dtype(cfg), stem_features=32,
                conv_features=128, fc_features=2048, **cfg.extra)


@register("vgg16")
def _build_vgg16(cfg: ModelConfig) -> nn.Module:
    from distributed_vgg_f_tpu.models.vgg16 import VGG16
    return VGG16(num_classes=cfg.num_classes, dropout_rate=cfg.dropout_rate,
                 compute_dtype=_dtype(cfg), **cfg.extra)


@register("resnet50")
def _build_resnet50(cfg: ModelConfig) -> nn.Module:
    from distributed_vgg_f_tpu.models.resnet import ResNet50
    return ResNet50(num_classes=cfg.num_classes, compute_dtype=_dtype(cfg),
                    **cfg.extra)


@register("vit_s16")
def _build_vit_s16(cfg: ModelConfig) -> nn.Module:
    from distributed_vgg_f_tpu.models.vit import ViT
    return ViT.s16(num_classes=cfg.num_classes, dropout_rate=cfg.dropout_rate,
                   compute_dtype=_dtype(cfg), **cfg.extra)
