from distributed_vgg_f_tpu.models.registry import (  # noqa: F401
    available_models,
    build_model,
    register,
)
