"""Per-model ingest descriptors — ONE u8-wire + device-ingest contract for
the whole model zoo (r13).

Through r12 only the VGG-F stem was first-class on the uint8 ingest wire:
the flagship preset wired `wire='u8'` + `space_to_depth=True` by hand and
the derived zoo presets hand-overrode the packing back off. The descriptor
table below replaces that VGGF-only wiring with a per-model declaration of
what each stem actually consumes:

- `space_to_depth` — whether the stem takes the 4x4-packed (S/4, S/4, 48)
  input layout (models/vggf.py Conv1SpaceToDepth's contract). Models whose
  stems take plain (S, S, 3) declare False and the device-finish prologue
  simply skips the relayout. (ResNet-50's optional 2x2 stem trick,
  models/resnet.py StemConv, is an ON-DEVICE relayout behind
  `model.extra.stem` — it consumes (S, S, 3) from the wire either way, so
  its descriptor stays False.)
- `stem_dtype` — the compute dtype the stem casts wire pixels into (the
  models' `compute_dtype` default); recorded so benches and receipts can
  label per-model rows without instantiating flax modules.
- `mean_rgb` / `stddev_rgb` — the normalize constants the device finish
  folds into the jitted step for this model (the zoo shares the ImageNet
  constants; a future model with different constants declares them HERE,
  not in a preset override).
- `wire` — the ingest wire the model's preset ships by default. Every zoo
  stem consumes the u8 contract: raw uint8 pixels over the wire,
  normalize/cast/(pack) fused into the step (data/device_ingest.py).
- `accepts_uint8` — always False for the zoo: raw 0..255 pixels must NEVER
  reach a stem (every model raises TypeError; the device finish is the
  only legal consumer of wire pixels).

This module is deliberately LIGHT (no flax/jax/numpy imports): config.py
presets resolve descriptors at preset-build time and the bench labels rows
from them, neither of which should pull the model libraries in. The public
import surface is models/registry.py, which re-exports everything here
next to `build_model`.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

#: The ImageNet normalize constants every zoo model shares (the values
#: DataConfig defaults to; single-sourced here so descriptor and config
#: can never drift apart — config's defaults are pinned equal by test).
IMAGENET_MEAN_RGB: Tuple[float, float, float] = (123.68, 116.78, 103.94)
IMAGENET_STDDEV_RGB: Tuple[float, float, float] = (58.393, 57.12, 57.375)


@dataclasses.dataclass(frozen=True)
class IngestDescriptor:
    """What one model's stem consumes from the ingest wire."""
    model: str
    #: stem consumes the 4x4-packed (S/4, S/4, 48) layout (VGG-F only)
    space_to_depth: bool = False
    #: compute dtype the stem casts pixels into (the model default)
    stem_dtype: str = "bfloat16"
    #: per-model normalize constants the device finish applies
    mean_rgb: Tuple[float, float, float] = IMAGENET_MEAN_RGB
    stddev_rgb: Tuple[float, float, float] = IMAGENET_STDDEV_RGB
    #: the ingest wire the model's preset ships (u8 for the whole zoo)
    wire: str = "u8"
    #: raw wire pixels may reach the stem directly (never, for the zoo —
    #: every stem raises TypeError on uint8; the device finish is the only
    #: legal consumer)
    accepts_uint8: bool = False
    #: serving-only preset (r23): the model exists for the serving tier
    #: ladder (a distilled student), not as a training preset — excluded
    #: from `zoo_model_names()` so the training/parity grids and the
    #: per-model presets never pick it up, but first-class for the serving
    #: router (serving/tiers.py builds the `student` tier from it)
    serving_only: bool = False

    def describe(self) -> dict:
        """JSON-ready receipt for bench rows and the trainer start record."""
        return {"model": self.model, "wire": self.wire,
                "space_to_depth": self.space_to_depth,
                "stem_dtype": self.stem_dtype}


#: The zoo contract table — one row per registered model. A model missing
#: here gets the conservative default (unpacked, u8 wire, ImageNet
#: constants) via `ingest_descriptor`.
INGEST_DESCRIPTORS: Dict[str, IngestDescriptor] = {
    "vggf": IngestDescriptor("vggf", space_to_depth=True),
    "vgg16": IngestDescriptor("vgg16"),
    "resnet50": IngestDescriptor("resnet50"),
    "vit_s16": IngestDescriptor("vit_s16"),
    # the half-width distillation target (train/distill.py) behind the
    # `student` serving tier — same stem contract as the flagship it
    # stands in for, but never a training preset
    "vggf_student": IngestDescriptor("vggf_student", space_to_depth=True,
                                     serving_only=True),
}


def reject_raw_uint8(x, model_name: str) -> None:
    """The zoo-wide `accepts_uint8=False` contract, enforced once: raw
    wire pixels must be finished (normalize/cast, data/device_ingest.py)
    BEFORE any stem — silently casting 0..255 integers to the compute
    dtype would train on an input distribution ~50x off the normalized
    one, with no error. The trainer/eval/predict steps all install the
    finish; a uint8 reaching a model means some caller bypassed it.
    Dtype-name comparison keeps this module jax-free (the import-weight
    contract in the module docstring); trace-time shapes carry a real
    dtype either way."""
    if str(getattr(x, "dtype", "")) == "uint8":
        raise TypeError(
            f"{model_name} received a raw uint8 batch — apply the "
            "device-finish prologue (data/device_ingest.py "
            "make_device_finish) before the model; the train/eval/predict "
            "steps install it automatically")


def zoo_model_names(*, include_serving_only: bool = False) -> Tuple[str, ...]:
    """The registered zoo, in table order — the serving router's model
    vocabulary (serving/server.py fronts one engine per descriptor row)
    and the per-model test grids iterate THIS, never a hand-kept list.
    Serving-only rows (the distilled student) are excluded by default so
    training grids and presets never see them; the serving surfaces opt
    in with `include_serving_only=True`."""
    return tuple(name for name, d in INGEST_DESCRIPTORS.items()
                 if include_serving_only or not d.serving_only)


def ingest_descriptor(model_name: str) -> IngestDescriptor:
    """The model's ingest contract; unknown models get the conservative
    unpacked default (so out-of-zoo experiments keep working) — packing is
    strictly opt-in via the table because a wrongly-packed batch fails
    shapes loudly while an unpacked one merely loses the stem trick."""
    desc = INGEST_DESCRIPTORS.get(model_name)
    if desc is None:
        return IngestDescriptor(model_name)
    return desc
