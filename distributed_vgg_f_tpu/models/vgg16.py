"""VGG-16 — BASELINE.json config #3: "deeper conv stack, same DP path".

Simonyan & Zisserman 2014 configuration D: 13 conv3x3 layers in five blocks
(64,64 / 128,128 / 256x3 / 512x3 / 512x3), 2x2/2 max-pool after each block,
fc 4096-4096-N. ~138M params at 1000 classes. No LRN (the VGG paper dropped it).

Same TPU conventions as VGG-F: NHWC, bf16 compute on the MXU, fp32 params/logits.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class VGG16(nn.Module):
    num_classes: int = 1000
    dropout_rate: float = 0.5
    compute_dtype: Any = jnp.bfloat16
    block_sizes: Sequence[int] = (2, 2, 3, 3, 3)
    block_features: Sequence[int] = (64, 128, 256, 512, 512)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        from distributed_vgg_f_tpu.models.ingest import reject_raw_uint8
        reject_raw_uint8(x, "VGG16")  # u8-wire zoo contract
        x = x.astype(self.compute_dtype)
        for b, (reps, feat) in enumerate(zip(self.block_sizes,
                                             self.block_features), start=1):
            for i in range(1, reps + 1):
                x = nn.Conv(feat, (3, 3), padding="SAME",
                            dtype=self.compute_dtype, param_dtype=jnp.float32,
                            name=f"conv{b}_{i}")(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.compute_dtype,
                             param_dtype=jnp.float32, name="fc6")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.compute_dtype,
                             param_dtype=jnp.float32, name="fc7")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=jnp.float32, name="fc8")(x)
        return x.astype(jnp.float32)
