"""VGG-F (CNN-F) — the reference's flagship model.

Architecture per SURVEY.md §3.3 (BASELINE.json north_star: "conv→ReLU→LRN→max-pool
stack + 3 FC heads"; exact dims from Chatfield et al., *Return of the Devil in the
Details*, BMVC 2014, Table 1 CNN-F row — the reference mount was empty, see
SURVEY.md §0):

    conv1 64@11x11/4 (VALID) → ReLU → LRN → maxpool 3x3/2
    conv2 256@5x5/1 (SAME)   → ReLU → LRN → maxpool 3x3/2
    conv3 256@3x3/1 (SAME)   → ReLU
    conv4 256@3x3/1 (SAME)   → ReLU
    conv5 256@3x3/1 (SAME)   → ReLU → maxpool 3x3/2
    flatten → fc6 4096 → ReLU → dropout
            → fc7 4096 → ReLU → dropout → fc8 num_classes

≈61M parameters at 1000 classes / 224×224 input.

TPU notes: convs/matmuls run in `compute_dtype` (bfloat16 by default) on the MXU
with float32 params; LRN computes its normalizer in float32 (ops/lrn.py). All
shapes static, NHWC layout (XLA:TPU's preferred image layout).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from distributed_vgg_f_tpu.ops.lrn import lrn as local_response_norm
from distributed_vgg_f_tpu.ops.pooling import maxpool_3x3s2_ceil


class Conv1SpaceToDepth(nn.Module):
    """VGG-F's 11x11/4 stem conv, computed via 4x4 space-to-depth.

    C_in=3 packs the MXU's 128-wide contraction lanes terribly (~12% MXU
    utilization measured for the plain conv at batch 1024 on v5e). The classic
    TPU fix (MLPerf ResNet stem trick): reshape the input 224x224x3 →
    56x56x48 (4x4 pixel blocks into channels) and convolve with the kernel
    rearranged to 3x3x48x64 at stride 1 — bit-identical output (the zero-padded
    12th tap multiplies pixels the 11-tap kernel never saw *within each 4-pixel
    phase*, i.e. nothing), with a 16x deeper contraction. Falls back to the
    plain conv when H/W aren't multiples of 4 (or are too small), so arbitrary
    input sizes keep working. The logical parameter stays (11,11,3,64) —
    checkpoints and torch-parity are layout-unchanged."""

    features: int = 64
    compute_dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (11, 11, 3, self.features), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (self.features,),
                          jnp.float32)
        h, w = x.shape[1], x.shape[2]
        packed = x.shape[-1] == 48  # input already space-to-depth packed
        if packed or (h % 4 == 0 and w % 4 == 0 and h >= 12 and w >= 12):
            if packed:
                # the host pipeline (data.space_to_depth) already emitted
                # (H/4, W/4, 48) blocks — skip the on-device relayout
                xs = x
            else:
                b = x.shape[0]
                xs = x.reshape(b, h // 4, 4, w // 4, 4, 3)
                xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(
                    b, h // 4, w // 4, 48)
            k = jnp.pad(kernel, ((0, 1), (0, 1), (0, 0), (0, 0)))  # 12x12 taps
            k = k.reshape(3, 4, 3, 4, 3, self.features)
            k = k.transpose(0, 2, 1, 3, 4, 5).reshape(3, 3, 48, self.features)
            y = lax.conv_general_dilated(
                xs, k.astype(self.compute_dtype), window_strides=(1, 1),
                padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        else:
            y = lax.conv_general_dilated(
                x, kernel.astype(self.compute_dtype), window_strides=(4, 4),
                padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return y + bias.astype(self.compute_dtype)


# 3x3/2 ceil-mode (Caffe-semantics) max pool with a hand-written backward —
# see ops/pooling.py for the why (select_and_scatter was ~7% of the step).
_maxpool_3x3s2 = maxpool_3x3s2_ceil


class VGGF(nn.Module):
    num_classes: int = 1000
    dropout_rate: float = 0.5
    compute_dtype: Any = jnp.bfloat16
    # LRN hyperparameters (AlexNet-paper / TF convention; SURVEY.md §7 hard parts).
    lrn_depth_radius: int = 2
    lrn_bias: float = 2.0
    lrn_alpha: float = 1e-4
    lrn_beta: float = 0.75
    # Layer widths. The defaults ARE CNN-F (param shapes unchanged for every
    # existing checkpoint); the serving-only `vggf_student` zoo preset halves
    # all three (models/registry.py) — the distillation target of
    # train/distill.py and the `student` serving tier.
    stem_features: int = 64
    conv_features: int = 256
    fc_features: int = 4096

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        conv = lambda feat, kernel, stride, pad, name: nn.Conv(
            feat, kernel, strides=stride, padding=pad, name=name,
            dtype=self.compute_dtype, param_dtype=jnp.float32)
        dense = lambda feat, name: nn.Dense(
            feat, name=name, dtype=self.compute_dtype, param_dtype=jnp.float32)
        lrn = lambda v: local_response_norm(
            v, self.lrn_depth_radius, self.lrn_bias, self.lrn_alpha, self.lrn_beta)

        from distributed_vgg_f_tpu.models.ingest import reject_raw_uint8
        reject_raw_uint8(x, "VGGF")  # u8-wire contract (r8; zoo-wide r13)
        x = x.astype(self.compute_dtype)
        x = nn.relu(Conv1SpaceToDepth(self.stem_features, self.compute_dtype,
                                      name="conv1")(x))
        x = _maxpool_3x3s2(lrn(x))
        x = nn.relu(conv(self.conv_features, (5, 5), (1, 1), "SAME", "conv2")(x))
        x = _maxpool_3x3s2(lrn(x))
        x = nn.relu(conv(self.conv_features, (3, 3), (1, 1), "SAME", "conv3")(x))
        x = nn.relu(conv(self.conv_features, (3, 3), (1, 1), "SAME", "conv4")(x))
        x = nn.relu(conv(self.conv_features, (3, 3), (1, 1), "SAME", "conv5")(x))
        x = _maxpool_3x3s2(x)

        x = x.reshape((x.shape[0], -1))
        x = nn.relu(dense(self.fc_features, "fc6")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.relu(dense(self.fc_features, "fc7")(x))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = dense(self.num_classes, "fc8")(x)
        return x.astype(jnp.float32)
