"""ResNet-50 with cross-replica sync-BatchNorm — BASELINE.json config #4.

He et al. 2015, v1.5 variant (stride-2 on the 3x3 conv of downsampling
bottlenecks — the variant used by standard ImageNet throughput benchmarks).

Sync-BN (SURVEY.md §2.3 cross-replica statistics): `nn.BatchNorm` is given the
mesh's data axis as `axis_name`, so during training the batch mean/var are
`pmean`-reduced across all replicas inside the jitted step — global-batch
statistics over ICI, the TPU-native equivalent of NCCL sync-BN. Running averages
then update identically on every replica, keeping state replicated. Set
`bn_axis_name=None` for per-replica (local) BN.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax import lax


class StemConv(nn.Module):
    """ResNet's 7x7/2 stem conv, optionally computed via 2x2 space-to-depth.

    `stem="space_to_depth"` (the targeted experiment from the r3 trace,
    VERDICT r3 #5): C_in=3 underfills the MXU's 128-deep contraction the
    same way VGG-F's stem did (models/vggf.py Conv1SpaceToDepth). Reshape
    the input HxWx3 → (H/2)x(W/2)x12 (2x2 pixel blocks into channels) and
    convolve with the kernel zero-padded 7x7 → 8x8 (one leading tap) and
    rearranged to 4x4x12xF at stride 1, block padding (2, 1): output i
    reads pixel taps 2i−4..2i+3 = blocks i−2..i+1, where the −4 tap is the
    zero row — bit-identical to the 7x7/2 pad-3 conv, with a 4x deeper
    contraction. The logical parameter stays (7, 7, 3, F) — checkpoints are
    layout-unchanged. Falls back to the plain conv when H/W aren't even.
    """

    features: int = 64
    compute_dtype: Any = jnp.bfloat16
    stem: str = "conv7"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.stem not in ("conv7", "space_to_depth"):
            raise ValueError(f"unknown resnet stem {self.stem!r}; "
                             f"expected 'conv7' or 'space_to_depth'")
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (7, 7, 3, self.features), jnp.float32)
        h, w = x.shape[1], x.shape[2]
        if (self.stem == "space_to_depth" and h % 2 == 0 and w % 2 == 0
                and min(h, w) >= 8):
            b = x.shape[0]
            xs = x.reshape(b, h // 2, 2, w // 2, 2, 3)
            xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 12)
            k = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))  # 8x8 taps
            k = k.reshape(4, 2, 4, 2, 3, self.features)
            k = k.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 12, self.features)
            return lax.conv_general_dilated(
                xs, k.astype(self.compute_dtype), window_strides=(1, 1),
                padding=[(2, 1), (2, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return lax.conv_general_dilated(
            x, kernel.astype(self.compute_dtype), window_strides=(2, 2),
            padding=[(3, 3), (3, 3)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class BottleneckBlock(nn.Module):
    features: int          # width of the 1x1/3x3 convs; output is 4x this
    strides: int = 1
    compute_dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = "data"

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> jnp.ndarray:
        conv = functools.partial(nn.Conv, use_bias=False,
                                 dtype=self.compute_dtype,
                                 param_dtype=jnp.float32)
        bn = functools.partial(nn.BatchNorm, use_running_average=not train,
                               momentum=0.9, epsilon=1e-5,
                               dtype=self.compute_dtype,
                               param_dtype=jnp.float32,
                               axis_name=self.bn_axis_name if train else None)
        residual = x
        y = nn.relu(bn(name="bn1")(conv(self.features, (1, 1), name="conv1")(x)))
        y = nn.relu(bn(name="bn2")(conv(self.features, (3, 3),
                                        strides=(self.strides, self.strides),
                                        name="conv2")(y)))
        # zero-init the last BN scale: identity-at-init residual branch,
        # standard large-batch ResNet practice (Goyal et al.).
        y = bn(name="bn3", scale_init=nn.initializers.zeros)(
            conv(4 * self.features, (1, 1), name="conv3")(y))
        if residual.shape != y.shape:
            residual = bn(name="bn_proj")(
                conv(4 * self.features, (1, 1),
                     strides=(self.strides, self.strides),
                     name="conv_proj")(residual))
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = "data"
    stem: str = "conv7"      # or "space_to_depth" (StemConv docstring)

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        from distributed_vgg_f_tpu.models.ingest import reject_raw_uint8
        reject_raw_uint8(x, "ResNet")  # u8-wire zoo contract
        x = x.astype(self.compute_dtype)
        x = StemConv(64, self.compute_dtype, stem=self.stem,
                     name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.compute_dtype,
                         param_dtype=jnp.float32,
                         axis_name=self.bn_axis_name if train else None,
                         name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block in range(num_blocks):
                x = BottleneckBlock(
                    features=64 * 2 ** stage,
                    strides=2 if stage > 0 and block == 0 else 1,
                    compute_dtype=self.compute_dtype,
                    bn_axis_name=self.bn_axis_name,
                    name=f"stage{stage + 1}_block{block + 1}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def ResNet50(**kwargs) -> ResNet:
    kwargs.setdefault("stage_sizes", (3, 4, 6, 3))
    return ResNet(**kwargs)
