"""ResNet-50 with cross-replica sync-BatchNorm — BASELINE.json config #4.

He et al. 2015, v1.5 variant (stride-2 on the 3x3 conv of downsampling
bottlenecks — the variant used by standard ImageNet throughput benchmarks).

Sync-BN (SURVEY.md §2.3 cross-replica statistics): `nn.BatchNorm` is given the
mesh's data axis as `axis_name`, so during training the batch mean/var are
`pmean`-reduced across all replicas inside the jitted step — global-batch
statistics over ICI, the TPU-native equivalent of NCCL sync-BN. Running averages
then update identically on every replica, keeping state replicated. Set
`bn_axis_name=None` for per-replica (local) BN.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


class BottleneckBlock(nn.Module):
    features: int          # width of the 1x1/3x3 convs; output is 4x this
    strides: int = 1
    compute_dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = "data"

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool) -> jnp.ndarray:
        conv = functools.partial(nn.Conv, use_bias=False,
                                 dtype=self.compute_dtype,
                                 param_dtype=jnp.float32)
        bn = functools.partial(nn.BatchNorm, use_running_average=not train,
                               momentum=0.9, epsilon=1e-5,
                               dtype=self.compute_dtype,
                               param_dtype=jnp.float32,
                               axis_name=self.bn_axis_name if train else None)
        residual = x
        y = nn.relu(bn(name="bn1")(conv(self.features, (1, 1), name="conv1")(x)))
        y = nn.relu(bn(name="bn2")(conv(self.features, (3, 3),
                                        strides=(self.strides, self.strides),
                                        name="conv2")(y)))
        # zero-init the last BN scale: identity-at-init residual branch,
        # standard large-batch ResNet practice (Goyal et al.).
        y = bn(name="bn3", scale_init=nn.initializers.zeros)(
            conv(4 * self.features, (1, 1), name="conv3")(y))
        if residual.shape != y.shape:
            residual = bn(name="bn_proj")(
                conv(4 * self.features, (1, 1),
                     strides=(self.strides, self.strides),
                     name="conv_proj")(residual))
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)   # ResNet-50
    num_classes: int = 1000
    compute_dtype: Any = jnp.bfloat16
    bn_axis_name: Optional[str] = "data"

    @nn.compact
    def __call__(self, x: jnp.ndarray, *, train: bool = False) -> jnp.ndarray:
        x = x.astype(self.compute_dtype)
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.compute_dtype,
                    param_dtype=jnp.float32, name="conv_init")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.compute_dtype,
                         param_dtype=jnp.float32,
                         axis_name=self.bn_axis_name if train else None,
                         name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, num_blocks in enumerate(self.stage_sizes):
            for block in range(num_blocks):
                x = BottleneckBlock(
                    features=64 * 2 ** stage,
                    strides=2 if stage > 0 and block == 0 else 1,
                    compute_dtype=self.compute_dtype,
                    bn_axis_name=self.bn_axis_name,
                    name=f"stage{stage + 1}_block{block + 1}")(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def ResNet50(**kwargs) -> ResNet:
    kwargs.setdefault("stage_sizes", (3, 4, 6, 3))
    return ResNet(**kwargs)
