"""Shared build-and-cache helper for the in-tree native (C++) libraries.

Both ctypes bindings (data/native_loader.py, data/native_jpeg.py) compile
their .so on demand with g++ and cache it next to the source. The mechanics
live here once: compile to a pid-unique temp path then atomically
`os.replace` into place (a concurrent process can never dlopen a half-written
.so — multi-process launches share this filesystem), with an mtime staleness
check so editing the .cc rebuilds.

Sanitizer variants (r15 correctness tooling plane): DVGGF_NATIVE_SANITIZER=
{asan,tsan} redirects every build/load in this process to an instrumented
variant of the SAME source, cached as <lib>.<variant>.so next to the
production .so (mirroring native/Makefile's `asan`/`tsan` targets). The
variant is resolved once per build call from the environment, so a child
pytest process launched with the env var + the matching LD_PRELOAD'd runtime
(`sanitizer_preload()`) runs the byte-parity/stress suites through the
instrumented decoder with zero call-site changes. `sanitizer_missing(kind)`
is the single skip-message source for those suites, mirroring
`toolchain_missing()`.
"""

from __future__ import annotations

import logging
import os
import subprocess
from typing import Sequence

log = logging.getLogger(__name__)

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

_CXX_FLAGS = ["-O3", "-march=native", "-fPIC", "-std=c++17", "-pthread",
              "-shared"]

# -O1 -fno-omit-frame-pointer: the sanitizer-friendly level — -O3 blurs
# report stacks, -O0 triples run time. Must stay in sync with
# native/Makefile's SAN_BASE/ASAN_FLAGS/TSAN_FLAGS.
_SANITIZER_FLAGS = {
    "asan": ["-O1", "-g", "-fno-omit-frame-pointer", "-march=native",
             "-fPIC", "-std=c++17", "-pthread", "-shared",
             "-fsanitize=address,undefined"],
    "tsan": ["-O1", "-g", "-fno-omit-frame-pointer", "-march=native",
             "-fPIC", "-std=c++17", "-pthread", "-shared",
             "-fsanitize=thread"],
}


def active_sanitizer() -> str | None:
    """The sanitizer variant this process builds/loads, from the
    DVGGF_NATIVE_SANITIZER env ('asan' | 'tsan'), or None for the
    production build. Unknown values fail loudly — a typo'd variant
    silently running the uninstrumented decoder would green a sanitizer
    suite that sanitized nothing."""
    kind = os.environ.get("DVGGF_NATIVE_SANITIZER", "").strip().lower()
    if not kind:
        return None
    if kind not in _SANITIZER_FLAGS:
        raise ValueError(
            f"DVGGF_NATIVE_SANITIZER={kind!r} not one of "
            f"{sorted(_SANITIZER_FLAGS)} (or unset)")
    return kind


def _variant_so_name(so_name: str, variant: str | None) -> str:
    if not variant:
        return so_name
    stem, ext = os.path.splitext(so_name)
    return f"{stem}.{variant}{ext}"


def sanitizer_runtime(kind: str) -> str | None:
    """Absolute path of the sanitizer runtime to LD_PRELOAD into an
    uninstrumented interpreter before dlopen'ing an instrumented .so
    (ASan insists on being first in the link order; preload is the only
    way to honor that from python), or None when g++ has no such runtime."""
    lib = {"asan": "libasan.so", "tsan": "libtsan.so"}[kind]
    try:
        out = subprocess.run(["g++", "-print-file-name=" + lib],
                             capture_output=True, text=True, timeout=60)
    except Exception:
        return None
    path = out.stdout.strip()
    # -print-file-name echoes the bare name back when it resolves nothing
    if out.returncode != 0 or not os.path.isabs(path) \
            or not os.path.exists(path):
        return None
    return path


def sanitizer_preload(kind: str) -> str | None:
    """The LD_PRELOAD value for running python against an instrumented
    .so: the sanitizer runtime FIRST (ASan refuses otherwise), then
    libstdc++ — without it, a third-party pybind11 extension throwing a
    C++ exception during import (matplotlib's ft2font does) trips ASan's
    `real___cxa_throw != 0` interceptor check, because the interceptor
    resolved before any C++ runtime was mapped. Caught driving the real
    decode bench under ASan in r15. None when the runtime is missing."""
    rt = sanitizer_runtime(kind)
    if rt is None:
        return None
    stdcpp = ""
    try:
        out = subprocess.run(["g++", "-print-file-name=libstdc++.so.6"],
                             capture_output=True, text=True, timeout=60)
        if out.returncode == 0:
            stdcpp = out.stdout.strip()
    except Exception:
        pass
    if os.path.isabs(stdcpp) and os.path.exists(stdcpp):
        return f"{rt} {stdcpp}"
    return rt


def sanitizer_missing(kind: str) -> str | None:
    """None when `kind` ('asan' | 'tsan') builds can be compiled, linked
    AND preloaded here, else a human-readable reason — the single
    skip-message source for the sanitizer suites (tests/test_sanitizers.py),
    mirroring `toolchain_missing()` so 'no sanitizer runtime' skips stay
    visible and specific instead of silent."""
    base = toolchain_missing()
    if base is not None:
        return base
    flags = {"asan": "-fsanitize=address,undefined",
             "tsan": "-fsanitize=thread"}[kind]
    try:
        probe = subprocess.run(
            ["g++", "-x", "c++", "-", flags, "-shared", "-o", os.devnull],
            input=b"int dvgg_probe() { return 0; }\n",
            capture_output=True, timeout=120)
    except Exception as e:
        return f"g++ {kind} probe failed ({e})"
    if probe.returncode != 0:
        return f"g++ cannot link {flags} (lib{kind} runtime missing)"
    if sanitizer_runtime(kind) is None:
        return f"lib{kind}.so not resolvable for LD_PRELOAD"
    return None


def toolchain_missing() -> str | None:
    """None when native sources can be compiled here, else a human-readable
    reason — the single skip-message source for the tests that exercise the
    build itself (tests/test_native_build_smoke.py, the decode parity suite),
    so 'no toolchain' skips stay visible and specific instead of silent.
    The header check asks the COMPILER (a one-shot preprocessor probe), not
    a hardcoded path list — conda/homebrew/CPATH installs must count."""
    import shutil
    if shutil.which("g++") is None:
        return "g++ not on PATH"
    try:
        probe = subprocess.run(
            ["g++", "-E", "-x", "c++", "-"],
            input=b"#include <cstdio>\n#include <jpeglib.h>\n",
            capture_output=True, timeout=60)
    except Exception as e:
        return f"g++ probe failed ({e})"
    if probe.returncode != 0:
        return "jpeglib.h not found (libjpeg dev headers missing)"
    return None


def build_native_lib(src_name: str, so_name: str,
                     extra_link_args: Sequence[str] = (),
                     force: bool = False) -> str | None:
    """Ensure native/<so_name> exists and is newer than native/<src_name>.
    Returns the .so path, or None if the source is missing or the build
    fails (callers fall back to their non-native path). `force` rebuilds
    unconditionally — used when the loaded library's ABI version doesn't
    match (mtime ties from tar/rsync/cp -p can defeat the staleness check).

    Under DVGGF_NATIVE_SANITIZER={asan,tsan} the build redirects to the
    instrumented <lib>.<variant>.so — same source, same ABI, sanitizer
    flags — so sanitizer child processes reuse every call site unchanged."""
    variant = active_sanitizer()
    src = os.path.join(NATIVE_DIR, src_name)
    so_path = os.path.join(NATIVE_DIR, _variant_so_name(so_name, variant))
    if not os.path.exists(src):
        return None
    try:
        stale = (force or not os.path.exists(so_path)
                 or os.path.getmtime(src) > os.path.getmtime(so_path))
    except OSError:
        stale = True
    if not stale:
        return so_path
    tmp = f"{so_path}.build.{os.getpid()}"
    flags = _SANITIZER_FLAGS[variant] if variant else _CXX_FLAGS
    try:
        subprocess.run(["g++", *flags, "-o", tmp, src,
                        *extra_link_args],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
        return so_path
    except Exception as e:  # missing toolchain, sandboxed fs, ...
        log.warning("native build of %s failed (%s)", src_name, e)
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None


def load_abi_checked(src_name: str, so_name: str, abi_symbol: str,
                     expected_abi: int, extra_link_args: Sequence[str] = ()):
    """Build + dlopen a native library, verifying `abi_symbol`() ==
    `expected_abi`. On mismatch (stale cached .so that the mtime check
    wrongly accepted) the library is force-rebuilt once; a persistent
    mismatch returns None so callers fall back rather than call a
    wrong-signature ABI — cdecl would silently absorb extra args and corrupt
    data instead of failing."""
    import ctypes
    import shutil
    for forced in (False, True):
        so_path = build_native_lib(src_name, so_name, extra_link_args,
                                   force=forced)
        if so_path is None:
            return None
        load_path = so_path
        if forced:
            # glibc dedups dlopen by pathname: re-dlopening the canonical
            # path would return the already-mapped STALE library, so the
            # retry loads a unique copy (unlinked right after dlopen — the
            # mapping persists; the canonical rebuild serves future
            # processes).
            load_path = f"{so_path}.{os.getpid()}.reload.so"
            try:
                shutil.copy2(so_path, load_path)
            except OSError as e:
                log.warning("copying rebuilt %s failed: %s", so_name, e)
                return None
        try:
            lib = ctypes.CDLL(load_path)
        except OSError as e:
            log.warning("loading %s failed: %s", so_name, e)
            return None
        finally:
            if forced:
                try:
                    os.unlink(load_path)
                except OSError:
                    pass
        try:
            fn = getattr(lib, abi_symbol)
            fn.restype = ctypes.c_int64
            fn.argtypes = []
            if int(fn()) == expected_abi:
                return lib
            got = int(fn())
        except AttributeError:
            got = None
        if forced:
            log.warning("%s ABI %s != expected %d after rebuild — native "
                        "path disabled", so_name, got, expected_abi)
            return None
        log.warning("%s has stale ABI %s (expected %d); rebuilding",
                    so_name, got, expected_abi)
    return None
