"""Shared build-and-cache helper for the in-tree native (C++) libraries.

Both ctypes bindings (data/native_loader.py, data/native_jpeg.py) compile
their .so on demand with g++ and cache it next to the source. The mechanics
live here once: compile to a pid-unique temp path then atomically
`os.replace` into place (a concurrent process can never dlopen a half-written
.so — multi-process launches share this filesystem), with an mtime staleness
check so editing the .cc rebuilds.
"""

from __future__ import annotations

import logging
import os
import subprocess
from typing import Sequence

log = logging.getLogger(__name__)

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

_CXX_FLAGS = ["-O3", "-march=native", "-fPIC", "-std=c++17", "-pthread",
              "-shared"]


def build_native_lib(src_name: str, so_name: str,
                     extra_link_args: Sequence[str] = ()) -> str | None:
    """Ensure native/<so_name> exists and is newer than native/<src_name>.
    Returns the .so path, or None if the source is missing or the build
    fails (callers fall back to their non-native path)."""
    src = os.path.join(NATIVE_DIR, src_name)
    so_path = os.path.join(NATIVE_DIR, so_name)
    if not os.path.exists(src):
        return None
    try:
        stale = (not os.path.exists(so_path)
                 or os.path.getmtime(src) > os.path.getmtime(so_path))
    except OSError:
        stale = True
    if not stale:
        return so_path
    tmp = f"{so_path}.build.{os.getpid()}"
    try:
        subprocess.run(["g++", *_CXX_FLAGS, "-o", tmp, src,
                        *extra_link_args],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
        return so_path
    except Exception as e:  # missing toolchain, sandboxed fs, ...
        log.warning("native build of %s failed (%s)", src_name, e)
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None
