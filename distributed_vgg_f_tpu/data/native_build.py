"""Shared build-and-cache helper for the in-tree native (C++) libraries.

Both ctypes bindings (data/native_loader.py, data/native_jpeg.py) compile
their .so on demand with g++ and cache it next to the source. The mechanics
live here once: compile to a pid-unique temp path then atomically
`os.replace` into place (a concurrent process can never dlopen a half-written
.so — multi-process launches share this filesystem), with an mtime staleness
check so editing the .cc rebuilds.
"""

from __future__ import annotations

import logging
import os
import subprocess
from typing import Sequence

log = logging.getLogger(__name__)

NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

_CXX_FLAGS = ["-O3", "-march=native", "-fPIC", "-std=c++17", "-pthread",
              "-shared"]


def toolchain_missing() -> str | None:
    """None when native sources can be compiled here, else a human-readable
    reason — the single skip-message source for the tests that exercise the
    build itself (tests/test_native_build_smoke.py, the decode parity suite),
    so 'no toolchain' skips stay visible and specific instead of silent.
    The header check asks the COMPILER (a one-shot preprocessor probe), not
    a hardcoded path list — conda/homebrew/CPATH installs must count."""
    import shutil
    if shutil.which("g++") is None:
        return "g++ not on PATH"
    try:
        probe = subprocess.run(
            ["g++", "-E", "-x", "c++", "-"],
            input=b"#include <cstdio>\n#include <jpeglib.h>\n",
            capture_output=True, timeout=60)
    except Exception as e:
        return f"g++ probe failed ({e})"
    if probe.returncode != 0:
        return "jpeglib.h not found (libjpeg dev headers missing)"
    return None


def build_native_lib(src_name: str, so_name: str,
                     extra_link_args: Sequence[str] = (),
                     force: bool = False) -> str | None:
    """Ensure native/<so_name> exists and is newer than native/<src_name>.
    Returns the .so path, or None if the source is missing or the build
    fails (callers fall back to their non-native path). `force` rebuilds
    unconditionally — used when the loaded library's ABI version doesn't
    match (mtime ties from tar/rsync/cp -p can defeat the staleness check)."""
    src = os.path.join(NATIVE_DIR, src_name)
    so_path = os.path.join(NATIVE_DIR, so_name)
    if not os.path.exists(src):
        return None
    try:
        stale = (force or not os.path.exists(so_path)
                 or os.path.getmtime(src) > os.path.getmtime(so_path))
    except OSError:
        stale = True
    if not stale:
        return so_path
    tmp = f"{so_path}.build.{os.getpid()}"
    try:
        subprocess.run(["g++", *_CXX_FLAGS, "-o", tmp, src,
                        *extra_link_args],
                       check=True, capture_output=True, timeout=120)
        os.replace(tmp, so_path)
        return so_path
    except Exception as e:  # missing toolchain, sandboxed fs, ...
        log.warning("native build of %s failed (%s)", src_name, e)
        try:
            if os.path.exists(tmp):
                os.remove(tmp)
        except OSError:
            pass
        return None


def load_abi_checked(src_name: str, so_name: str, abi_symbol: str,
                     expected_abi: int, extra_link_args: Sequence[str] = ()):
    """Build + dlopen a native library, verifying `abi_symbol`() ==
    `expected_abi`. On mismatch (stale cached .so that the mtime check
    wrongly accepted) the library is force-rebuilt once; a persistent
    mismatch returns None so callers fall back rather than call a
    wrong-signature ABI — cdecl would silently absorb extra args and corrupt
    data instead of failing."""
    import ctypes
    import shutil
    for forced in (False, True):
        so_path = build_native_lib(src_name, so_name, extra_link_args,
                                   force=forced)
        if so_path is None:
            return None
        load_path = so_path
        if forced:
            # glibc dedups dlopen by pathname: re-dlopening the canonical
            # path would return the already-mapped STALE library, so the
            # retry loads a unique copy (unlinked right after dlopen — the
            # mapping persists; the canonical rebuild serves future
            # processes).
            load_path = f"{so_path}.{os.getpid()}.reload.so"
            try:
                shutil.copy2(so_path, load_path)
            except OSError as e:
                log.warning("copying rebuilt %s failed: %s", so_name, e)
                return None
        try:
            lib = ctypes.CDLL(load_path)
        except OSError as e:
            log.warning("loading %s failed: %s", so_name, e)
            return None
        finally:
            if forced:
                try:
                    os.unlink(load_path)
                except OSError:
                    pass
        try:
            fn = getattr(lib, abi_symbol)
            fn.restype = ctypes.c_int64
            fn.argtypes = []
            if int(fn()) == expected_abi:
                return lib
            got = int(fn())
        except AttributeError:
            got = None
        if forced:
            log.warning("%s ABI %s != expected %d after rebuild — native "
                        "path disabled", so_name, got, expected_abi)
            return None
        log.warning("%s has stale ABI %s (expected %d); rebuilding",
                    so_name, got, expected_abi)
    return None
