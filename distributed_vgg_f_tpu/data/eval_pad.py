"""Exact-eval support: finite, re-iterable eval streams with pad-and-mask.

The reference scores the held-out split exactly once per eval pass (SURVEY.md
§3.4). Under SPMD that needs static batch shapes and identical step counts on
every host, so the classic trick is `.repeat()` — which re-scores a few tail
examples. This module replaces that trade-off with the exact scheme:

- each host's eval stream is FINITE and pads only the final partial batch with
  zero rows carried alongside a per-example `valid` mask;
- the eval step counts only `valid` rows (ops/metrics.topk_correct masking) and
  psums a valid-count, so the reported top-1/top-5 is over exactly the
  `num_eval_examples` split;
- hosts whose shard exhausts early keep feeding all-invalid `padding_batch()`es
  while any other host still has data (Trainer.evaluate drives this), so uneven
  host shards can never strand the cross-replica collective.
"""

from __future__ import annotations

from typing import Callable, Iterator, Mapping

import numpy as np

Batch = Mapping[str, np.ndarray]


class FiniteEvalIterable:
    """Re-iterable finite eval stream of {'image', 'label', 'valid'} batches.

    `epoch_factory` yields {'image', 'label'} batches whose final batch may be
    partial (ragged); every yielded batch here has exactly `local_batch` rows,
    the tail zero-padded with `valid=False`. Re-iterable: each `iter()` starts a
    fresh pass, so the trainer can evaluate repeatedly during one fit().
    """

    is_finite = True

    def __init__(self, epoch_factory: Callable[[], Iterator[Batch]],
                 local_batch: int, image_shape: tuple, image_dtype) -> None:
        self._factory = epoch_factory
        self.local_batch = int(local_batch)
        self._image_shape = tuple(image_shape)   # (H, W, C)
        self._image_dtype = np.dtype(image_dtype)

    def __iter__(self) -> Iterator[Batch]:
        def gen():
            for batch in self._factory():
                yield self._pad(batch)
        return gen()

    def _pad(self, batch: Batch) -> Batch:
        n = len(batch["label"])
        b = self.local_batch
        if n > b:
            raise ValueError(f"eval batch of {n} rows exceeds local_batch {b}")
        valid = np.zeros((b,), np.bool_)
        valid[:n] = True
        if n == b:
            return {**batch, "valid": valid}
        out = {k: np.concatenate(
            [v, np.zeros((b - n,) + v.shape[1:], v.dtype)])
            for k, v in batch.items()}
        out["valid"] = valid
        return out

    def padding_batch(self) -> Batch:
        """An all-invalid batch, fed by hosts that exhausted their shard while
        other hosts still have data — keeps every host's eval-step count equal
        so the psum collective always completes."""
        return {
            "image": np.zeros((self.local_batch,) + self._image_shape,
                              self._image_dtype),
            "label": np.zeros((self.local_batch,), np.int32),
            "valid": np.zeros((self.local_batch,), np.bool_),
        }
