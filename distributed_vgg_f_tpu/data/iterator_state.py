"""Position-exact resumable ingest (r18) — checkpointable iterator state,
zero-replay restart, and the live rebuild that unbinds the autotuner's last
knob.

The tf.data paper's iterator checkpointing (arXiv 2101.12127) is the
precedent, and this stack earned it cheaply: the native train stream is a
pure function of (seed, position) — item g's dataset index rides the
SplitMix64 epoch shuffle and its crop/flip RNG is `mix(seed, 0xA0A0+g)`
(the python mirrors in data/snapshot_cache.py, pinned byte-identical
against native output; the disaggregated-ingest worker already reconstructs
ANY batch from the cursor alone). So the FULL iterator state serializes to
a ~hundred-byte JSON blob:

    {"kind": "ingest_iterator_state", "version": 1,
     "cursor": <next batch the TRAINER will consume>,
     "epoch": cursor // batches_per_epoch,
     "shuffle": {"algo": "splitmix64", "seed": S, "epoch": E},
     "source_cursor": <next batch the SOURCE will decode>,
     "in_flight": [cursor .. source_cursor),   # the read-ahead set
     ...stream identity (seed, batch, wire, ingest label)}

Cursor semantics — THE shared contract (ISSUE 15 satellite): a cursor is
always the NEXT-ITEM-TO-EMIT, never the last-emitted. `epoch_of` below is
the single implementation of the epoch-boundary off-by-one (the batch AT
cursor k*N belongs to epoch k, not k-1); the service plane's
`shard_owner` (data/ingest_service.py) and the blob both route through it,
pinned against each other by tests/test_iterator_state.py.

Three pieces:

- **`ResumableIngest`** wraps the trainer's host-batch source (native
  loader, snapshot-cache warm iterator, tf.data/grain snapshot iterators,
  the service client — anything `build_dataset` returns) and counts the
  SOURCE cursor. The read-ahead stages above it (HostPrefetchIterator,
  DevicePrefetchIterator) hold `source_cursor - cursor` already-drawn
  batches; the blob records that set so a restore can account for it.
- **`capture_state` / `restore_from_blob`**: the blob rides every
  checkpoint's `extra` (next to the r14 opt-layout receipt); restore
  validates it (schema + stream identity) and performs the read-ahead
  transplant — the rebuilt source is seeked to `cursor`, so the prefetch
  refill re-issues EXACTLY the in-flight items and the trainer replays
  zero batches (`ingest_state/transplanted_items` is the receipt).
  Receipt-absent (pre-r18) checkpoints dispatch to the unchanged r17
  replay path.
- **`rebuild_live`**: tear down the inner source and reconstruct it at the
  captured cursor under a CHANGED wire/decode config — the position-exact
  rebuild the r11 autotuner's wire knob was receipted as waiting for. The
  trainer now binds that knob through `wire_knob()` (retiring the r11
  "trainer deliberately leaves it unbound" carve-out): escalation rebuilds
  host_f32→u8 mid-epoch and the stream continues byte-identically
  (same cursors, same labels, u8 pixel parity per the r8 wire gates).
  Batches already in the read-ahead queues keep their old wire format —
  legal by construction, because the device-finish prologue dispatches
  per batch on dtype.

Elastic note (r19, parallel/elastic.py): the fresh-ingest
`restore_from_blob` path doubles as the live-resize data handoff — the
trainer captures the blob at the preemption barrier, builds a FRESH ingest
over the survivor topology, and restores it at the exact cursor, so a mesh
shrink reassigns data ownership by ROUTING ALONE (zero replayed batches,
no data movement). `restore_state` refusing an already-started ingest is
what forces that fresh-surface discipline.

Multi-host note: the blob in the (single, process-0-written) checkpoint
`extra` is process 0's capture. That is sufficient: every host consumes in
lockstep, so `cursor` is identical on all hosts, and each host restores
its OWN shard's stream to that cursor; only the `in_flight` receipt is
per-host color.

Kill-switch (`data.iterator_state.enabled=false`, r6–r16 discipline): the
wrapper is structurally absent, no blob is captured, restore takes the r17
path — byte-identical to pre-r18 behavior, pinned in
tests/test_iterator_state.py.

Counters (`ingest_state/` namespace, README table): `saves` (blobs written
into durable checkpoints), `restores` (blob-dispatched resumes),
`transplanted_items` (in-flight read-ahead batches re-issued at restore),
`rebuilds` (live position-exact reconstructions, wire switches included).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Callable, Dict, List, Optional

from distributed_vgg_f_tpu import telemetry

log = logging.getLogger(__name__)

#: Blob format version; bump on any field rename/retype. The checkpoint
#: dispatch treats an unknown version exactly like an absent receipt
#: (epoch-boundary replay), never a guess.
ITERATOR_STATE_VERSION = 1

#: `kind` tag of the checkpoint-extra blob.
BLOB_KIND = "ingest_iterator_state"

#: Identity fields a restore validates against the live run before
#: trusting a blob's cursor — a blob from a different stream must fall
#: back to replay, never seek a wrong position silently.
IDENTITY_FIELDS = ("seed", "batches_per_epoch", "ingest")


def epoch_of(cursor: int, batches_per_epoch: int) -> int:
    """THE cursor→epoch map (next-item-to-emit semantics): the batch AT
    cursor k*N is the first batch OF epoch k — a cursor is never read as
    "last emitted". Single implementation shared by the iterator-state
    blob, the service plane's `shard_owner` ownership split
    (data/ingest_service.py), and the client's blob restore — the
    epoch-boundary off-by-one is pinned across all three by
    tests/test_iterator_state.py."""
    return int(cursor) // max(1, int(batches_per_epoch))


def _register_counters() -> None:
    reg = telemetry.get_registry()
    reg.counter("ingest_state/saves")
    reg.counter("ingest_state/restores")
    reg.counter("ingest_state/transplanted_items")
    reg.counter("ingest_state/rebuilds")


def _wire_of(inner) -> str:
    """The wire the inner source actually ships, as a blob receipt:
    'u8' for raw-uint8 batches, else the host-normalize dtype."""
    dtype = getattr(inner, "image_dtype", None)
    if dtype == "uint8":
        return "u8"
    if dtype == "bfloat16":
        return "host_bf16"
    return "host_f32"


class ResumableIngest:
    """Cursor-counting rebuild surface over the trainer's host-batch
    source. Sits BETWEEN `build_dataset` and the prefetch stages: the
    read-ahead queues above it keep their contents across a live rebuild
    (mixed-wire in-flight batches are legal — the device finish dispatches
    on dtype), and across a process death the blob's cursor seeks the
    fresh source so the refill re-issues exactly the in-flight set.

    Thread safety: the host-prefetch worker calls `__next__` concurrently
    with the trainer thread's `capture_state` / `rebuild_live` (autotuner
    actuations) — one lock covers the inner swap, so a draw lands entirely
    on the old or entirely on the new source, never astride.
    """

    supports_state = True

    def __init__(self, factory: Callable[[object], object], data_cfg, *,
                 seed: int, batches_per_epoch: int, label: str = "local",
                 start_cursor: int = 0):
        self._factory = factory
        self._cfg = data_cfg
        self._seed = int(seed)
        self._batches_per_epoch = max(1, int(batches_per_epoch))
        self._label = str(label)
        self._lock = threading.RLock()
        self._cursor = int(start_cursor)   # next SOURCE draw
        self._started = False
        self._closed = False
        self._rebuilds = 0
        self._decode_errors_closed = 0
        _register_counters()
        self._inner = factory(data_cfg)
        self._wire = _wire_of(self._inner)

    # ------------------------------------------------------------ iterator
    def __iter__(self) -> "ResumableIngest":
        return self

    def __next__(self):
        with self._lock:
            if self._closed:
                raise StopIteration
            self._started = True
            batch = next(self._inner)
            self._cursor += 1
            return batch

    @property
    def reuses_output_buffers(self) -> bool:
        return bool(getattr(self._inner, "reuses_output_buffers", False))

    @property
    def cursor(self) -> int:
        """Next batch the SOURCE will draw (>= the trainer's next step by
        however much the read-ahead stages have buffered)."""
        with self._lock:
            return self._cursor

    @property
    def rebuilds(self) -> int:
        return self._rebuilds

    @property
    def wire(self) -> str:
        return self._wire

    # ------------------------------------------------------------- resume
    def restore_state(self, step: int) -> bool:
        """Pre-start position-exact seek (the shared iterator contract:
        cursor = next-item-to-emit). False when the inner source cannot
        seek — the caller falls back to replay, exactly the r17 path."""
        with self._lock:
            if self._started:
                return False
            fn = getattr(self._inner, "restore_state", None)
            if not (getattr(self._inner, "supports_state", False)
                    and callable(fn) and fn(int(step))):
                return False
            self._cursor = int(step)
            return True

    def capture_state(self, next_step: int) -> Dict[str, object]:
        """The checkpoint-extra blob, captured at the step barrier:
        `next_step` is the batch the TRAINER will consume next (== the
        restored run's start step), the source cursor is wherever the
        read-ahead has pulled the inner stream, and everything between is
        the in-flight set the restore transplant re-issues. Cheap (no
        inner access — a post-teardown final save still captures)."""
        with self._lock:
            cursor = int(next_step)
            source_cursor = max(self._cursor, cursor)
            in_flight = list(range(cursor, source_cursor))
            epoch = epoch_of(cursor, self._batches_per_epoch)
            return {
                "kind": BLOB_KIND,
                "version": ITERATOR_STATE_VERSION,
                "cursor": cursor,
                "epoch": epoch,
                "batches_per_epoch": self._batches_per_epoch,
                "seed": self._seed,
                "shuffle": {"algo": "splitmix64", "seed": self._seed,
                            "epoch": epoch},
                "source_cursor": source_cursor,
                "in_flight": in_flight,
                "wire": self._wire,
                "ingest": self._label,
                "rebuilds": self._rebuilds,
            }

    def window_receipt(self, next_step: int) -> Dict[str, object]:
        """The per-window `iterator_state` JSONL block (schema-validated,
        telemetry/schema.py validate_iterator_state_block)."""
        with self._lock:
            source_cursor = max(self._cursor, int(next_step))
            return {
                "cursor": int(next_step),
                "source_cursor": source_cursor,
                "in_flight": source_cursor - int(next_step),
                "epoch": epoch_of(int(next_step), self._batches_per_epoch),
                "rebuilds": self._rebuilds,
                "wire": self._wire,
            }

    # ------------------------------------------------------ live rebuild
    def wire_rebuild_available(self) -> bool:
        """Whether a position-exact WIRE rebuild can succeed here: the
        imagenet native path with the u8 wire accepted (or already
        shipping). The service client's stream identity is handshook with
        the worker fleet and a local wire flip would break it; synthetic /
        cifar10 / teacher have no u8 wire at all."""
        cfg = self._cfg
        if getattr(cfg, "name", "") != "imagenet":
            return False
        svc = getattr(cfg, "service", None)
        if svc is not None and svc.enabled:
            return False
        if getattr(cfg, "backend", "auto") == "tfdata":
            return False
        if self._wire == "u8":
            return True
        from distributed_vgg_f_tpu.data.native_jpeg import wire_u8_enabled
        return bool(wire_u8_enabled())

    def wire_value(self) -> int:
        """The autotuner wire knob's `get` surface: 1 = the u8 wire is
        live, 0 = a host-normalize wire."""
        return 1 if self._wire == "u8" else 0

    def apply_wire(self, target: int) -> Optional[int]:
        """The autotuner wire knob's `apply` surface — the hook the r11
        receipt said the trainer could not bind without a position-exact
        rebuild. Rebuilds the inner source on the target wire AT the
        current cursor; returns the now-active wire value, or None when
        the rebuild is unavailable/refused (knob reads unavailable, never
        a silent no-op)."""
        target = 1 if target else 0
        with self._lock:
            if target == self.wire_value():
                return target
            if not self.wire_rebuild_available():
                return None
            host_wire = ("host_bf16"
                         if getattr(self._cfg, "image_dtype", "float32")
                         == "bfloat16" else "host_f32")
            receipt = self.rebuild_live(
                wire="u8" if target else host_wire)
            if receipt is None:
                return None
            # the builder may itself have fallen back (u8 refused at
            # create): report the ACTUAL wire so a failed escalation
            # reads as railed, never as switched
            return self.wire_value() if self.wire_value() == target \
                else None

    def rebuild_live(self, *, wire: Optional[str] = None) \
            -> Optional[Dict[str, object]]:
        """Tear down and reconstruct the inner source at the captured
        cursor, optionally on a different wire. The stream continues
        position-exactly: the fresh source is seeked to the source cursor
        (next undrawn batch), so nothing is replayed and nothing is
        skipped — byte-identical continuation on the same wire, label-
        identical + r8-pixel-parity continuation across a wire switch.
        Carries the decode thread knob's current value over. Returns the
        rebuild receipt, or None when the rebuild failed and the previous
        source was restored (a second failure propagates — a dead feed
        path must be loud)."""
        with self._lock:
            if self._closed:
                return None
            old_cfg, old_wire = self._cfg, self._wire
            new_cfg = (dataclasses.replace(self._cfg, wire=wire)
                       if wire is not None else self._cfg)
            threads = self.num_threads()
            cursor = self._cursor
            self._latch_and_close_inner()
            try:
                self._inner = self._open_at(new_cfg, cursor)
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                log.warning(
                    "iterator_state: live rebuild onto wire=%s failed "
                    "(%s) — restoring the previous pipeline", wire, e)
                # second failure propagates: no feed path left to save
                self._inner = self._open_at(old_cfg, cursor)
                self._cfg, self._wire = old_cfg, old_wire
                if threads is not None:
                    self.set_num_threads(threads)
                return None
            self._cfg = new_cfg
            self._wire = _wire_of(self._inner)
            if threads is not None:
                self.set_num_threads(threads)
            self._rebuilds += 1
            telemetry.inc("ingest_state/rebuilds")
            receipt = {"cursor": cursor, "from_wire": old_wire,
                       "to_wire": self._wire, "rebuilds": self._rebuilds}
            log.info("iterator_state: live rebuild at cursor %d "
                     "(%s -> %s)", cursor, old_wire, self._wire)
            return receipt

    def _open_at(self, data_cfg, cursor: int):
        """factory + position-exact seek; replay fallback for sources
        without seek (synthetic et al. — cheap draws by contract)."""
        inner = self._factory(data_cfg)
        if cursor:
            fn = getattr(inner, "restore_state", None)
            if getattr(inner, "supports_state", False) and callable(fn) \
                    and fn(int(cursor)):
                return inner
            for _ in range(int(cursor)):
                next(inner)
        return inner

    def _latch_and_close_inner(self) -> None:
        fn = getattr(self._inner, "decode_errors", None)
        if callable(fn):
            try:
                self._decode_errors_closed += int(fn())
            except Exception:  # noqa: BLE001 — receipts never block teardown
                pass
        close = getattr(self._inner, "close", None)
        if callable(close):
            close()

    def wire_knob(self):
        """The trainer-side wire knob (r18 — retiring the r11 'trainer
        deliberately leaves it unbound' receipt): bound only when a
        position-exact rebuild is actually available here, else None and
        the controller simply has no such knob."""
        if not self.wire_rebuild_available():
            return None
        from distributed_vgg_f_tpu.data.autotune import wire_knob
        return wire_knob(self.wire_value, self.apply_wire)

    # -------------------------------------------------------- forwarding
    def num_threads(self) -> Optional[int]:
        fn = getattr(self._inner, "num_threads", None)
        return fn() if callable(fn) else None

    def set_num_threads(self, n: int) -> Optional[int]:
        fn = getattr(self._inner, "set_num_threads", None)
        return fn(int(n)) if callable(fn) else None

    def decode_errors(self) -> int:
        fn = getattr(self._inner, "decode_errors", None)
        live = int(fn()) if callable(fn) else 0
        return self._decode_errors_closed + live

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._latch_and_close_inner()

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass


# --------------------------------------------------------------- dispatch

def restore_from_blob(ingest, blob, *, step: int,
                      expect: Optional[Dict[str, object]] = None) \
        -> Optional[Dict[str, object]]:
    """Blob-dispatched resume: validate the receipt (schema + stream
    identity + cursor agreement with the checkpoint's step), seek the
    ingest to the cursor, and return the restore receipt — or None when
    the blob cannot be trusted / the seek is refused, in which case the
    caller falls back to the unchanged r17 replay path (exactly how a
    receipt-absent pre-r18 checkpoint restores).

    The read-ahead transplant: the blob's `in_flight` set names the
    batches the dead run's prefetch stages held; seeking the fresh source
    to `cursor` makes the refill re-issue exactly those items (the stream
    is a pure function of position), so the resumed trainer replays ZERO
    batches. `ingest_state/transplanted_items` receipts the set size."""
    from distributed_vgg_f_tpu.telemetry import schema
    errors: List[str] = []
    schema.validate_iterator_state_blob(blob, "checkpoint.extra", errors)
    if errors:
        log.warning("iterator_state: checkpoint blob failed validation "
                    "(%s) — falling back to replay resume", errors[:3])
        return None
    if int(blob.get("version", -1)) != ITERATOR_STATE_VERSION:
        log.warning(
            "iterator_state: blob version %s unknown (have %d) — treating "
            "as receipt-absent", blob.get("version"),
            ITERATOR_STATE_VERSION)
        return None
    if int(blob["cursor"]) != int(step):
        # blob and checkpoint step drifted apart — a wrong-position seek
        # is worse than a replay
        log.warning(
            "iterator_state: blob cursor %s != checkpoint step %d — "
            "falling back to replay resume", blob["cursor"], step)
        return None
    for field in IDENTITY_FIELDS:
        if expect and field in expect and field in blob \
                and blob[field] != expect[field]:
            log.warning(
                "iterator_state: blob %s=%r but this run expects %r — "
                "different stream, falling back to replay resume",
                field, blob[field], expect[field])
            return None
    if not (getattr(ingest, "supports_state", False)
            and ingest.restore_state(int(blob["cursor"]))):
        return None
    transplanted = len(blob.get("in_flight") or [])
    telemetry.inc("ingest_state/restores")
    telemetry.inc("ingest_state/transplanted_items", transplanted)
    return {"cursor": int(blob["cursor"]),
            "epoch": int(blob["epoch"]),
            "transplanted_items": transplanted,
            "replayed_batches": 0,
            "wire": blob.get("wire")}
