"""ImageNet-1k input pipeline — the reference's JPEG decode/crop/flip path
(BASELINE.json north_star: "ImageNet JPEG decode/crop/flip pipeline moves to
tf.data on the TPU VM host feeding device infeed"; SURVEY.md §2.1 #5).

Two on-disk layouts are supported, auto-detected from `data_dir`:

1. TFRecords in the standard `train-*-of-*` / `validation-*-of-*` layout
   (each record: encoded JPEG + integer label) — sharded per host by file.
2. Raw JPEG directory-per-class (`train/<wnid>/*.JPEG`) — sharded per host by
   a strided split of the (deterministically shuffled) file list; labels are
   the sorted class-directory index.

Both feed the same preprocessing:

  train: decode(+crop window straight from JPEG bytes) → random-resized-crop
         to `image_size` → random h-flip → mean/std normalize; shuffle, batch
  eval:  decode → resize short side 256 → center crop → normalize; repeated so
         uneven host shards cannot strand the eval collective

TensorFlow is imported lazily so the rest of the framework has no TF dependency.
"""

from __future__ import annotations

import os
from typing import Iterator

from distributed_vgg_f_tpu.config import DataConfig

IMAGE_FEATURES = {
    "image/encoded": "jpeg bytes",
    "image/class/label": "int64 label (1-based in classic ImageNet TFRecords)",
}


def _preprocess_fns(tf, cfg: DataConfig):
    """(train_fn, eval_fn), each (encoded_jpeg, label) -> (image, label)."""
    mean = tf.constant(cfg.mean_rgb, tf.float32)
    std = tf.constant(cfg.stddev_rgb, tf.float32)
    size = cfg.image_size

    def train_preprocess(encoded, label):
        # random-resized crop straight from JPEG bytes: decode only the crop
        # window (decode_and_crop_jpeg) — large host-CPU saving on 1-vCPU hosts
        shape = tf.io.extract_jpeg_shape(encoded)
        bbox = tf.constant([0.0, 0.0, 1.0, 1.0], shape=[1, 1, 4])
        begin, crop_size, _ = tf.image.sample_distorted_bounding_box(
            shape, bbox, area_range=(0.08, 1.0),
            aspect_ratio_range=(3 / 4, 4 / 3), max_attempts=10,
            use_image_if_no_bounding_boxes=True)
        offset_y, offset_x, _ = tf.unstack(begin)
        target_h, target_w, _ = tf.unstack(crop_size)
        img = tf.image.decode_and_crop_jpeg(
            encoded, tf.stack([offset_y, offset_x, target_h, target_w]),
            channels=3)
        img = tf.image.resize(img, (size, size), method="bilinear")
        img = tf.image.random_flip_left_right(img)
        img = (tf.cast(img, tf.float32) - mean) / std
        return img, label

    def eval_preprocess(encoded, label):
        img = tf.io.decode_jpeg(encoded, channels=3)
        shape = tf.shape(img)
        h, w = shape[0], shape[1]
        scale = 256.0 / tf.cast(tf.minimum(h, w), tf.float32)
        nh = tf.cast(tf.round(tf.cast(h, tf.float32) * scale), tf.int32)
        nw = tf.cast(tf.round(tf.cast(w, tf.float32) * scale), tf.int32)
        img = tf.image.resize(img, (nh, nw), method="bilinear")
        top = (nh - size) // 2
        left = (nw - size) // 2
        img = tf.image.crop_to_bounding_box(img, top, left, size, size)
        img = (tf.cast(img, tf.float32) - mean) / std
        return img, label

    return train_preprocess, eval_preprocess


def _finalize(tf, ds, cfg: DataConfig, is_train: bool, local_batch: int,
              seed: int) -> Iterator:
    """Shared pipeline tail: preprocess → repeat policy → batch → dtype →
    prefetch → numpy-dict iterator."""
    train_fn, eval_fn = _preprocess_fns(tf, cfg)
    if is_train:
        ds = ds.shuffle(cfg.shuffle_buffer, seed=seed + 1)
        ds = ds.map(train_fn, num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.repeat()
    else:
        ds = ds.map(eval_fn, num_parallel_calls=tf.data.AUTOTUNE)
        # Repeat so every host can always draw the number of eval batches the
        # trainer asks for: with per-host sharding a host can hold a few
        # examples fewer than num_eval_examples/num_hosts, and a host running
        # out would strand the others inside the eval collective. The tail of
        # the final pass may therefore re-score a few early examples — the
        # standard padding trade-off.
        ds = ds.repeat()
    ds = ds.batch(local_batch, drop_remainder=True)
    if cfg.image_dtype != "float32":
        out_dtype = tf.dtypes.as_dtype(cfg.image_dtype)
        ds = ds.map(lambda img, label: (tf.cast(img, out_dtype), label),
                    num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.prefetch(cfg.prefetch)

    def to_numpy():
        for img, label in ds.as_numpy_iterator():
            yield {"image": img, "label": label}

    return iter(to_numpy())


def build_imagenet(cfg: DataConfig, split: str, local_batch: int, *,
                   seed: int = 0, num_shards: int = 1, shard_index: int = 0,
                   label_offset: int | None = None) -> Iterator:
    import tensorflow as tf

    tf.config.set_visible_devices([], "GPU")
    tf.config.set_visible_devices([], "TPU")

    is_train = split == "train"
    pattern = os.path.join(
        cfg.data_dir, "train-*" if is_train else "validation-*")
    files = tf.io.gfile.glob(pattern)
    if not files:
        # Fall back to the raw-JPEG directory-per-class layout
        # (train/<wnid>/*.JPEG), the other common ImageNet distribution.
        return _build_imagenet_imagefolder(
            tf, cfg, split, local_batch, seed=seed, num_shards=num_shards,
            shard_index=shard_index)
    files.sort()
    if label_offset is None:
        # classic ImageNet TFRecords store labels 1..1000
        label_offset = 1

    def parse(serialized):
        feats = tf.io.parse_single_example(serialized, {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        })
        label = tf.cast(feats["image/class/label"], tf.int32) - label_offset
        return feats["image/encoded"], label

    ds = tf.data.Dataset.from_tensor_slices(files)
    if num_shards > 1:
        ds = ds.shard(num_shards, shard_index)
    if is_train:
        ds = ds.shuffle(len(files), seed=seed)
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=min(16, max(1, len(files))),
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=not is_train)
    ds = ds.map(parse, num_parallel_calls=tf.data.AUTOTUNE)
    return _finalize(tf, ds, cfg, is_train, local_batch, seed)


def _build_imagenet_imagefolder(tf, cfg: DataConfig, split: str,
                                local_batch: int, *, seed: int,
                                num_shards: int, shard_index: int) -> Iterator:
    import numpy as np

    is_train = split == "train"
    split_dir = os.path.join(cfg.data_dir,
                             "train" if is_train else "validation")
    if not os.path.isdir(split_dir):
        split_dir_alt = os.path.join(cfg.data_dir,
                                     "train" if is_train else "val")
        if os.path.isdir(split_dir_alt):
            split_dir = split_dir_alt
        else:
            raise FileNotFoundError(
                f"no ImageNet data under {cfg.data_dir!r}: neither "
                "TFRecords (train-*-of-*) nor directory-per-class "
                f"({split_dir!r}) found")
    classes = sorted(d for d in os.listdir(split_dir)
                     if os.path.isdir(os.path.join(split_dir, d)))
    if not classes:
        raise FileNotFoundError(f"no class directories under {split_dir!r}")
    files, labels = [], []
    for idx, cls in enumerate(classes):
        for fname in sorted(os.listdir(os.path.join(split_dir, cls))):
            files.append(os.path.join(split_dir, cls, fname))
            labels.append(idx)
    # deterministic global shuffle, then strided per-host split so every host
    # sees a class-balanced 1/num_shards slice; slice the index array BEFORE
    # materializing paths so each host only holds its own shard (the global
    # padded-unicode path array would be ~0.5GB at ImageNet scale). Example
    # order within the shard is then _finalize's shuffle_buffer.
    order = np.random.default_rng(seed).permutation(len(files))
    if num_shards > 1:
        order = order[shard_index::num_shards]
    files = np.asarray([files[i] for i in order])
    labels = np.asarray(labels, np.int32)[order]

    ds = tf.data.Dataset.from_tensor_slices((files, labels))
    ds = ds.map(lambda path, label: (tf.io.read_file(path), label),
                num_parallel_calls=tf.data.AUTOTUNE)
    return _finalize(tf, ds, cfg, is_train, local_batch, seed)
