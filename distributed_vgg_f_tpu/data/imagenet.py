"""ImageNet-1k input pipeline — the reference's JPEG decode/crop/flip path
(BASELINE.json north_star: "ImageNet JPEG decode/crop/flip pipeline moves to
tf.data on the TPU VM host feeding device infeed"; SURVEY.md §2.1 #5).

Two on-disk layouts are supported, auto-detected from `data_dir`:

1. TFRecords in the standard `train-*-of-*` / `validation-*-of-*` layout
   (each record: encoded JPEG + integer label) — sharded per host by file.
2. Raw JPEG directory-per-class (`train/<wnid>/*.JPEG`) — sharded per host by
   a strided split of the (deterministically shuffled) file list; labels are
   the sorted class-directory index.

Both feed the same preprocessing:

  train: decode(+crop window straight from JPEG bytes) → random-resized-crop
         to `image_size` → random h-flip → mean/std normalize; shuffle, batch
  eval:  decode → resize short side 256 → center crop → normalize; repeated so
         uneven host shards cannot strand the eval collective

TensorFlow is imported lazily so the rest of the framework has no TF dependency.
"""

from __future__ import annotations

import os
from typing import Iterator

from distributed_vgg_f_tpu.config import DataConfig
from distributed_vgg_f_tpu.data.iter_snapshots import SnapshotResumableIterator

IMAGE_FEATURES = {
    "image/encoded": "jpeg bytes",
    "image/class/label": "int64 label (1-based in classic ImageNet TFRecords)",
}


class DataLayoutError(Exception):
    """The dataset itself is broken/misdescribed (e.g. labels below
    label_offset). Deliberately NOT a ValueError: backend fallback chains
    catch ValueError as "this backend is unavailable, try the next one", but
    a broken dataset must fail the run loudly on EVERY backend — falling
    back would silently train on corrupt labels."""


def _preprocess_fns(tf, cfg: DataConfig, seed: int = 0):
    """(train_fn, eval_fn). train_fn is (index, (encoded, label)) -> (image,
    label) with STATELESS augmentations keyed on (seed, stream index): the
    train stream is a pure function of (seed, position), which is what makes
    mid-stream iterator restore bit-identical (deterministic resume) — TF's
    stateful random ops would re-draw differently after a restart."""
    mean = tf.constant(cfg.mean_rgb, tf.float32)
    std = tf.constant(cfg.stddev_rgb, tf.float32)
    size = cfg.image_size
    # Flip ownership (r13): with the fused on-device augmentation stage
    # enabled and owning flips (data/augment.py, AugmentConfig.owns_hflip),
    # the host pipeline must never flip — exactly one side of the
    # host/device boundary holds the flag, so double-flip is structurally
    # impossible.
    host_flips = not cfg.augment.owns_hflip

    def train_preprocess(index, encoded_label):
        encoded, label = encoded_label
        aug_seed = tf.stack([tf.cast(seed, tf.int64), index])
        # random-resized crop straight from JPEG bytes: decode only the crop
        # window (decode_and_crop_jpeg) — large host-CPU saving on 1-vCPU hosts
        shape = tf.io.extract_jpeg_shape(encoded)
        bbox = tf.constant([0.0, 0.0, 1.0, 1.0], shape=[1, 1, 4])
        begin, crop_size, _ = tf.image.stateless_sample_distorted_bounding_box(
            shape, bbox, seed=aug_seed, area_range=(0.08, 1.0),
            aspect_ratio_range=(3 / 4, 4 / 3), max_attempts=10,
            use_image_if_no_bounding_boxes=True)
        offset_y, offset_x, _ = tf.unstack(begin)
        target_h, target_w, _ = tf.unstack(crop_size)
        img = tf.image.decode_and_crop_jpeg(
            encoded, tf.stack([offset_y, offset_x, target_h, target_w]),
            channels=3)
        img = tf.image.resize(img, (size, size), method="bilinear")
        if host_flips:
            img = tf.image.stateless_random_flip_left_right(
                img, seed=aug_seed + 1)
        img = (tf.cast(img, tf.float32) - mean) / std
        return img, label

    def eval_preprocess(encoded, label):
        img = tf.io.decode_jpeg(encoded, channels=3)
        shape = tf.shape(img)
        h, w = shape[0], shape[1]
        scale = 256.0 / tf.cast(tf.minimum(h, w), tf.float32)
        nh = tf.cast(tf.round(tf.cast(h, tf.float32) * scale), tf.int32)
        nw = tf.cast(tf.round(tf.cast(w, tf.float32) * scale), tf.int32)
        img = tf.image.resize(img, (nh, nw), method="bilinear")
        top = (nh - size) // 2
        left = (nw - size) // 2
        img = tf.image.crop_to_bounding_box(img, top, left, size, size)
        img = (tf.cast(img, tf.float32) - mean) / std
        return img, label

    return train_preprocess, eval_preprocess


class CheckpointableTfIterator(SnapshotResumableIterator):
    """Infinite train iterator over a tf.data pipeline with O(1) mid-stream
    restore (SURVEY.md §5: data-iterator state in the checkpoint).

    SYMBOLIC tf.data checkpoints (seeds + offsets, not buffer contents) are
    written to a rotating set of files under `snapshot_dir`; the snapshot
    cadence/rotation/restore protocol lives in data/iter_snapshots.py,
    shared with the grain backend. `restore_state(D)` replaces the
    O(decoded images) replay that deterministic ImageNet resume previously
    required.
    """

    def __init__(self, tf, ds, *, snapshot_dir: str = "",
                 snapshot_every: int = 0, keep: int = 4):
        super().__init__(snapshot_dir=snapshot_dir,
                         snapshot_every=snapshot_every, keep=keep)
        self._tf = tf
        self._it = iter(ds)
        self._ckpt = tf.train.Checkpoint(iterator=self._it)

    def __next__(self):
        img, label = next(self._it)
        self._after_draw()
        return {"image": img.numpy(), "label": label.numpy()}

    def _path(self, draws: int) -> str:
        return os.path.join(self._dir, f"iter_{draws:012d}")

    def _write_snapshot(self, draws: int) -> None:
        # Write under a tmp prefix, then rename: a SIGKILL mid-write must not
        # leave a final-named half-snapshot that a restart would trust. The
        # .index file is renamed LAST so its presence implies a complete set.
        tmp = os.path.join(self._dir, f"tmp_{draws:012d}")
        final = self._path(draws)
        self._ckpt.write(tmp)
        parts = [f for f in os.listdir(self._dir)
                 if f.startswith(f"tmp_{draws:012d}.")]
        for f in sorted(parts, key=lambda f: f.endswith(".index")):
            os.replace(os.path.join(self._dir, f),
                       final + f[len(f"tmp_{draws:012d}"):])

    def _snapshot_exists(self, draws: int) -> bool:
        return os.path.exists(self._path(draws) + ".index")

    def _read_snapshot(self, draws: int) -> None:
        self._ckpt.read(self._path(draws)).expect_partial()

    def _remove_snapshot(self, draws: int) -> None:
        for f in os.listdir(self._dir):
            if f.startswith(f"iter_{draws:012d}"):
                os.remove(os.path.join(self._dir, f))

    def _list_stamps(self) -> list[int]:
        return [int(f[len("iter_"):-len(".index")])
                for f in os.listdir(self._dir)
                if f.startswith("iter_") and f.endswith(".index")]


def _finalize(tf, ds, cfg: DataConfig, is_train: bool, local_batch: int,
              seed: int, state_dir: str = "",
              snapshot_every: int = 0) -> Iterator:
    """Shared pipeline tail: preprocess → batch → dtype → prefetch.

    Train: infinite shuffled iterator, deterministic per seed (seeded shuffle,
    stateless index-keyed augmentation), checkpointable via
    CheckpointableTfIterator. Eval: a FINITE re-iterable pass over this host's
    shard — the final partial batch is pad-and-masked (data/eval_pad.py) so
    every example is scored exactly once; hosts with uneven shards are kept in
    lockstep by Trainer.evaluate feeding all-invalid padding batches, not by
    `.repeat()` re-scoring."""
    _warn_wire_u8_unshipped(cfg, is_train, "tf.data")
    train_fn, eval_fn = _preprocess_fns(tf, cfg, seed)
    out_dtype = tf.dtypes.as_dtype(cfg.image_dtype)
    if is_train:
        ds = ds.shuffle(cfg.shuffle_buffer, seed=seed + 1)
        ds = ds.repeat()
        # enumerate AFTER repeat: the stream index keys the stateless
        # augmentations, so crops/flips differ across epochs yet are a pure
        # function of (seed, position) — bit-identical under resume.
        ds = ds.enumerate()
        ds = ds.map(train_fn, num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.batch(local_batch, drop_remainder=True)
        if cfg.host_space_to_depth:
            # tf.nn.space_to_depth's channel order (dy, dx, c) matches the
            # VGG-F stem's packed-input contract (models/vggf.py). With
            # device augmentation enabled the host never packs — the train
            # step relayouts AFTER the geometric augments
            # (DataConfig.host_space_to_depth is the single source).
            ds = ds.map(lambda img, label:
                        (tf.nn.space_to_depth(img, 4), label),
                        num_parallel_calls=tf.data.AUTOTUNE)
        if cfg.image_dtype != "float32":
            ds = ds.map(lambda img, label: (tf.cast(img, out_dtype), label),
                        num_parallel_calls=tf.data.AUTOTUNE)
        ds = ds.prefetch(cfg.prefetch)
        # Symbolic checkpoints: iterator state = seeds + offsets, not the
        # shuffle buffer's contents, so snapshot files stay tiny.
        opts = tf.data.Options()
        opts.experimental_symbolic_checkpoint = True
        ds = ds.with_options(opts)
        return CheckpointableTfIterator(tf, ds, snapshot_dir=state_dir,
                                        snapshot_every=snapshot_every)

    from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable

    ds = ds.map(eval_fn, num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.batch(local_batch, drop_remainder=False)
    if cfg.image_dtype != "float32":
        ds = ds.map(lambda img, label: (tf.cast(img, out_dtype), label),
                    num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.prefetch(cfg.prefetch)

    def epoch():
        for img, label in ds.as_numpy_iterator():
            yield {"image": img, "label": label}

    import numpy as np
    np_dtype = (np.dtype("float32") if cfg.image_dtype == "float32"
                else out_dtype.as_numpy_dtype)
    return FiniteEvalIterable(epoch, local_batch,
                              (cfg.image_size, cfg.image_size, 3), np_dtype)


def _resolve_wire(cfg: DataConfig) -> DataConfig:
    """Fold `cfg.wire` host-dtype overrides into `image_dtype` so every
    downstream path (tf.data, grain, native) ships the requested
    host-normalize dtype without knowing about wires."""
    import dataclasses

    from distributed_vgg_f_tpu.data.dtypes import resolve_wire_dtype
    dtype = resolve_wire_dtype(cfg.wire, cfg.image_dtype)
    if dtype != cfg.image_dtype:
        cfg = dataclasses.replace(cfg, image_dtype=dtype)
    return cfg


def _wire_u8_active(cfg: DataConfig, is_train: bool) -> bool:
    """True iff this pipeline should ship the uint8 wire: requested
    (data.wire='u8'), a TRAIN stream (eval keeps the host path for parity),
    and the native library actually accepts the u8 kind right now (library
    loaded, compiled in, not kill-switched). A refused request falls back
    to the host-normalize wire with a logged warning — byte-identical to
    the pre-u8 behavior, never a silent format change."""
    if cfg.wire != "u8" or not is_train:
        return False
    from distributed_vgg_f_tpu.data.native_jpeg import wire_u8_enabled
    if wire_u8_enabled():
        return True
    import logging
    logging.getLogger(__name__).warning(
        "data.wire='u8' requested but the native uint8 wire is unavailable "
        "(library missing, -DDVGGF_NO_WIRE_U8 build, or DVGGF_WIRE_U8=0) — "
        "falling back to the host-normalize %s wire", cfg.image_dtype)
    return False


def _warn_wire_u8_unshipped(cfg: DataConfig, is_train: bool,
                            backend: str) -> None:
    """The uint8 wire is a native-TRAIN-loader capability; every other
    backend ships host-normalized batches. The start record labels the run
    with the REQUESTED wire, so the fallback must be in the log — a silent
    format change would misattribute the run's throughput/H2D numbers."""
    if cfg.wire == "u8" and is_train:
        import logging
        logging.getLogger(__name__).warning(
            "data.wire='u8' requested but the %s backend ships "
            "host-normalized %s batches — only the native train loader "
            "ships the uint8 wire", backend, cfg.image_dtype)


def build_imagenet(cfg: DataConfig, split: str, local_batch: int, *,
                   seed: int = 0, num_shards: int = 1, shard_index: int = 0,
                   label_offset: int | None = None, state_dir: str = "",
                   snapshot_every: int = 0) -> Iterator:
    import tensorflow as tf

    cfg = _resolve_wire(cfg)

    tf.config.set_visible_devices([], "GPU")
    tf.config.set_visible_devices([], "TPU")

    is_train = split == "train"
    pattern = os.path.join(
        cfg.data_dir, "train-*" if is_train else "validation-*")
    files = tf.io.gfile.glob(pattern)
    if not files:
        # Fall back to the raw-JPEG directory-per-class layout
        # (train/<wnid>/*.JPEG), the other common ImageNet distribution.
        return _build_imagenet_imagefolder(
            tf, cfg, split, local_batch, seed=seed, num_shards=num_shards,
            shard_index=shard_index, state_dir=state_dir,
            snapshot_every=snapshot_every)
    files.sort()
    if label_offset is None:
        # classic ImageNet TFRecords store labels 1..1000
        label_offset = 1
    host_files = files[shard_index::num_shards] if num_shards > 1 else files

    if cfg.backend == "grain":
        try:
            return _build_tfrecord_grain(
                cfg, host_files, split, local_batch, seed, label_offset,
                state_dir=state_dir, snapshot_every=snapshot_every)
        except (RuntimeError, OSError, ValueError, ImportError) as e:
            import logging
            logging.getLogger(__name__).warning(
                "grain backend unavailable (%s); falling back to auto", e)

    if _use_native(cfg, is_train):
        # Native path: index the shards once (JPEG byte ranges + labels,
        # native/tfrecord_index.cc), then decode straight out of the TFRecord
        # files with the ranged libjpeg loader — no TF in the hot loop.
        try:
            return _build_tfrecord_native(cfg, host_files, is_train,
                                          local_batch, seed, label_offset)
        except (RuntimeError, OSError, ValueError) as e:
            # observable fallback — see the imagefolder branch's rationale
            import logging
            logging.getLogger(__name__).warning(
                "native tfrecord loader unavailable (%s); using tf.data", e)

    def parse(serialized):
        feats = tf.io.parse_single_example(serialized, {
            "image/encoded": tf.io.FixedLenFeature([], tf.string),
            "image/class/label": tf.io.FixedLenFeature([], tf.int64),
        })
        label = tf.cast(feats["image/class/label"], tf.int32) - label_offset
        return feats["image/encoded"], label

    ds = tf.data.Dataset.from_tensor_slices(files)
    if num_shards > 1:
        ds = ds.shard(num_shards, shard_index)
    if is_train:
        ds = ds.shuffle(len(files), seed=seed)
    # deterministic=True even for train: the stream must be a pure function of
    # the seed for bit-identical deterministic resume (and symbolic iterator
    # checkpoints require a deterministic pipeline). The file-level shuffle
    # above still decorrelates the read order.
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=min(16, max(1, len(files))),
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=True)
    ds = ds.map(parse, num_parallel_calls=tf.data.AUTOTUNE)
    return _finalize(tf, ds, cfg, is_train, local_batch, seed,
                     state_dir=state_dir, snapshot_every=snapshot_every)


def _use_native(cfg: DataConfig, is_train: bool) -> bool:
    """Backend selection for the native loader ("grain" is tried before this
    and falls back into the auto rules)."""
    if cfg.backend == "native":
        return True
    if cfg.backend == "tfdata":
        return False
    return cfg.native_jpeg and (is_train or cfg.native_jpeg_eval)


def _tfrecord_items(cfg: DataConfig, files: list[str], label_offset: int):
    """(path_idx, offsets, lengths, labels) for TFRecord shards via the
    native indexer, with labels shifted into the 0-based space."""
    import numpy as np

    from distributed_vgg_f_tpu.data.native_tfrecord import index_tfrecords

    cache_dir = os.path.join(
        os.path.expanduser("~"), ".cache", "distributed_vgg_f_tpu")
    path_idx, offsets, lengths, labels64 = index_tfrecords(
        files, cache_dir=cache_dir)
    if len(labels64) == 0:
        raise ValueError("no records with image/encoded found")
    labels = (labels64 - label_offset).astype(np.int32)
    if (labels < 0).any():
        bad = int((labels < 0).sum())
        raise DataLayoutError(
            f"{bad} records have label < label_offset ({label_offset}) — "
            "records missing image/class/label, or wrong label_offset")
    return path_idx, offsets, lengths, labels


def _build_tfrecord_grain(cfg: DataConfig, files: list[str], split: str,
                          local_batch: int, seed: int, label_offset: int, *,
                          state_dir: str = "",
                          snapshot_every: int = 0) -> Iterator:
    from distributed_vgg_f_tpu.data.grain_imagenet import build_grain_imagenet

    _warn_wire_u8_unshipped(cfg, split == "train", "grain")
    path_idx, offsets, lengths, labels = _tfrecord_items(cfg, files,
                                                         label_offset)
    # files are already sharded per host (file-striding, like every other
    # path) — grain's own sharding stays disabled
    return build_grain_imagenet(
        cfg, split, local_batch, seed=seed, num_shards=1, shard_index=0,
        files=files, path_idx=path_idx, offsets=offsets, lengths=lengths,
        labels=labels, state_dir=state_dir, snapshot_every=snapshot_every)


def _build_tfrecord_native(cfg: DataConfig, files: list[str], is_train: bool,
                           local_batch: int, seed: int,
                           label_offset: int) -> Iterator:
    """TFRecord layout on the native loader: tfrecord_index.cc byte ranges →
    jpeg_loader.cc ranged decode. Train is the infinite deterministic stream
    (O(1) seek resume); eval is the exact finite center-crop pass."""
    import numpy as np

    from distributed_vgg_f_tpu.data.native_jpeg import (
        NativeJpegEvalIterator, NativeJpegTrainIterator)

    path_idx, offsets, lengths, labels = _tfrecord_items(cfg, files,
                                                         label_offset)
    u8 = _wire_u8_active(cfg, is_train)
    common = dict(
        batch=local_batch, image_size=cfg.image_size,
        mean=np.asarray(cfg.mean_rgb, np.float32),
        std=np.asarray(cfg.stddev_rgb, np.float32),
        image_dtype="uint8" if u8 else cfg.image_dtype,
        num_threads=cfg.native_threads or None,
        ranges=(path_idx, offsets, lengths))
    if is_train:
        # u8 wire: the host never packs — normalize/cast/space-to-depth
        # ride the device-finish prologue (data/device_ingest.py).
        # hflip=False (ABI v9) when the fused on-device augmentation owns
        # the flip (r13): the native decoder then never flips, same crops.
        it = NativeJpegTrainIterator(
            files, labels, seed=seed,
            space_to_depth=cfg.host_space_to_depth and not u8,
            hflip=not cfg.augment.owns_hflip, **common)
        # decoded-crop snapshot cache (r9): warm epochs skip libjpeg
        from distributed_vgg_f_tpu.data.snapshot_cache import (
            wrap_train_iterator)
        return wrap_train_iterator(it, cfg, seed=seed, files=files,
                                   labels=labels,
                                   ranges=(path_idx, offsets, lengths))
    return NativeJpegEvalIterator(files, labels, **common)


def _class_index(cfg: DataConfig) -> list[str] | None:
    """Sorted wnid list from the train split's class directories — the label
    space every layout maps into (label = sorted-wnid index)."""
    d = os.path.join(cfg.data_dir, "train")
    if os.path.isdir(d):
        classes = sorted(x for x in os.listdir(d)
                         if os.path.isdir(os.path.join(d, x)))
        if classes:
            return classes
    return None


_LABEL_MAP_NAMES = ("val_labels.txt", "validation_labels.txt",
                    "ILSVRC2012_validation_ground_truth.txt")


def _flat_val_listing(cfg: DataConfig, split_dir: str):
    """(files, labels) for the common real-ImageNet FLAT validation layout:
    `val/ILSVRC2012_val_*.JPEG` directly in the split dir plus a label mapping
    file. Accepted mapping formats (auto-detected per line):

    - two columns ``<filename> <wnid>``: wnid resolved to the sorted-wnid index
      of the train split's class directories (or of the wnids in the file when
      no train split is present);
    - two columns ``<filename> <int>``: the integer IS the class index in this
      framework's sorted-wnid label space (0-based);
    - one column ``<int>`` per line (ILSVRC2012 ground-truth style): line i
      labels the i-th file in sorted filename order. NOTE: the devkit's
      1-based ints are in the devkit's own class order, NOT sorted-wnid order —
      only use this format if your ints are already 0-based sorted-wnid
      indices; prefer the unambiguous ``filename wnid`` form.
    """
    # the label mapping may itself live inside the split dir — never count it
    # (or any .txt sidecar) as a validation image
    skip = set(_LABEL_MAP_NAMES)
    if cfg.val_labels_file:
        skip.add(os.path.basename(cfg.val_labels_file))
    entries = sorted(f for f in os.listdir(split_dir)
                     if os.path.isfile(os.path.join(split_dir, f))
                     and not f.startswith(".")
                     and not f.endswith(".txt") and f not in skip)
    if not entries:
        raise FileNotFoundError(f"no validation images under {split_dir!r}")
    candidates = ([cfg.val_labels_file] if cfg.val_labels_file else [
        os.path.join(d, n)
        for d in (split_dir, cfg.data_dir)
        for n in _LABEL_MAP_NAMES])
    map_path = next((p for p in candidates if p and os.path.isfile(p)), None)
    if map_path is None:
        raise FileNotFoundError(
            f"flat validation layout at {split_dir!r} needs a label mapping "
            "file (val_labels.txt with '<filename> <wnid>' lines, or set "
            "data.val_labels_file); none found")
    with open(map_path) as f:
        lines = [ln.split() for ln in f.read().splitlines() if ln.strip()]
    if all(len(ln) == 1 for ln in lines):
        # ordered ground-truth ints, one per sorted filename
        if len(lines) != len(entries):
            raise ValueError(
                f"{map_path!r} has {len(lines)} labels for {len(entries)} "
                f"validation files")
        by_name = {name: ln[0] for name, ln in zip(entries, lines)}
    else:
        by_name = {ln[0]: ln[1] for ln in lines}
    missing = [e for e in entries if e not in by_name]
    if missing:
        raise ValueError(
            f"{map_path!r} is missing labels for {len(missing)} files "
            f"(first: {missing[0]!r})")
    values = [by_name[e] for e in entries]
    if all(v.lstrip("-").isdigit() for v in values):
        labels = [int(v) for v in values]
    else:
        classes = _class_index(cfg) or sorted(set(values))
        index = {wnid: i for i, wnid in enumerate(classes)}
        unknown = next((v for v in values if v not in index), None)
        if unknown is not None:
            raise ValueError(
                f"wnid {unknown!r} from {map_path!r} not among the "
                f"{len(index)} train class directories")
        labels = [index[v] for v in values]
    return [os.path.join(split_dir, e) for e in entries], labels


def _imagefolder_listing(cfg: DataConfig, split: str, *, seed: int,
                         num_shards: int, shard_index: int):
    """(files, labels) numpy arrays for the imagefolder layout, after the
    deterministic global shuffle and strided per-host split. The SINGLE
    listing implementation — `_build_imagenet_imagefolder` and the
    disaggregated-ingest worker (`native_train_items`) both call it, so
    the decode-worker fleet can never drift from the trainer's item set."""
    import numpy as np

    is_train = split == "train"
    split_dir = None
    for name in (("train",) if is_train else ("validation", "val")):
        d = os.path.join(cfg.data_dir, name)
        if os.path.isdir(d):
            split_dir = d
            break
    if split_dir is None:
        raise FileNotFoundError(
            f"no ImageNet data under {cfg.data_dir!r}: neither TFRecords "
            "(train-*-of-*) nor a train/validation/val directory found")
    classes = sorted(d for d in os.listdir(split_dir)
                     if os.path.isdir(os.path.join(split_dir, d)))
    if classes:
        files, labels = [], []
        for idx, cls in enumerate(classes):
            for fname in sorted(os.listdir(os.path.join(split_dir, cls))):
                files.append(os.path.join(split_dir, cls, fname))
                labels.append(idx)
    elif not is_train:
        # Flat real-ImageNet validation layout: val/*.JPEG + label mapping.
        files, labels = _flat_val_listing(cfg, split_dir)
    else:
        raise FileNotFoundError(f"no class directories under {split_dir!r}")
    # deterministic global shuffle, then strided per-host split so every host
    # sees a class-balanced 1/num_shards slice; slice the index array BEFORE
    # materializing paths so each host only holds its own shard (the global
    # padded-unicode path array would be ~0.5GB at ImageNet scale). Example
    # order within the shard is then _finalize's shuffle_buffer.
    order = np.random.default_rng(seed).permutation(len(files))
    if num_shards > 1:
        order = order[shard_index::num_shards]
    return (np.asarray([files[i] for i in order]),
            np.asarray(labels, np.int32)[order])


def native_train_items(cfg: DataConfig, *, seed: int = 0,
                       num_shards: int = 1, shard_index: int = 0):
    """(files, labels, ranges | None): the exact TRAIN item set the native
    builders construct their iterator over — TFRecord byte ranges when the
    `train-*` shards exist (classic 1-based labels, the build_imagenet
    default), the imagefolder listing otherwise. This is what makes the
    disaggregated-ingest worker's position-keyed reconstruction
    (data/ingest_service.py) byte-identical to the trainer's local stream:
    both sides index the SAME items in the SAME order."""
    pattern = os.path.join(cfg.data_dir, "train-*")
    if "://" in (cfg.data_dir or ""):
        import tensorflow as tf  # remote filesystems (gs://, ...) only
        files = tf.io.gfile.glob(pattern)
    else:
        # local paths glob without TF — decode workers start in ~a second
        import glob as _glob
        files = _glob.glob(pattern)
    if files:
        files.sort()
        host_files = files[shard_index::num_shards] if num_shards > 1 \
            else files
        path_idx, offsets, lengths, labels = _tfrecord_items(
            cfg, host_files, 1)
        return (host_files, [int(l) for l in labels],
                (path_idx, offsets, lengths))
    files, labels = _imagefolder_listing(
        cfg, "train", seed=seed, num_shards=num_shards,
        shard_index=shard_index)
    return [str(f) for f in files], [int(l) for l in labels], None


def _build_imagenet_imagefolder(tf, cfg: DataConfig, split: str,
                                local_batch: int, *, seed: int,
                                num_shards: int, shard_index: int,
                                state_dir: str = "",
                                snapshot_every: int = 0) -> Iterator:
    import numpy as np

    is_train = split == "train"
    files, labels = _imagefolder_listing(
        cfg, split, seed=seed, num_shards=num_shards,
        shard_index=shard_index)

    if cfg.backend == "grain":
        try:
            from distributed_vgg_f_tpu.data.grain_imagenet import (
                build_grain_imagenet)
            from distributed_vgg_f_tpu.data.native_jpeg import (
                _whole_file_ranges)
            _warn_wire_u8_unshipped(cfg, is_train, "grain")
            path_idx, offsets, lengths = _whole_file_ranges(len(files))
            return build_grain_imagenet(
                cfg, split, local_batch, seed=seed, num_shards=1,
                shard_index=0, files=[str(f) for f in files],
                path_idx=path_idx, offsets=offsets, lengths=lengths,
                labels=labels, state_dir=state_dir,
                snapshot_every=snapshot_every)
        except (RuntimeError, OSError, ValueError, ImportError) as e:
            import logging
            logging.getLogger(__name__).warning(
                "grain backend unavailable (%s); falling back to auto", e)

    if _use_native(cfg, is_train):
        # Native libjpeg path (native/jpeg_loader.cc): DCT-scaled partial
        # decode in C++ worker threads — measured ~1.3–1.6x tf.data per host
        # core (benchmarks/host_pipeline_bench.py; frozen per-core baseline
        # in benchmarks/baseline.json). Train is deterministic per seed with
        # O(1) exact seek
        # (restore_state), so it also satisfies the deterministic-resume
        # protocol without snapshot files; eval is the exact finite
        # center-crop pass. Falls back to tf.data below if the build fails.
        try:
            from distributed_vgg_f_tpu.data.native_jpeg import (
                NativeJpegEvalIterator, NativeJpegTrainIterator)
            u8 = _wire_u8_active(cfg, is_train)
            common = dict(
                batch=local_batch, image_size=cfg.image_size,
                mean=np.asarray(cfg.mean_rgb, np.float32),
                std=np.asarray(cfg.stddev_rgb, np.float32),
                image_dtype="uint8" if u8 else cfg.image_dtype,
                num_threads=cfg.native_threads or None)
            fl = [str(f) for f in files]
            lb = [int(l) for l in labels]
            if is_train:
                # u8 wire: space-to-depth moves to the device finish;
                # hflip=False when device-side augmentation owns flips (r13)
                it = NativeJpegTrainIterator(
                    fl, lb, seed=seed,
                    space_to_depth=cfg.host_space_to_depth and not u8,
                    hflip=not cfg.augment.owns_hflip, **common)
                # decoded-crop snapshot cache (r9): warm epochs skip libjpeg
                from distributed_vgg_f_tpu.data.snapshot_cache import (
                    wrap_train_iterator)
                return wrap_train_iterator(it, cfg, seed=seed, files=fl,
                                           labels=lb)
            return NativeJpegEvalIterator(fl, lb, **common)
        except (RuntimeError, OSError, ValueError) as e:
            # the switch must be observable: the tf.data stream draws
            # different (same-distribution) augmentations and resumes via
            # snapshots instead of seek — a silent swap would be confusing,
            # and in multi-host runs a single host falling back deserves a
            # visible signal.
            import logging
            logging.getLogger(__name__).warning(
                "native jpeg loader unavailable (%s); using tf.data", e)
    ds = tf.data.Dataset.from_tensor_slices((files, labels))
    ds = ds.map(lambda path, label: (tf.io.read_file(path), label),
                num_parallel_calls=tf.data.AUTOTUNE)
    return _finalize(tf, ds, cfg, is_train, local_batch, seed,
                     state_dir=state_dir, snapshot_every=snapshot_every)
