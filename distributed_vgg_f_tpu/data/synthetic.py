"""Deterministic synthetic image batches — used by tests and the throughput
benchmark (removes host-input bottlenecks so the benchmark isolates device step
time, SURVEY.md §4 throughput harness)."""

from __future__ import annotations

import numpy as np


class SyntheticDataset:
    """Iterator of {'image', 'label'} numpy batches.

    `fixed=True` yields the same batch forever (memorization target for
    loss-decrease tests); otherwise batches cycle deterministically from `seed`.
    """

    def __init__(self, batch_size: int, image_size: int = 224,
                 num_classes: int = 1000, seed: int = 0,
                 num_examples: int = 100_000, channels: int = 3,
                 fixed: bool = False, image_dtype: str = "float32"):
        self.batch_size = batch_size
        self.image_size = image_size
        self.num_classes = num_classes
        self.num_examples = num_examples
        self.channels = channels
        self.fixed = fixed
        # bfloat16 halves H2D transfer volume and skips the on-device f32→bf16
        # convert (the model casts to compute_dtype anyway).
        from distributed_vgg_f_tpu.data.dtypes import resolve_image_dtype
        self.image_dtype = resolve_image_dtype(image_dtype)
        self._rng = np.random.default_rng(seed)
        self._fixed_batch = self._draw() if fixed else None

    def _draw(self):
        images = self._rng.standard_normal(
            (self.batch_size, self.image_size, self.image_size, self.channels),
            dtype=np.float32)
        if self.image_dtype != np.dtype(np.float32):
            images = images.astype(self.image_dtype)
        labels = self._rng.integers(
            0, self.num_classes, size=(self.batch_size,), dtype=np.int32)
        return {"image": images, "label": labels}

    def __iter__(self):
        return self

    def __next__(self):
        if self.fixed:
            return self._fixed_batch
        return self._draw()
