"""Deterministic synthetic image batches — used by tests and the throughput
benchmark (removes host-input bottlenecks so the benchmark isolates device step
time, SURVEY.md §4 throughput harness)."""

from __future__ import annotations

import numpy as np


class SyntheticDataset:
    """Iterator of {'image', 'label'} numpy batches.

    `fixed=True` yields the same batch forever (memorization target for
    loss-decrease tests); otherwise batches cycle deterministically from `seed`.

    Position-exact seek (`restore_state`, the shared iterator-state
    contract of data/iterator_state.py): the stream is a pure function of
    (seed, draw count), so seeking re-derives the RNG and discards draws —
    draws are cheap by this module's contract, which makes synthetic a
    first-class source for the r18 cursor restore and the r19 elastic
    data handoff.
    """

    supports_state = True

    def __init__(self, batch_size: int, image_size: int = 224,
                 num_classes: int = 1000, seed: int = 0,
                 num_examples: int = 100_000, channels: int = 3,
                 fixed: bool = False, image_dtype: str = "float32",
                 space_to_depth: bool = False):
        self.batch_size = batch_size
        self.image_size = image_size
        self.num_classes = num_classes
        self.num_examples = num_examples
        self.channels = channels
        self.fixed = fixed
        # Emit (S/4, S/4, 16*C) space-to-depth blocks instead of (S, S, C) —
        # the host side of the VGG-F stem's packed-input contract
        # (models/vggf.py Conv1SpaceToDepth; data.space_to_depth).
        self.space_to_depth = space_to_depth
        if space_to_depth and image_size % 4 != 0:
            raise ValueError("space_to_depth needs image_size % 4 == 0")
        # bfloat16 halves H2D transfer volume and skips the on-device f32→bf16
        # convert (the model casts to compute_dtype anyway).
        from distributed_vgg_f_tpu.data.dtypes import resolve_image_dtype
        self.image_dtype = resolve_image_dtype(image_dtype)
        self._seed = seed
        self._rng = np.random.default_rng(seed)
        self._fixed_batch = self._draw() if fixed else None

    def restore_state(self, step: int) -> bool:
        """Seek so the NEXT draw is the `step`-th (0-based) of the stream."""
        step = int(step)
        if step < 0:
            return False
        if not self.fixed:  # fixed: every position yields the same batch
            self._rng = np.random.default_rng(self._seed)
            for _ in range(step):
                self._draw()
        return True

    def _draw(self):
        images = self._rng.standard_normal(
            (self.batch_size, self.image_size, self.image_size, self.channels),
            dtype=np.float32)
        if self.space_to_depth:
            b, s, c = self.batch_size, self.image_size, self.channels
            images = images.reshape(b, s // 4, 4, s // 4, 4, c) \
                .transpose(0, 1, 3, 2, 4, 5).reshape(b, s // 4, s // 4, 16 * c)
        if self.image_dtype != np.dtype(np.float32):
            images = images.astype(self.image_dtype)
        labels = self._rng.integers(
            0, self.num_classes, size=(self.batch_size,), dtype=np.int32)
        return {"image": images, "label": labels}

    def __iter__(self):
        return self

    def __next__(self):
        if self.fixed:
            return self._fixed_batch
        return self._draw()
