"""Input pipelines (SURVEY.md §2.1 #5): host-side data feeding the device mesh.

`build_dataset(cfg.data, ...)` returns an iterator of process-local numpy batches
{'image': (B_local, H, W, 3) float32, 'label': (B_local,) int32}; the trainer
shards them over the mesh with `parallel.mesh.shard_host_batch`.
"""

from distributed_vgg_f_tpu.data.synthetic import SyntheticDataset  # noqa: F401


def build_dataset(data_cfg, split: str = "train", *, seed: int = 0,
                  num_shards: int = 1, shard_index: int = 0,
                  state_dir: str = "", snapshot_every: int = 0,
                  num_classes: int | None = None):
    """Dataset factory. Per-host sharding: each process gets 1/num_shards of the
    global batch (the reference's per-worker shard, SURVEY.md §1).

    `state_dir`/`snapshot_every` enable deterministic-resume iterator
    snapshots for pipelines that support them (imagenet tf.data train).

    `num_classes` is the MODEL's head width; real datasets have intrinsic
    label spaces, but synthetic labels must stay inside the head — a
    1000-class synthetic label against a 10-class head is an out-of-range
    CE gather (r3: surfaced as loss=nan with finite grads when overriding
    model.num_classes under the synthetic pipeline)."""
    if data_cfg.global_batch_size % num_shards != 0:
        raise ValueError(
            f"global batch {data_cfg.global_batch_size} not divisible by "
            f"{num_shards} host shards")
    local_batch = data_cfg.global_batch_size // num_shards
    # Disaggregated ingest (r16, data/service_client.py): the TRAIN stream
    # comes from the decode-worker fleet instead of local decode. The
    # kill-switch contract mirrors r6-r14: enabled=false (the default)
    # takes none of this branch — local ingest byte-identical, pinned in
    # tests/test_ingest_service.py. Eval always decodes locally (the
    # exact finite pass has no service protocol and no throughput problem).
    svc = getattr(data_cfg, "service", None)
    if svc is not None and svc.enabled and split == "train":
        from distributed_vgg_f_tpu.data.service_client import (
            build_service_client)
        return build_service_client(
            data_cfg, local_batch, seed=seed, num_shards=num_shards,
            shard_index=shard_index, num_classes=num_classes,
            state_dir=state_dir, snapshot_every=snapshot_every)
    if data_cfg.name == "synthetic":
        return SyntheticDataset(
            batch_size=local_batch, image_size=data_cfg.image_size,
            num_classes=num_classes or _num_classes(data_cfg),
            seed=seed + shard_index,
            num_examples=data_cfg.num_train_examples,
            image_dtype=data_cfg.image_dtype,
            # host_space_to_depth: with device-side augmentation enabled
            # the host ships unpacked and the train step packs post-augment
            space_to_depth=data_cfg.host_space_to_depth
            and split == "train")
    if data_cfg.name == "teacher":
        from distributed_vgg_f_tpu.data.teacher import build_teacher
        return build_teacher(data_cfg, split, local_batch, seed=seed,
                             num_shards=num_shards, shard_index=shard_index)
    if data_cfg.name == "cifar10":
        from distributed_vgg_f_tpu.data.cifar10 import build_cifar10
        return build_cifar10(data_cfg, split, local_batch, seed=seed,
                             num_shards=num_shards, shard_index=shard_index)
    if data_cfg.name == "imagenet":
        from distributed_vgg_f_tpu.data.imagenet import build_imagenet
        return build_imagenet(data_cfg, split, local_batch, seed=seed,
                              num_shards=num_shards, shard_index=shard_index,
                              state_dir=state_dir,
                              snapshot_every=snapshot_every)
    raise KeyError(f"unknown dataset {data_cfg.name!r}")


def _num_classes(data_cfg) -> int:
    return {"cifar10": 10, "teacher": 10}.get(data_cfg.name, 1000)
