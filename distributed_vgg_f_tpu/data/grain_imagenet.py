"""Grain (PyGrain) ImageNet pipeline — the JAX-ecosystem host input backend
(`data.backend = "grain"`).

Why a third backend (SURVEY.md §7 named "possibly Grain instead of tf.data"):
PyGrain is the JAX-native data loader — deterministic index sampling, true
MULTIPROCESS decode workers (`data.grain_workers`; tf.data AUTOTUNE threads
and the native loader's C++ threads both live in one process), and
checkpointable iterators. Decode stays native: each record runs through
`dvgg_jpeg_decode_single` (native/jpeg_loader.cc — the same DCT-scaled
partial-decode math as the batch loader) seeded from (seed, stream index),
so the stream is a pure function of position, any worker count included.

Layouts: both — raw-JPEG items are whole files, TFRecord items are the byte
ranges the native indexer emits (data/native_tfrecord.py); reads go through
`os.pread` on per-process lazily-opened fds (the source must pickle across
grain's worker-process spawn).

Resume: `GrainTrainIterator` snapshots the PyGrain iterator state (a small
JSON blob) to rotating files at the checkpoint cadence — the same protocol
as the tf.data `CheckpointableTfIterator` — so ImageNet restarts restore the
exact mid-stream position in O(1).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Iterator, Sequence

import numpy as np

from distributed_vgg_f_tpu.data.iter_snapshots import SnapshotResumableIterator

log = logging.getLogger(__name__)


class JpegRangeSource:
    """Grain RandomAccessDataSource over JPEG byte ranges.

    Items are (path_idx, offset, length, label); offset < 0 means "the whole
    file" (raw-JPEG layout). Returns {'jpeg': bytes, 'label': int32}.
    Picklable: holds only arrays; fds open lazily per process/thread.
    """

    def __init__(self, files: Sequence[str], path_idx, offsets, lengths,
                 labels):
        self._files = list(files)
        self._path_idx = np.ascontiguousarray(path_idx, np.int32)
        self._offsets = np.ascontiguousarray(offsets, np.int64)
        self._lengths = np.ascontiguousarray(lengths, np.int64)
        self._labels = np.ascontiguousarray(labels, np.int32)
        self._digest = None
        self._local = threading.local()

    def __len__(self) -> int:
        return len(self._labels)

    def __repr__(self) -> str:
        # grain validates checkpoints against repr(data_source): make it a
        # pure function of the source CONTENT, not the object identity, so a
        # restart (new process, same dataset) accepts its own snapshots
        if self._digest is None:
            import hashlib
            h = hashlib.sha256()
            for f in self._files:
                h.update(f.encode() + b"|")
            for arr in (self._path_idx, self._offsets, self._lengths,
                        self._labels):
                h.update(arr.tobytes())
            self._digest = h.hexdigest()[:16]
        return (f"JpegRangeSource(n={len(self._labels)}, "
                f"digest={self._digest})")

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_local"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._local = threading.local()

    # per-thread fd cache bound: real ImageNet is 1024 shards and grain uses
    # several read threads — unbounded caching would exhaust the common
    # nofile=1024 soft limit mid-training
    _FD_CACHE_MAX = 64

    def _fd(self, path_i: int) -> int:
        if getattr(self._local, "fds", None) is None:
            self._local.fds = {}
        fd = self._local.fds.get(path_i)
        if fd is None:
            if len(self._local.fds) >= self._FD_CACHE_MAX:
                for old in self._local.fds.values():
                    try:
                        os.close(old)
                    except OSError:
                        pass
                self._local.fds.clear()
            fd = os.open(self._files[path_i], os.O_RDONLY)
            self._local.fds[path_i] = fd
        return fd

    def __getitem__(self, i: int):
        i = int(i)
        path_i = int(self._path_idx[i])
        off, length = int(self._offsets[i]), int(self._lengths[i])
        if off < 0:
            with open(self._files[path_i], "rb") as f:
                data = f.read()
        else:
            # pread may return short (signal interruption); loop so a truncated
            # file surfaces as an IO error rather than truncated JPEG bytes
            # that decode_one silently zero-fills as a "corrupt image"
            fd = self._fd(path_i)
            chunks, pos, remaining = [], off, length
            while remaining > 0:
                chunk = os.pread(fd, remaining, pos)
                if not chunk:
                    raise IOError(
                        f"short read: {self._files[path_i]} item {i} wanted "
                        f"{length}B at {off}, got {length - remaining}B "
                        f"(file truncated since indexing?)")
                chunks.append(chunk)
                pos += len(chunk)
                remaining -= len(chunk)
            data = chunks[0] if len(chunks) == 1 else b"".join(chunks)
        return {"jpeg": data, "label": self._labels[i]}


def _decode_single(lib, jpeg: bytes, out_size: int, mean, std, *, bf16: bool,
                   pack4: bool, eval_mode: bool, area, rng_seed: int,
                   hflip: bool = True):
    """One native decode into a fresh numpy array; zero-filled on failure."""
    import ctypes
    if pack4:
        shape = (out_size // 4, out_size // 4, 48)
    else:
        shape = (out_size, out_size, 3)
    raw = np.empty(shape, np.uint16 if bf16 else np.float32)
    rc = lib.dvgg_jpeg_decode_single(
        jpeg, len(jpeg), out_size,
        mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        int(bf16), int(pack4), int(eval_mode), int(hflip),
        float(area[0]), float(area[1]), rng_seed & 0xFFFFFFFFFFFFFFFF,
        raw.ctypes.data_as(ctypes.c_void_p))
    failed = rc != 0
    if failed:
        raw[:] = 0
    if bf16:
        import ml_dtypes
        raw = raw.view(np.dtype(ml_dtypes.bfloat16))
    return raw, failed


class NativeDecodeTransform:
    """grain RandomMapTransform: JPEG bytes → augmented normalized image.

    Grain derives each record's `np.random.Generator` deterministically from
    (sampler seed, stream index) — identical for any worker count — and the
    native decode consumes one uint64 from it, so the stream stays a pure
    function of (seed, position). Must be picklable (plain fields only); the
    native lib loads lazily in each worker process."""

    def __init__(self, image_size: int, mean, std, *,
                 image_dtype: str, space_to_depth: bool, train: bool,
                 hflip: bool = True):
        self.image_size = int(image_size)
        self.mean = np.ascontiguousarray(mean, np.float32)
        self.std = np.ascontiguousarray(std, np.float32)
        self.bf16 = image_dtype == "bfloat16"
        self.pack4 = bool(space_to_depth)
        self.train = bool(train)
        # Flip ownership (ABI v9): False when the fused on-device
        # augmentation stage owns flips — the host decode then never flips.
        self.hflip = bool(hflip)

    def random_map(self, element, rng: np.random.Generator):
        from distributed_vgg_f_tpu.data.native_jpeg import load_native_jpeg
        lib = load_native_jpeg()
        if lib is None:  # pragma: no cover — callers pre-check availability
            raise RuntimeError("native jpeg decoder unavailable")
        seed = int(rng.integers(0, 2**63, dtype=np.int64))
        image, failed = _decode_single(
            lib, element["jpeg"], self.image_size, self.mean, self.std,
            bf16=self.bf16, pack4=self.pack4, eval_mode=not self.train,
            area=(0.08, 1.0), rng_seed=seed, hflip=self.hflip)
        # the flag rides the batch back to the consuming process (the decode
        # may run in a grain worker, whose memory the trainer cannot see) and
        # feeds the decode_errors() counter the trainer's log watches
        return {"image": image, "label": np.int32(element["label"]),
                "failed": np.bool_(failed)}


# grain.RandomMapTransform is an ABC registered at import time; subclass
# lazily so this module imports even where grain is absent.
def _make_transform(cls_kwargs):
    import grain.python as gp

    class _T(NativeDecodeTransform, gp.RandomMapTransform):
        pass

    return _T(**cls_kwargs)


class GrainTrainIterator(SnapshotResumableIterator):
    """Infinite deterministic train iterator over a PyGrain DataLoader with
    O(1) mid-stream restore via iterator-state snapshot files (the shared
    data/iter_snapshots.py protocol: a snapshot tagged D means "the next
    draw is batch D"). Decode failures (zero-filled images, counted in the
    per-record transform and summed here from the batched 'failed' flags)
    surface through `decode_errors()` — the counter the trainer's periodic
    log watches."""

    def __init__(self, loader, *, snapshot_dir: str = "",
                 snapshot_every: int = 0, keep: int = 4):
        super().__init__(snapshot_dir=snapshot_dir,
                         snapshot_every=snapshot_every, keep=keep)
        self._it = iter(loader)
        self._decode_errors = 0

    def __next__(self):
        batch = dict(next(self._it))
        failed = batch.pop("failed", None)
        if failed is not None:
            self._decode_errors += int(np.asarray(failed).sum())
        self._after_draw()
        return batch

    def decode_errors(self) -> int:
        return self._decode_errors

    def close(self) -> None:
        """Release the PyGrain iterator (reaps worker processes / prefetch
        buffers via grain's finalizers) — benches measuring other pipelines
        afterwards must not share the host with abandoned workers."""
        self._it = None
        import gc
        gc.collect()

    def _path(self, draws: int) -> str:
        return os.path.join(self._dir, f"grain_{draws:012d}.state")

    def _write_snapshot(self, draws: int) -> None:
        state = self._it.get_state()
        tmp = self._path(draws) + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(state)
        os.replace(tmp, self._path(draws))

    def _snapshot_exists(self, draws: int) -> bool:
        return os.path.exists(self._path(draws))

    def _read_snapshot(self, draws: int) -> None:
        with open(self._path(draws), "rb") as f:
            self._it.set_state(f.read())

    def _remove_snapshot(self, draws: int) -> None:
        try:
            os.remove(self._path(draws))
        except OSError:
            pass

    def _list_stamps(self) -> list[int]:
        return [int(f[len("grain_"):-len(".state")])
                for f in os.listdir(self._dir)
                if f.startswith("grain_") and f.endswith(".state")]


def build_grain_imagenet(cfg, split: str, local_batch: int, *, seed: int,
                         num_shards: int, shard_index: int,
                         files: Sequence[str], path_idx, offsets, lengths,
                         labels, state_dir: str = "",
                         snapshot_every: int = 0) -> Iterator:
    """Assemble the grain pipeline over pre-listed items (both layouts).

    Train: infinite shuffled stream, `data.grain_workers` decode processes.
    Eval: one sequential finite pass wrapped in the exact-eval pad-and-mask
    protocol (each `iter()` builds a fresh single-pass loader)."""
    import grain.python as gp

    from distributed_vgg_f_tpu.data.native_jpeg import load_native_jpeg
    if load_native_jpeg() is None:
        raise RuntimeError("grain backend needs the native jpeg decoder")

    is_train = split == "train"
    source = JpegRangeSource(files, path_idx, offsets, lengths, labels)
    # Flip/pack ownership (r13): when the fused on-device augmentation
    # stage is enabled the host neither flips (device owns the flip) nor
    # packs space-to-depth (packing must happen AFTER the device-side
    # geometric augments) — config.DataConfig.host_space_to_depth is the
    # single source of the packing decision.
    aug = getattr(cfg, "augment", None)
    device_flips = bool(aug is not None and aug.owns_hflip)
    transform = _make_transform(dict(
        image_size=cfg.image_size, mean=cfg.mean_rgb, std=cfg.stddev_rgb,
        image_dtype=cfg.image_dtype,
        space_to_depth=cfg.host_space_to_depth and is_train, train=is_train,
        hflip=not (device_flips and is_train)))
    shard = gp.ShardOptions(shard_index=shard_index, shard_count=num_shards,
                            drop_remainder=is_train)
    workers = int(getattr(cfg, "grain_workers", 0))

    if is_train:
        loader = gp.DataLoader(
            data_source=source,
            sampler=gp.IndexSampler(len(source), shard_options=shard,
                                    shuffle=True, num_epochs=None, seed=seed),
            operations=[transform,
                        gp.Batch(local_batch, drop_remainder=True)],
            worker_count=workers)
        return GrainTrainIterator(loader, snapshot_dir=state_dir,
                                  snapshot_every=snapshot_every)

    from distributed_vgg_f_tpu.data.eval_pad import FiniteEvalIterable

    errors = {"n": 0}

    def epoch():
        loader = gp.DataLoader(
            data_source=source,
            sampler=gp.IndexSampler(len(source), shard_options=shard,
                                    shuffle=False, num_epochs=1, seed=seed),
            operations=[transform,
                        gp.Batch(local_batch, drop_remainder=False)],
            worker_count=workers)
        for batch in loader:
            batch = dict(batch)
            failed = batch.pop("failed", None)
            if failed is not None:
                errors["n"] += int(np.asarray(failed).sum())
            yield batch

    if cfg.image_dtype == "bfloat16":
        import ml_dtypes
        np_dtype = np.dtype(ml_dtypes.bfloat16)
    else:
        np_dtype = np.dtype(np.float32)
    fe = FiniteEvalIterable(epoch, local_batch,
                            (cfg.image_size, cfg.image_size, 3), np_dtype)
    # surface corrupt-image zero-fills to Trainer.evaluate's counter read
    fe.decode_errors = lambda: errors["n"]
    return fe
